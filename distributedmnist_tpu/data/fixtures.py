"""Materialized idx-format dataset fixtures.

This environment has no network egress, so the canonical MNIST /
Fashion-MNIST archives (≙ maybe_download, src/mnist_data.py:176-187)
cannot be fetched. To still exercise the REAL ingest pipeline —
idx(.gz) parse → [-0.5, 0.5] normalization → host sharding → training →
evaluator oracle — this module writes the deterministic learnable
synthetic dataset (datasets.make_synthetic) to disk in the exact idx
ubyte format the reference downloads, via ``write_idx_ubyte`` (the
inverse of the parser, so the bytes round-trip bit-exactly).

The fixture is clearly labeled on disk (PROVENANCE.md): it is NOT the
real MNIST pixels — it is a stand-in with the same file format, shapes,
dtype, value range and split sizes, generated from a fixed seed.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

import pickle

from .datasets import (_IDX_FILES, _open_maybe_gz, make_synthetic,
                       write_idx_ubyte)

# Per-dataset generation seeds: distinct data for mnist/fashion_mnist.
_FIXTURE_SEEDS = {"mnist": 12345, "fashion_mnist": 54321}


def _idx_dims(path: Path) -> tuple[int, ...]:
    """Read just the idx header (16 bytes max) — shape check without
    decompressing the payload."""
    import struct
    with _open_maybe_gz(path) as f:
        magic = struct.unpack(">HBB", f.read(4))
        if magic[0] != 0 or magic[1] != 0x08:
            return ()
        return struct.unpack(f">{magic[2]}I", f.read(4 * magic[2]))


def materialize_idx_fixture(data_dir: str | Path, dataset: str = "mnist",
                            num_train: int = 60000, num_test: int = 10000,
                            image_size: int = 28, noise: float = 0.08,
                            gzip_files: bool = True) -> Path:
    """Write a full 4-file idx dataset under ``data_dir`` (idempotent:
    returns immediately when all four files exist). Shapes/sizes match
    the real archives: train-images [60000,28,28], t10k [10000,28,28].
    """
    root = Path(data_dir)
    suffix = ".gz" if gzip_files else ""
    paths = {k: root / (names[0] + suffix) for k, names in _IDX_FILES.items()}
    want = {"train_images": (num_train, image_size, image_size),
            "train_labels": (num_train,),
            "test_images": (num_test, image_size, image_size),
            "test_labels": (num_test,)}
    if all(p.exists() for p in paths.values()):
        # idempotent only when the cached shapes match the request — a
        # quick-run cache must not silently serve a later full run
        if all(_idx_dims(paths[k]) == want[k] for k in paths):
            return root
    seed = _FIXTURE_SEEDS.get(dataset, 12345)
    ds = make_synthetic(num_train, num_test, image_size=image_size,
                        num_channels=1, seed=seed, noise=noise)

    def to_u8(images: np.ndarray) -> np.ndarray:
        # exact inverse of the loader's (u8 - 127.5)/255 normalization
        return np.clip(np.round((images[..., 0] + 0.5) * 255.0),
                       0, 255).astype(np.uint8)

    # the loader carves its own validation slice out of the train file
    # (load_idx_dataset), exactly as it would from the real archive
    write_idx_ubyte(paths["train_images"], to_u8(ds.train.images))
    write_idx_ubyte(paths["train_labels"], ds.train.labels.astype(np.uint8))
    write_idx_ubyte(paths["test_images"], to_u8(ds.test.images))
    write_idx_ubyte(paths["test_labels"], ds.test.labels.astype(np.uint8))
    (root / "PROVENANCE.md").write_text(
        f"# Fixture dataset ({dataset})\n\n"
        "Deterministic synthetic data materialized in idx ubyte format "
        "(distributedmnist_tpu.data.fixtures) because this environment "
        f"has no network egress. seed={seed}, "
        f"{num_train} train / {num_test} test. NOT the real archives — "
        "same format, shapes, dtype and split sizes.\n")
    return root


def materialize_cifar10_fixture(data_dir: str | Path,
                                num_train: int = 50000,
                                num_test: int = 10000) -> Path:
    """Write a full CIFAR-10 python-pickle batch set under ``data_dir``
    (idempotent) so ``load_cifar10``'s REAL parse path — pickle decode,
    [N, 3072] u8 → NHWC transpose, pixel normalization — runs end to
    end (≙ the ingest fidelity of src/mnist_data.py:132-155 applied to
    BASELINE config #5, which otherwise only ever hits the logged
    synthetic fallback).

    Layout matches the real archive: ``cifar-10-batches-py/`` holding
    five ``data_batch_N`` of 10k rows each plus ``test_batch``, every
    pickle a dict with b"data" [N, 3072] uint8 (CHW channel-major rows)
    and b"labels".
    """
    root = Path(data_dir)
    batch_dir = root / "cifar-10-batches-py"
    n_batches = 5
    per = num_train // n_batches
    files = [batch_dir / f"data_batch_{i + 1}" for i in range(n_batches)]
    files.append(batch_dir / "test_batch")
    if all(p.exists() for p in files):
        return root
    batch_dir.mkdir(parents=True, exist_ok=True)
    seed = _FIXTURE_SEEDS.get("cifar10", 67890)
    ds = make_synthetic(num_train, num_test, image_size=32, num_channels=3,
                        seed=seed)

    def to_rows(images: np.ndarray) -> np.ndarray:
        # inverse of load_cifar10's (u8 - 127.5)/255, NHWC → [N, 3072]
        # channel-major rows exactly as the archive stores them
        u8 = np.clip(np.round(images * 255.0 + 127.5), 0, 255).astype(np.uint8)
        return np.ascontiguousarray(
            u8.transpose(0, 3, 1, 2).reshape(len(u8), -1))

    # convert per 10k batch, not the whole train set at once — caps the
    # u8/transpose copies at one batch's worth on top of the float base
    for i, path in enumerate(files[:n_batches]):
        sl = slice(i * per, (i + 1) * per)
        with open(path, "wb") as f:
            pickle.dump({b"data": to_rows(ds.train.images[sl]),
                         b"labels": ds.train.labels[sl].tolist(),
                         b"batch_label": f"fixture batch {i + 1}".encode()}, f)
    with open(files[-1], "wb") as f:
        pickle.dump({b"data": to_rows(ds.test.images),
                     b"labels": ds.test.labels.tolist(),
                     b"batch_label": b"fixture test batch"}, f)
    (root / "PROVENANCE.md").write_text(
        "# Fixture dataset (cifar10)\n\n"
        "Deterministic synthetic data materialized in the CIFAR-10 "
        "python pickle batch format (distributedmnist_tpu.data."
        f"fixtures) because this environment has no network egress. "
        f"seed={seed}, {num_train} train / {num_test} test. NOT the "
        "real archive — same layout, shapes, dtype and split sizes.\n")
    return root
