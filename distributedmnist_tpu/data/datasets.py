"""Dataset loading — MNIST/Fashion-MNIST idx files, CIFAR-10, and a
deterministic synthetic fallback.

Capability parity with src/mnist_data.py, redesigned:

* idx.gz parsing and [-0.5, 0.5] normalization match the reference
  (src/mnist_data.py:132-155; normalization at :142).
* The reference accepts ``worker_id``/``n_workers`` but ignores them —
  every worker shuffles the full 60k with a time seed
  (src/mnist_data.py:55,80-84,156-163,212-213). Here sharding is real:
  ``shard_mode="sharded"`` gives each host a deterministic slice;
  ``shard_mode="independent"`` reproduces the reference's
  full-copy-per-worker behavior (with a *seeded* shuffle, not a time
  seed).
* The reference aliases validation := the 10k test set
  (src/mnist_data.py:200-201) — a documented quirk we do not copy:
  validation is carved from the train split.
* The latent fake-data fixture (src/mnist_data.py:46,60-62,164-172) is
  promoted to a first-class deterministic *learnable* synthetic dataset
  — also the default in egress-free environments where the idx files
  cannot be downloaded.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import pickle
import struct
from pathlib import Path

import numpy as np

from ..core.config import DataConfig

PIXEL_DEPTH = 255  # ≙ src/mnist.py:31


@dataclasses.dataclass(frozen=True)
class ArrayDataset:
    """An in-memory split. For image tasks: images [N,H,W,C] float32 in
    [-0.5, 0.5], labels [N] int32. For LM tasks: images [N,S] int32
    token sequences, labels [N,S] (the same tokens — the loss shifts
    internally for next-token prediction)."""

    images: np.ndarray
    labels: np.ndarray

    def __post_init__(self):
        assert self.images.ndim in (2, 4), self.images.shape
        assert self.labels.ndim in (1, 2), self.labels.shape
        assert len(self.images) == len(self.labels)

    @property
    def num_examples(self) -> int:
        return len(self.labels)

    def take(self, idx: np.ndarray) -> "ArrayDataset":
        return ArrayDataset(self.images[idx], self.labels[idx])

    def shard(self, shard_id: int, num_shards: int) -> "ArrayDataset":
        """Deterministic contiguous-strided shard (fixes the reference's
        no-op sharding, src/mnist_data.py:156-163)."""
        sel = np.arange(shard_id, self.num_examples, num_shards)
        return self.take(sel)


@dataclasses.dataclass(frozen=True)
class Datasets:
    """≙ the reference's ``Datasets(train, validation, test)`` result
    (src/mnist_data.py:212-213)."""

    train: ArrayDataset
    validation: ArrayDataset
    test: ArrayDataset


# --------------------------------------------------------------------------
# idx format (MNIST / Fashion-MNIST)
# --------------------------------------------------------------------------

def _open_maybe_gz(path: Path):
    if path.suffix == ".gz":
        return gzip.open(path, "rb")
    return open(path, "rb")


def _read_idx_ubyte(path: Path, expect_ndim: int) -> np.ndarray:
    """Raw idx(.gz) ubyte payload.

    The numpy path is the DEFAULT decode. Repeated bench_native_loader
    idx_decode runs on the 60k-image idx3.gz put the two readers within
    run-to-run noise of each other (native 130-157 MB/s vs numpy
    136-151 — both zlib-inflate-bound); numpy avoids the extra ctypes
    boundary copy (native_loader.read_idx's .copy()) and any dependence
    on the C++ build, so it wins the default. The native reader stays
    available for the C-ABI round-trip tests and any caller that wants
    decode off the Python heap."""
    with _open_maybe_gz(path) as f:
        magic = struct.unpack(">HBB", f.read(4))
        if magic[0] != 0 or magic[1] != 0x08:
            raise ValueError(f"{path}: bad idx magic {magic}")
        dims = struct.unpack(f">{magic[2]}I", f.read(4 * magic[2]))
        buf = f.read(int(np.prod(dims)))
    arr = np.frombuffer(buf, dtype=np.uint8).reshape(dims)
    if arr.ndim != expect_ndim:
        raise ValueError(f"{path}: expected {expect_ndim}-d idx, got {arr.ndim}-d")
    return arr


def read_idx_images(path: Path) -> np.ndarray:
    """Parse an idx3-ubyte image file → float32 [N,H,W,1] in [-0.5,0.5]
    (≙ extract_data, src/mnist_data.py:132-146)."""
    data = _read_idx_ubyte(path, 3).astype(np.float32)
    data = (data - PIXEL_DEPTH / 2.0) / PIXEL_DEPTH  # :142 parity
    return data[..., np.newaxis]


def read_idx_labels(path: Path) -> np.ndarray:
    """Parse an idx1-ubyte label file (≙ extract_labels,
    src/mnist_data.py:147-155)."""
    return _read_idx_ubyte(path, 1).astype(np.int32)


def write_idx_ubyte(path: Path, arr: np.ndarray) -> Path:
    """Write a uint8 array as an idx(.gz) file — the exact inverse of
    ``_read_idx_ubyte``. Used by tests (round-trip fixtures) and as a
    dataset snapshot tool; gzip when the suffix is ``.gz``."""
    arr = np.ascontiguousarray(arr, dtype=np.uint8)
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    header = struct.pack(">HBB", 0, 0x08, arr.ndim)
    header += struct.pack(f">{arr.ndim}I", *arr.shape)
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "wb") as f:
        f.write(header)
        f.write(arr.tobytes())
    return path


_IDX_FILES = {
    "train_images": ["train-images-idx3-ubyte", "train-images.idx3-ubyte"],
    "train_labels": ["train-labels-idx1-ubyte", "train-labels.idx1-ubyte"],
    "test_images": ["t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"],
    "test_labels": ["t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"],
}

# Public mirrors for the canonical idx archives. Fashion-MNIST ships
# the same four file names.
_IDX_MIRRORS = {
    "mnist": [
        "https://storage.googleapis.com/cvdf-datasets/mnist/",
        "https://ossci-datasets.s3.amazonaws.com/mnist/",
    ],
    "fashion_mnist": [
        "http://fashion-mnist.s3-website.eu-central-1.amazonaws.com/",
    ],
}

# Pinned sha256 digests of the canonical MNIST .gz archives (as
# published across OSS dataset tooling) — passed by default so the
# default download path rejects a well-formed substitute served by a
# hostile mirror, not just a corrupt one. A mismatch is handled like
# any fetch failure: the file is discarded and the next mirror (or the
# synthetic fallback) takes over, so a stale pin can never hard-break
# ingest. Fashion-MNIST publishes md5s, not sha256s, in its README —
# no offline-verifiable sha256 exists here, so it stays unpinned
# (structural idx validation still applies).
_PINNED_SHA256 = {
    "mnist": {
        "train-images-idx3-ubyte.gz":
            "440fcabf73cc546fa21475e81ea370265605f56be210a4024d2ca8f203523609",
        "train-labels-idx1-ubyte.gz":
            "3552534a0a558bbed6aed32b30c495cca23d567ec52cac8be1a0730e8010255c",
        "t10k-images-idx3-ubyte.gz":
            "8d422c7b0a1c1c79245a5bcf07fe86e33eeafee792b84584aec276f5a2dbc4e6",
        "t10k-labels-idx1-ubyte.gz":
            "f7ae60f92e00ec6debd23a6088c31dbd2371eca3ffa0defaefb259924204aec6",
    },
}


def maybe_download(data_dir: str | Path, dataset: str = "mnist",
                   timeout: float = 30.0,
                   expected_sha256: dict[str, str] | None = None) -> bool:
    """Fetch any missing idx.gz files into ``data_dir`` with caching
    (≙ maybe_download, src/mnist_data.py:176-187 — which pulled from
    the Yann LeCun host; mirrors here because that host now throttles).

    Returns True when all four files are present afterwards. Network
    failure is not an error — the caller falls back to synthetic data —
    but a file that downloads with a corrupt idx payload is deleted and
    reported so a truncated fetch can't poison the cache. Pass
    ``expected_sha256`` ({file name → hex digest}) to pin archives
    cryptographically — the structural idx validation alone cannot
    reject a well-formed substitute served by a hostile network. When
    omitted, the per-dataset ``_PINNED_SHA256`` pins apply by default;
    pass ``{}`` explicitly to disable pinning.

    Concurrency-safe for shared data dirs (e.g. every process of a
    multi-host launch downloading at once): each writer stages to a
    pid-unique temp file and installs with an atomic rename.
    """
    from ..core.log import get_logger
    logger = get_logger("data")
    root = Path(data_dir)
    mirrors = _IDX_MIRRORS.get(dataset)
    if mirrors is None:
        return False
    if expected_sha256 is None:
        expected_sha256 = _PINNED_SHA256.get(dataset, {})
    root.mkdir(parents=True, exist_ok=True)
    ok = True
    for key, names in _IDX_FILES.items():
        if _find_idx(root, names) is not None:
            continue  # cached
        fname = names[0] + ".gz"
        fetched = False
        for base in mirrors:
            url = base + fname
            # gz suffix kept so the validator opens the staged file
            # through gzip; pid-unique stem avoids cross-process races
            tmp = root / f".{os.getpid()}.part.{fname}"
            final = root / fname
            try:
                import urllib.request
                with urllib.request.urlopen(url, timeout=timeout) as r, \
                        open(tmp, "wb") as f:
                    f.write(r.read())
                if expected_sha256 and fname in expected_sha256:
                    import hashlib
                    got = hashlib.sha256(tmp.read_bytes()).hexdigest()
                    if got != expected_sha256[fname]:
                        raise ValueError(
                            f"sha256 mismatch for {fname}: {got}")
                # full structural parse → truncated/corrupt payloads out
                _read_idx_ubyte(tmp, 3 if "images" in key else 1)
                tmp.rename(final)  # atomic install
            except Exception as e:  # no egress / mirror down / corrupt
                tmp.unlink(missing_ok=True)
                logger.warning("could not fetch %s: %s", url, e)
                continue
            logger.info("downloaded %s from %s", fname, base)
            fetched = True
            break
        # another process may have installed it while we failed
        ok &= fetched or _find_idx(root, names) is not None
    return ok


def _find_idx(root: Path, names: list[str]) -> Path | None:
    for name in names:
        for cand in (root / name, root / (name + ".gz")):
            if cand.exists():
                return cand
    return None


def load_idx_dataset(data_dir: str | Path, validation_size: int = 5000) -> Datasets:
    """Load MNIST-format idx files from ``data_dir`` (works for MNIST
    and Fashion-MNIST, which share the format)."""
    root = Path(data_dir)
    paths = {k: _find_idx(root, v) for k, v in _IDX_FILES.items()}
    missing = [k for k, v in paths.items() if v is None]
    if missing:
        raise FileNotFoundError(
            f"idx files missing under {root}: {missing} "
            f"(no network egress — place files there or use dataset='synthetic')")
    train_x = read_idx_images(paths["train_images"])
    train_y = read_idx_labels(paths["train_labels"])
    test_x = read_idx_images(paths["test_images"])
    test_y = read_idx_labels(paths["test_labels"])
    v = min(validation_size, len(train_y) // 10)
    return Datasets(
        train=ArrayDataset(train_x[v:], train_y[v:]),
        validation=ArrayDataset(train_x[:v], train_y[:v]),
        test=ArrayDataset(test_x, test_y),
    )


# --------------------------------------------------------------------------
# CIFAR-10 (python pickle batches) — the v4-32 stress config's payload
# (BASELINE.json configs[4])
# --------------------------------------------------------------------------

def load_cifar10(data_dir: str | Path, validation_size: int = 5000) -> Datasets:
    root = Path(data_dir)
    batch_dir = root / "cifar-10-batches-py"
    if not batch_dir.exists():
        batch_dir = root
    train_files = sorted(batch_dir.glob("data_batch_*"))
    test_file = batch_dir / "test_batch"
    if not train_files or not test_file.exists():
        raise FileNotFoundError(
            f"CIFAR-10 pickle batches not found under {root} "
            f"(use dataset='synthetic' when no data is on disk)")

    def load_batch(path: Path):
        with open(path, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32)
        x = (x - PIXEL_DEPTH / 2.0) / PIXEL_DEPTH
        y = np.asarray(d[b"labels"], dtype=np.int32)
        return x, y

    xs, ys = zip(*(load_batch(p) for p in train_files))
    train_x, train_y = np.concatenate(xs), np.concatenate(ys)
    test_x, test_y = load_batch(test_file)
    v = min(validation_size, len(train_y) // 10)
    return Datasets(
        train=ArrayDataset(train_x[v:], train_y[v:]),
        validation=ArrayDataset(train_x[:v], train_y[:v]),
        test=ArrayDataset(test_x, test_y),
    )


# --------------------------------------------------------------------------
# Deterministic learnable synthetic data
# --------------------------------------------------------------------------

def make_synthetic(num_train: int, num_test: int, image_size: int = 28,
                   num_channels: int = 1, num_classes: int = 10,
                   seed: int = 12345, noise: float = 0.08) -> Datasets:
    """Class-conditional smooth templates + Gaussian noise: separable
    (a CNN reaches ≈100% — making it a usable convergence oracle, ≙ the
    evaluator's role in SURVEY §4) yet non-trivial, and fully
    deterministic given ``seed``."""
    rng = np.random.default_rng(seed)
    low = max(4, image_size // 4)
    templates = rng.standard_normal((num_classes, low, low, num_channels)).astype(np.float32)
    # bilinear-upsample templates to full resolution → smooth class shapes
    up = np.empty((num_classes, image_size, image_size, num_channels), np.float32)
    xs = np.linspace(0, low - 1, image_size)
    x0 = np.clip(np.floor(xs).astype(int), 0, low - 2)
    fx = (xs - x0).astype(np.float32)
    for c in range(num_classes):
        t = templates[c]
        rows = (t[x0] * (1 - fx)[:, None, None] + t[x0 + 1] * fx[:, None, None])
        up[c] = (rows[:, x0] * (1 - fx)[None, :, None]
                 + rows[:, x0 + 1] * fx[None, :, None])
    up = up / (np.abs(up).max() + 1e-6) * 0.45  # keep within [-0.5, 0.5]

    def sample(n: int) -> ArrayDataset:
        labels = rng.integers(0, num_classes, size=n).astype(np.int32)
        images = up[labels] + rng.standard_normal(
            (n, image_size, image_size, num_channels)).astype(np.float32) * noise
        images = np.clip(images, -0.5, 0.5)
        return ArrayDataset(images, labels)

    return Datasets(train=sample(num_train),
                    validation=sample(max(num_test // 2, 256)),
                    test=sample(num_test))


def make_synthetic_lm(num_train: int, num_test: int, seq_len: int = 128,
                      vocab_size: int = 256, seed: int = 12345,
                      peak: float = 3.0) -> Datasets:
    """Deterministic learnable token sequences for the long-context
    (transformer) family: a fixed random first-order Markov chain with
    peaked transitions. A causal LM that learns the transition table
    drives next-token loss well below the unigram entropy — the
    convergence oracle for the sequence path."""
    rng = np.random.default_rng(seed)
    logits = rng.standard_normal((vocab_size, vocab_size)) * peak
    probs = np.exp(logits - logits.max(axis=1, keepdims=True))
    probs /= probs.sum(axis=1, keepdims=True)
    cdf = np.cumsum(probs, axis=1)

    def sample(n: int) -> ArrayDataset:
        seqs = np.empty((n, seq_len), np.int32)
        seqs[:, 0] = rng.integers(0, vocab_size, n)
        for t in range(1, seq_len):
            u = rng.random(n)[:, None]
            seqs[:, t] = (cdf[seqs[:, t - 1]] < u).sum(axis=1)
        return ArrayDataset(seqs, seqs.copy())

    return Datasets(train=sample(num_train),
                    validation=sample(max(num_test // 2, 64)),
                    test=sample(num_test))


# --------------------------------------------------------------------------
# registry entry point
# --------------------------------------------------------------------------

def load_datasets(cfg: DataConfig, image_size: int = 28, num_channels: int = 1,
                  num_classes: int = 10, seq_len: int = 128,
                  vocab_size: int = 256) -> Datasets:
    """≙ load_mnist (src/mnist_data.py:212-213), generalized. Falls
    back to synthetic data when real files are absent (logged, never
    silent)."""
    from ..core.log import get_logger
    logger = get_logger("data")
    name = cfg.dataset
    try:
        if name in ("mnist", "fashion_mnist"):
            # hand-placed flat files still load; downloads always land
            # in a per-dataset subdir (mnist and fashion_mnist share
            # file names — a flat cache would silently cross-serve)
            sub = Path(cfg.data_dir) / name
            root = sub if sub.exists() else Path(cfg.data_dir)
            if (cfg.download
                    and any(_find_idx(root, v) is None
                            for v in _IDX_FILES.values())):
                maybe_download(sub, name)
                root = sub
            return load_idx_dataset(root)
        if name == "cifar10":
            return load_cifar10(cfg.data_dir)
        if name == "synthetic":
            return make_synthetic(cfg.synthetic_train_size, cfg.synthetic_test_size,
                                  image_size, num_channels, num_classes)
        if name == "synthetic_lm":
            return make_synthetic_lm(cfg.synthetic_train_size,
                                     cfg.synthetic_test_size,
                                     seq_len, vocab_size)
        raise ValueError(f"unknown dataset {name!r}")
    except FileNotFoundError as e:
        logger.warning("%s — falling back to synthetic data", e)
        return make_synthetic(cfg.synthetic_train_size, cfg.synthetic_test_size,
                              image_size, num_channels, num_classes)
