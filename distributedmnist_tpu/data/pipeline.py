"""Batching pipeline: deterministic per-epoch reshuffle, host sharding,
eval padding, and (optional) native C++ prefetch.

≙ ``DataSet.next_batch`` — which reshuffles per epoch with a *time*
seed (src/mnist_data.py:55,80-84,102-130). Here the shuffle stream is
seeded (replayable) and epoch-indexed; under ``shard_mode="sharded"``
each host iterates only its slice, under ``"independent"`` each host
iterates its own full-data shuffle (the reference's faithful mode).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from ..core.config import DataConfig
from .datasets import ArrayDataset


class BatchIterator:
    """Infinite epoch-reshuffling batch stream over an ArrayDataset.

    Yields numpy dicts {"image": [b, ...], "label": [b]} where ``b`` is
    the *host-local* batch (global batch / process_count).
    """

    def __init__(self, data: ArrayDataset, batch_size: int, seed: int,
                 host_id: int = 0, num_hosts: int = 1,
                 shard_mode: str = "sharded", drop_remainder: bool = True):
        if batch_size % num_hosts != 0:
            raise ValueError(f"global batch {batch_size} not divisible by "
                             f"{num_hosts} hosts")
        self.local_batch = batch_size // num_hosts
        # the cursor's WORLD: hosts consume in lockstep (one local batch
        # per host per global batch), so a cursor can be re-expressed
        # under a different host count — see restore()
        self.host_id = host_id
        self.num_hosts = num_hosts
        self.global_batch = batch_size
        if shard_mode == "sharded":
            self.data = data.shard(host_id, num_hosts) if num_hosts > 1 else data
            self.seed = seed  # same shuffle stream, disjoint data
        elif shard_mode == "independent":
            self.data = data  # full copy per host, host-distinct stream
            self.seed = seed * 1_000_003 + host_id
        else:
            raise ValueError(f"unknown shard_mode {shard_mode!r}")
        if self.data.num_examples < self.local_batch:
            raise ValueError(
                f"host-local dataset ({self.data.num_examples}) smaller than "
                f"host-local batch ({self.local_batch})")
        self.drop_remainder = drop_remainder
        self._epoch = 0
        self._pos = 0
        self._order = self._epoch_order(0)

    def _epoch_order(self, epoch: int) -> np.ndarray:
        rng = np.random.default_rng((self.seed, epoch))
        return rng.permutation(self.data.num_examples)

    @property
    def epoch(self) -> int:
        return self._epoch

    def __iter__(self) -> Iterator[dict]:
        return self

    def __next__(self) -> dict:
        n = self.data.num_examples
        if self._pos + self.local_batch > n:
            # drop the ragged tail and reshuffle (≙ src/mnist_data.py:113-125)
            self._epoch += 1
            self._order = self._epoch_order(self._epoch)
            self._pos = 0
        idx = self._order[self._pos:self._pos + self.local_batch]
        self._pos += self.local_batch
        return {"image": self.data.images[idx], "label": self.data.labels[idx]}

    @property
    def batches_per_epoch(self) -> int:
        """Full local batches one epoch of THIS host's shard yields
        (the ragged tail is dropped, matching ``__next__``)."""
        return self.data.num_examples // self.local_batch

    @property
    def batches_consumed(self) -> int:
        """Lockstep global-batch count this cursor has advanced through
        — the world-size-independent coordinate every host of every
        world agrees on (each global batch consumes exactly one local
        batch on every host)."""
        return (self._epoch * self.batches_per_epoch
                + self._pos // self.local_batch)

    def state(self) -> dict:
        """Checkpointable position (the reference cannot resume its
        data stream; we can). Tagged with the shuffle implementation —
        an (epoch, pos) cursor only identifies a stream position within
        ONE permutation sequence — and with the WORLD it was taken
        under plus the world-independent ``batches`` coordinate, so a
        resume onto a different host count can re-derive its own
        (epoch, pos) instead of misreading a foreign shard's cursor."""
        return {"impl": "numpy", "epoch": self._epoch, "pos": self._pos,
                "batches": self.batches_consumed,
                "world": {"num_hosts": self.num_hosts,
                          "host_id": self.host_id,
                          "batch_size": self.global_batch}}

    def seek_batches(self, batches: int) -> None:
        """Position the stream exactly ``batches`` global batches in —
        the old-world→new-world cursor reassignment: ``batches`` is
        host-count-independent, so every host of the NEW world seeks to
        the same lockstep coordinate and the union of consumed sample
        slots continues gap- and overlap-free across the world change
        (see :func:`consumed_sample_ranges`)."""
        if batches < 0:
            raise ValueError(f"batches must be >= 0, got {batches}")
        bpe = self.batches_per_epoch
        self._epoch = batches // bpe
        self._order = self._epoch_order(self._epoch)
        self._pos = (batches % bpe) * self.local_batch

    def restore(self, state: dict) -> None:
        impl = state.get("impl", "numpy")
        if impl != "numpy":
            raise ValueError(
                f"data-iterator state was produced by the {impl!r} pipeline; "
                "restoring it into the numpy shuffle stream would replay a "
                "different permutation")
        world = state.get("world")
        if world is not None and (
                world.get("num_hosts") != self.num_hosts
                or world.get("host_id") != self.host_id
                or world.get("batch_size") != self.global_batch):
            # cross-world resume (elastic reconfigure, or a grown
            # worker seeded with a survivor's checkpoint): the saved
            # (epoch, pos) indexes a DIFFERENT shard's permutation —
            # reassign via the lockstep batch coordinate so no sample
            # range is dropped or double-visited
            batches = state.get("batches")
            if batches is None:
                raise ValueError(
                    f"data-iterator state from world {world} has no "
                    f"'batches' coordinate; cannot reassign it to world "
                    f"(num_hosts={self.num_hosts}, host_id={self.host_id}, "
                    f"batch_size={self.global_batch})")
            self.seek_batches(int(batches))
            return
        self._epoch = int(state["epoch"])
        self._order = self._epoch_order(self._epoch)
        self._pos = int(state["pos"])


class GradAccumFeed:
    """Feed adapter for gradient accumulation (train.grad_accum_steps):
    each ``next()`` pulls ``accum`` consecutive batches from the inner
    stream and concatenates them along dim 0 — the train step scans
    that as microbatches and applies the optimizer once.

    The inner ``BatchIterator``'s cursor math is untouched: it simply
    advances ``accum`` batches per training step, so ``state()`` /
    ``restore()`` (passed straight through) checkpoint the exact
    sample-stream position in the same lockstep ``batches`` coordinate
    the elastic-resume contract uses — a resume under a different
    ``grad_accum_steps`` (or world size) re-derives its own grouping
    from the same coordinate with no samples dropped or re-visited."""

    def __init__(self, inner, accum: int):
        if accum < 1:
            raise ValueError(f"grad_accum_steps must be >= 1, got {accum}")
        self.inner = inner
        self.accum = accum
        self.has_state = callable(getattr(inner, "state", None))
        if not self.has_state:
            # shadow the pass-through methods so feed consumers that
            # probe callable(feed.state) (Trainer._save, the device
            # prefetcher) see the inner stream's true statelessness
            self.state = None      # type: ignore[assignment]
            self.restore = None    # type: ignore[assignment]

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        batches = [next(self.inner) for _ in range(self.accum)]
        if self.accum == 1:
            return batches[0]
        return {k: np.concatenate([b[k] for b in batches])
                for k in batches[0]}

    def state(self) -> dict:
        return self.inner.state()

    def restore(self, state: dict) -> None:
        self.inner.restore(state)

    def close(self) -> None:
        close = getattr(self.inner, "close", None)
        if callable(close):
            close()


def consumed_sample_ranges(state: dict) -> list[tuple[int, int]]:
    """The half-open global CONSUMPTION-SLOT index ranges a cursor
    state covers: global batch ``b`` assigns slots
    ``[b·B + h·lb, b·B + (h+1)·lb)`` to host ``h`` (``B`` = global
    batch, ``lb = B / num_hosts``). Under lockstep consumption the
    union over a world's hosts is exactly ``[0, batches·B)`` and the
    per-host ranges are disjoint — which is the old-world→new-world
    reassignment contract: after :meth:`BatchIterator.restore` onto a
    different host count, the new world's union equals the old world's
    (no slot dropped, none double-visited). The property test in
    tests/test_elastic.py pins this."""
    world = state.get("world")
    if world is None or state.get("batches") is None:
        raise ValueError("cursor state carries no world/batches "
                         "coordinates (legacy pre-elastic state)")
    B = int(world["batch_size"])
    h = int(world["host_id"])
    lb = B // int(world["num_hosts"])
    batches = int(state["batches"])
    return [(b * B + h * lb, b * B + (h + 1) * lb) for b in range(batches)]


def eval_batches(data: ArrayDataset, batch_size: int, pad_multiple: int = 1,
                 host_id: int = 0, num_hosts: int = 1) -> Iterator[dict]:
    """Fixed-order eval batches with 0/1 weights; batches are
    zero-padded to full size so shapes stay static under jit (the
    reference instead builds a graph at batch = full test-set size,
    src/nn_eval.py:121-122 — static shapes are the TPU-native answer).

    Multi-host: ``data`` is the full split on every host; each host
    yields only its strided stripe (so psum'd weights count every
    example exactly once), and the number of batches is computed from
    the *global* size so all hosts stay in lockstep.
    """
    global_n = data.num_examples
    if batch_size <= 0:
        batch_size = global_n
    if batch_size % num_hosts != 0:
        batch_size += num_hosts - batch_size % num_hosts
    local_bs = batch_size // num_hosts
    if local_bs % pad_multiple != 0:
        local_bs += pad_multiple - local_bs % pad_multiple
    stripe = data.shard(host_id, num_hosts) if num_hosts > 1 else data
    max_stripe = -(-global_n // num_hosts)  # ceil: the largest stripe
    num_batches = max(1, -(-max_stripe // local_bs))
    for b in range(num_batches):
        start = b * local_bs
        stop = min(start + local_bs, stripe.num_examples)
        take = max(stop - start, 0)
        x = stripe.images[start:start + take]
        y = stripe.labels[start:start + take]
        w = np.ones(take, np.float32)
        pad = local_bs - take
        if pad:
            x = np.concatenate([x, np.zeros((pad,) + data.images.shape[1:],
                                            data.images.dtype)])
            y = np.concatenate([y, np.zeros((pad,) + data.labels.shape[1:],
                                            data.labels.dtype)])
            w = np.concatenate([w, np.zeros(pad, np.float32)])
        yield {"image": x, "label": y, "weight": w}


def host_can_spare_producer_thread() -> bool:
    """One shared gate for every producer-thread optimization (the
    native C++ prefetcher below, the device-side ``DevicePrefetcher``):
    a producer thread needs a SPARE core. On a 1-core host it only
    fights the consumer for the one core — measured as a net slowdown
    (see the native gate's numbers below). Turn the knobs off
    explicitly (``data.use_native_pipeline`` /
    ``data.device_prefetch``) to override in the other direction."""
    import os

    return (os.cpu_count() or 1) >= 2


def device_prefetch_pays() -> bool:
    """Gate for the DEVICE-side prefetch stage specifically (train
    loop and eval share this one policy): a spare host core, OR a real
    accelerator backend — there the consumer's device drains park the
    host GIL-free, which is exactly when a producer thread gets its
    cycles even on one core. Single-core CPU-backend hosts feed
    inline (same measurement as the gate above)."""
    import jax

    return (host_can_spare_producer_thread()
            or jax.default_backend() != "cpu")


def make_train_iterator(data: ArrayDataset, cfg: DataConfig, seed: int,
                        host_id: int = 0, num_hosts: int = 1) -> BatchIterator:
    it = BatchIterator(data, cfg.batch_size, seed=seed, host_id=host_id,
                       num_hosts=num_hosts, shard_mode=cfg.shard_mode)
    if cfg.use_native_pipeline:
        from ..core.log import get_logger
        if not host_can_spare_producer_thread():
            # a prefetch thread can only fight the consumer for the one
            # core — measured as a net slowdown by bench_native_loader
            # under BOTH consumer shapes: cpu-busy (~0.6x) AND the
            # train loop's real device-blocked shape AT THE PRODUCTION
            # DEPTH of prefetch_batches=2 (median 0.90x over repeated
            # quiet-box runs). The earlier BENCH_r04 1.07x for this
            # case was measured at depth=10 — re-measured at depth 10
            # it is break-even noise (0.96-1.03x across runs), and at
            # the depth this gate actually governs the native path
            # loses: the per-batch queue handoff on one core costs
            # more than the ~2 ms prep it hides. Prefetching pays off
            # when a SPARE core runs the producer.
            get_logger("data").info(
                "single-core host: skipping the prefetch thread, "
                "using inline batching")
            return it
        try:
            from .native_loader import NativePrefetcher
        except ImportError as e:
            get_logger("data").warning(
                "native pipeline unavailable (%s); using pure-python batching", e)
        else:
            return NativePrefetcher(it, depth=cfg.prefetch_batches)  # type: ignore[return-value]
    return it
