"""Device-side prefetch: a bounded queue of batches already staged
through ``Topology.device_put_batch``.

The synchronous loop serially runs ``next(iter)`` → ``device_put`` →
dispatch, so host batch assembly and the H2D transfer sit on the
device's critical path every step. ``DevicePrefetcher`` moves both
onto a producer thread behind a bounded queue (depth ≥ 1): while the
device executes step *k*, the producer assembles and stages batch
*k+1* (and *k+2*, …, up to the depth), so ``next()`` hands the loop a
ready sharded global array. This is the input-pipeline overlap both
arXiv:1909.09756 (MLPerf on TPU-v3 pods) and arXiv:1605.08695
(TensorFlow) name as the first-order throughput fix — the same job
tf.data's ``prefetch_to_device`` does, built here over the repo's own
iterator protocol.

Guarantees the experiments lean on:

* **Exact order.** One producer thread and a FIFO queue: the staged
  stream is the inner iterator's stream, batch for batch. The CDF /
  quorum experiments replay bit-identical data under either feed.
* **Checkpointing.** ``state()`` returns the inner iterator's cursor
  *as of the last consumed batch* (the producer snapshots the cursor
  alongside every batch it stages), so a resume replays exactly the
  batches the training step never saw — prefetched-but-unconsumed
  batches are not skipped. ``restore()`` passes through.
* **Clean shutdown.** ``stop()``/``close()`` unblock and join the
  producer even when it is parked on a full queue, and re-sync the
  inner iterator's cursor to the consumed position so a later
  ``state()``/restart observes no phantom progress. A consumer that
  raises mid-stream just calls ``stop()`` from its ``finally``.

Producer errors (a broken inner iterator, a failed ``device_put``)
are captured and re-raised in the consumer at the next ``next()``.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator

_ITEM, _DONE, _ERR = "item", "done", "err"


class DevicePrefetcher:
    """Wraps a batch iterator; stages each batch via ``put`` (typically
    ``Topology.device_put_batch``) on a producer thread, ``depth``
    batches ahead.

    ``put`` may return anything — the eval path stages
    ``(host_weight_sum, global_array)`` tuples through it.

    The producer starts lazily on the first ``next()``, so wrapping an
    iterator costs nothing until the loop actually runs (and a restore
    before the first step never races the producer).
    """

    def __init__(self, it: Iterator[dict], put: Callable[[dict], Any],
                 depth: int = 2):
        self._it = it
        self._put = put
        self.depth = max(1, int(depth))
        self.has_state = callable(getattr(it, "state", None))
        self._restorable = self.has_state and callable(
            getattr(it, "restore", None))
        self._consumed_state = it.state() if self.has_state else None
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._closed = False

    @property
    def inner(self) -> Iterator[dict]:
        """The wrapped host-batch iterator."""
        return self._it

    # -- producer ------------------------------------------------------

    def _q_put(self, kind: str, payload: Any) -> bool:
        """Bounded put that stays responsive to ``stop()``; returns
        False when asked to stop instead of blocking forever on a full
        queue nobody will drain."""
        while not self._stop.is_set():
            try:
                self._q.put((kind, payload), timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    batch = next(self._it)
                except StopIteration:
                    self._q_put(_DONE, None)
                    return
                # cursor AFTER producing this batch == "this batch
                # consumed" once the consumer takes it
                snap = self._it.state() if self.has_state else None
                staged = self._put(batch)
                if not self._q_put(_ITEM, (staged, snap)):
                    return  # stopping; stop() re-syncs the cursor
        except BaseException as e:  # surface in the consumer thread
            self._q_put(_ERR, e)

    def _ensure_started(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._producer, name="device-prefetch", daemon=True)
            self._thread.start()

    # -- consumer ------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        if self._closed:
            raise RuntimeError("DevicePrefetcher is closed")
        self._ensure_started()
        while True:
            try:
                kind, payload = self._q.get(timeout=1.0)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    # the producer may have enqueued its terminal
                    # sentinel between our timeout and the liveness
                    # check — drain once before declaring it lost
                    try:
                        kind, payload = self._q.get_nowait()
                        break
                    except queue.Empty:
                        raise RuntimeError(
                            "device-prefetch producer died without "
                            "a sentinel")
        if kind is _ERR:
            # full stop(), not just a join: the producer advanced the
            # inner cursor past a batch it failed to stage — re-sync
            # (or close, if the inner can't rewind) so a consumer that
            # catches the error and retries never sees a silent hole
            self.stop()
            raise payload
        if kind is _DONE:
            self._join()
            raise StopIteration
        staged, snap = payload
        self._consumed_state = snap
        return staged

    @property
    def qsize(self) -> int:
        """Staged batches ready right now (the overlap gauge: 0 every
        step means the producer is the bottleneck; ``depth`` means the
        device is)."""
        return self._q.qsize()

    # -- checkpoint passthrough ---------------------------------------

    def state(self) -> dict:
        """The inner iterator's cursor at the last *consumed* batch."""
        if not self.has_state:
            raise RuntimeError("inner iterator has no checkpointable state")
        return dict(self._consumed_state)

    def restore(self, state: dict) -> None:
        if self._closed:
            raise RuntimeError("DevicePrefetcher is closed")
        if not self._restorable:
            raise RuntimeError("inner iterator is not restorable")
        self.stop()
        self._it.restore(state)
        self._consumed_state = dict(state)

    # -- lifecycle -----------------------------------------------------

    def _join(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            while t.is_alive():
                # the producer may be parked on a full queue; drain so
                # its put (or the stop check after it) can complete
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    pass
                t.join(timeout=0.05)
        while True:  # discard anything staged after the last drain
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread = None

    def stop(self) -> None:
        """Stop and join the producer, discarding staged batches, and
        re-sync the inner cursor to the consumed position (so nothing
        is skipped if iteration resumes — ``next()`` restarts the
        producer lazily). With a non-restorable inner iterator the
        discarded batches cannot be regenerated, so the prefetcher
        closes instead of resuming with a hole in the stream."""
        self._join()
        if self._restorable:
            self._it.restore(self._consumed_state)
        else:
            self._closed = True

    def close(self) -> None:
        """``stop()`` + permanently closed. Idempotent."""
        if not self._closed:
            self.stop()
        self._closed = True

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            if not self._closed:
                self._stop.set()  # don't block GC on a full-queue join
                self.close()
        except Exception:
            pass
