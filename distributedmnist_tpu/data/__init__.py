from .datasets import (ArrayDataset, Datasets, load_cifar10, load_datasets,
                       load_idx_dataset, make_synthetic)
from .pipeline import BatchIterator, eval_batches, make_train_iterator

__all__ = [
    "ArrayDataset", "Datasets", "load_cifar10", "load_datasets",
    "load_idx_dataset", "make_synthetic", "BatchIterator", "eval_batches",
    "make_train_iterator",
]
