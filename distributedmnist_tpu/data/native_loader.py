"""ctypes bindings for the native (C++) data pipeline.

``NativePrefetcher`` is a drop-in replacement for
:class:`~distributedmnist_tpu.data.pipeline.BatchIterator`: same batch
shapes, same epoch/cursor checkpoint state, same drop-ragged-tail
epoch semantics (≙ src/mnist_data.py:113-125) — but batch gathering
and shuffling run in a C++ producer thread behind a bounded prefetch
queue, so host batch assembly overlaps device execution. The shuffle
stream is the library's own splitmix64 Fisher-Yates keyed on
(seed, epoch): deterministic and resumable, though a *different*
permutation than the numpy stream of the python iterator.

Importing this module builds the library on first use; an unavailable
toolchain surfaces as ImportError so `make_train_iterator`'s fallback
catches it.
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..native import NativeBuildError, load_library
from .pipeline import BatchIterator

try:
    _LIB = load_library()
except NativeBuildError as e:  # degrade to the pure-python pipeline
    raise ImportError(str(e)) from e


def read_idx(path) -> np.ndarray:
    """Decode an idx(.gz) file via the native reader (≙ the python
    readers in data.datasets, which remain the fallback)."""
    out_data = ctypes.POINTER(ctypes.c_uint8)()
    ndim = ctypes.c_int32(0)
    dims = (ctypes.c_int64 * 4)()
    rc = _LIB.dml_read_idx(str(path).encode(), ctypes.byref(out_data),
                           ctypes.byref(ndim), dims)
    if rc != 0:
        raise ValueError(f"native idx read of {path} failed (code {rc})")
    shape = tuple(dims[i] for i in range(ndim.value))
    try:
        n = int(np.prod(shape))
        arr = np.ctypeslib.as_array(out_data, shape=(n,)).copy().reshape(shape)
    finally:
        _LIB.dml_free(out_data)
    return arr


class NativePrefetcher:
    """Wraps a fresh BatchIterator's dataset in the C++ prefetch loader."""

    def __init__(self, it: BatchIterator, depth: int = 2):
        self.local_batch = it.local_batch
        # Keep contiguous copies alive for the lifetime of the handle —
        # the C++ side borrows these buffers.
        self._images = np.ascontiguousarray(it.data.images)
        self._labels = np.ascontiguousarray(it.data.labels)
        self._img_row = int(self._images.dtype.itemsize
                            * np.prod(self._images.shape[1:], dtype=np.int64))
        self._lab_row = int(self._labels.dtype.itemsize
                            * np.prod(self._labels.shape[1:], dtype=np.int64))
        self._handle = _LIB.dml_loader_create(
            self._images.ctypes.data, self._labels.ctypes.data,
            self._images.shape[0], self._img_row, self._lab_row,
            self.local_batch, int(it.seed) & 0xFFFFFFFFFFFFFFFF,
            max(1, int(depth)))
        if not self._handle:
            raise RuntimeError("dml_loader_create rejected its arguments")
        self._epoch = 0
        self._pos = 0

    @property
    def epoch(self) -> int:
        return self._epoch

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        if not self._handle:
            raise RuntimeError("NativePrefetcher is closed")
        b = self.local_batch
        images = np.empty((b,) + self._images.shape[1:], self._images.dtype)
        labels = np.empty((b,) + self._labels.shape[1:], self._labels.dtype)
        epoch = ctypes.c_int64(0)
        pos = ctypes.c_int64(0)
        rc = _LIB.dml_loader_next(self._handle, images.ctypes.data,
                                  labels.ctypes.data, ctypes.byref(epoch),
                                  ctypes.byref(pos))
        if rc != 0:
            raise RuntimeError("native loader stopped")
        self._epoch, self._pos = epoch.value, pos.value
        return {"image": images, "label": labels}

    def state(self) -> dict:
        """Checkpointable cursor of the last *consumed* batch, tagged
        with the shuffle implementation (a cursor is only meaningful
        within one permutation stream)."""
        return {"impl": "native", "epoch": self._epoch, "pos": self._pos}

    def restore(self, state: dict) -> None:
        if not self._handle:
            raise RuntimeError("NativePrefetcher is closed")
        impl = state.get("impl", "numpy")
        if impl != "native":
            raise ValueError(
                f"data-iterator state was produced by the {impl!r} pipeline; "
                "restoring it into the native shuffle stream would replay a "
                "different permutation")
        self._epoch = int(state["epoch"])
        self._pos = int(state["pos"])
        _LIB.dml_loader_restore(self._handle, self._epoch, self._pos)

    def close(self) -> None:
        if getattr(self, "_handle", None):
            _LIB.dml_loader_destroy(self._handle)
            self._handle = None

    def __enter__(self) -> "NativePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        # same with-block lifecycle as data.device_prefetch — the two
        # stages compose (C++ assembles k+2 while the device stage
        # uploads k+1), so they should tear down the same way too
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
