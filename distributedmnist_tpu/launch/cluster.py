"""Pluggable cluster backends beneath one launcher.

≙ the reference's orchestrator, split the way TF-Replicator
(arXiv:1902.00465) splits it: one user-facing lifecycle surface, N
backend realizations. ``tools/tf_ec2.py`` fused "what a cluster is"
(EC2 spot instances, :237-271) with "how to drive one" (parallel SSH
fan-out, :536-569) into one file; here :class:`ClusterBackend` is the
contract — create / delete / status / run_train / kill_all / exec_all
/ download / poll — and two backends realize it:

* :class:`GcloudTpuBackend` — the gcloud TPU-VM argv builders
  refactored out of ``launch/pod.py`` (argv unchanged; ``PodManager``
  now delegates here).
* :class:`LocalProcessCluster` — the same lifecycle as REAL local
  subprocesses: N worker processes running ``launch train`` under
  ``JAX_PLATFORMS=cpu``, per-worker logdirs, a pgrep-equivalent
  ``status()`` probe, file-copy ``download``. Every verb executes as
  an actual subprocess through :class:`~.exec.CommandExecutor`, so
  ``create → run → poll --until-step → download → delete`` runs
  end-to-end on this box and leaves a JSONL command journal.

The module-level :func:`wait_until_step` / :func:`run_until_step`
drivers (≙ tools/benchmark.py:24-44 launch → poll ssh'd log → kill at
step N) are generic over backends — the fault-injected lifecycle tests
drive them against real processes.
"""

from __future__ import annotations

import abc
import dataclasses
import json
import os
import shlex
import subprocess
import time
from pathlib import Path
from typing import Any

from ..core.log import get_logger
from ..obsv.journal import tail_records
from .exec import CommandExecutor, ExecError, FaultPlan, RetryPolicy

logger = get_logger("cluster")


class ClusterError(RuntimeError):
    pass


def parse_poll_output(text: str | None) -> dict[str, Any]:
    """Parse the tail of a ``train_log.jsonl`` into {"step", "record"}.

    Scans BACKWARDS (obsv/journal.py ``tail_records``) past a torn/
    non-JSON final line to the last intact STEP record: reporting step
    -1 for a whole poll tick makes live progress look stalled — which
    a supervisor's ``stall_timeout_s`` could misread as a hang. Intact
    non-step records (the ``event: "compile"`` line a precompiling
    worker appends before its first step) are skipped the same way:
    they are liveness, not regression to -1. step is -1 only when no
    step record exists at all (run still booting, or the tail window
    held nothing usable — the next poll resolves it).
    """
    for record in tail_records(text=text or ""):
        if "step" not in record:
            continue  # compile/other event record — not a step reading
        return {"step": int(record["step"]), "record": record}
    return {"step": -1, "record": None}


def worker_logged_since_spawn(worker: dict) -> bool:
    """Has this worker appended to its own train_log.jsonl since its
    CURRENT incarnation spawned? False means it is still booting (a
    restarted jax worker spends ~15-30 s before its first log line).
    ``worker`` is a status()/state entry carrying ``logdir`` and
    ``spawned_at``; an unknown spawn time (pre-``spawned_at`` state
    files) reads as True — the legacy behavior. Shared by the chaos
    drain and the supervisor's reconfigure-resume watch."""
    spawned = worker.get("spawned_at")
    if spawned is None:
        return True
    log = Path(worker["logdir"]) / "train_log.jsonl"
    try:
        return log.stat().st_mtime >= spawned
    except OSError:
        return False  # no log at all yet: definitely still booting


def worker_resumed_step_since_spawn(worker: dict,
                                    events: tuple[str, ...] = ("step",)
                                    ) -> tuple[int, float | None] | None:
    """``(step, record_time)`` proving this worker's CURRENT
    incarnation produced a training step, or None if it has not
    provably resumed. Log mtime moving since the worker's own
    (re)spawn is necessary but NOT sufficient: a restarted trainer
    journals its ``event: "compile"`` record before its first step,
    and an adopted logdir still carries the previous incarnation's
    step records — closing on either would journal a resume with a
    stale step and count a worker that wedged right after boot as
    recovered. Only the newest intact record being a STEP record
    (appended since spawn, so it is this incarnation's) is a
    first-moved-step; its own ``time`` stamp (when the step happened,
    vs when this sweep observed it) is what MTTR-style latencies close
    on. A torn newest line returns None — the next-intact record
    behind it may belong to the previous incarnation; wait a tick.

    ``events``: the record types that count as this payload's progress
    — ``("step",)`` for trainers; a serving payload's progress records
    are ``event: "heartbeat"`` (terminal-outcome count), so its
    callers pass ``("step", "heartbeat")``."""
    if not worker_logged_since_spawn(worker):
        return None
    log = Path(worker["logdir"]) / "train_log.jsonl"
    try:
        with open(log, "rb") as fh:
            fh.seek(0, 2)
            fh.seek(max(0, fh.tell() - 8192))
            lines = fh.read().decode("utf-8", "replace").splitlines()
    except OSError:
        return None
    for ln in reversed(lines):
        if not ln.strip():
            continue
        try:
            rec = json.loads(ln)
        except ValueError:
            return None  # torn newest write — cannot prove resume yet
        if not isinstance(rec, dict):
            return None
        if rec.get("event", "step") not in events:
            return None  # newest intact record: compile, not progress
        step = rec.get("step")
        if not isinstance(step, int):
            return None
        t = rec.get("time")
        return step, (t if isinstance(t, (int, float)) else None)
    return None


class ClusterBackend(abc.ABC):
    """The lifecycle contract every backend realizes (≙ the reference's
    11-subcommand dispatch, tools/tf_ec2.py:828-856, as an interface)."""

    @abc.abstractmethod
    def create(self) -> None: ...

    @abc.abstractmethod
    def delete(self, ignore_missing: bool = False) -> None:
        """Tear the cluster down. ``ignore_missing``: deleting a
        cluster that does not exist is not an error (the
        delete-if-exists step of clean-launch-run)."""

    @abc.abstractmethod
    def status(self) -> dict[str, Any] | None: ...

    @abc.abstractmethod
    def run_train(self) -> None: ...

    @abc.abstractmethod
    def kill_all(self, worker: str = "all") -> None: ...

    @abc.abstractmethod
    def exec_all(self, command: str, worker: str = "all") -> None: ...

    @abc.abstractmethod
    def download(self, local_dir: str | Path,
                 remote_path: str | None = None,
                 worker: str = "0") -> None: ...

    @abc.abstractmethod
    def poll(self) -> dict[str, Any] | None: ...

    # recovery verb (non-abstract so pre-existing backends stay valid):
    # respawn ONE worker's training process in place; the worker's own
    # resume-from-checkpoint logic decides where it continues
    def restart_worker(self, k: int) -> None:
        raise NotImplementedError(
            f"{type(self).__name__} cannot restart individual workers")

    # elastic verb (ROADMAP item 2): reshape the cluster's world
    # WITHOUT spawning — the supervisor drains before and relaunches
    # after. Non-abstract: backends without it simply aren't elastic.
    def reconfigure(self, new_num_workers: int,
                    survivors: list[int] | None = None) -> dict[str, Any]:
        raise NotImplementedError(
            f"{type(self).__name__} cannot reconfigure its world size")


# ---------------------------------------------------------------------------
# generic lifecycle drivers (backend-agnostic)
# ---------------------------------------------------------------------------

def wait_until_step(backend: ClusterBackend, target: int,
                    poll_secs: float = 30.0,
                    timeout_secs: float = 24 * 3600.0) -> dict[str, Any]:
    """Block until the cluster's run reaches ``target`` steps
    (≙ benchmark.py's run-until-step-N loop :24-34). Dry-run backends
    record exactly one poll argv and return immediately."""
    deadline = time.monotonic() + timeout_secs
    while True:
        got = backend.poll()
        if got is None:  # dry-run
            return {"step": target, "record": None, "dry_run": True}
        if got["step"] >= target:
            return got
        if got.get("workers_alive") == 0:
            # every worker is gone and the log never reached the target
            # — a crashed cluster must fail now, not at the poll timeout
            # (backends that can't count workers omit the key)
            raise ClusterError(
                f"no live workers and the run stopped at step "
                f"{got['step']} < {target}")
        if time.monotonic() >= deadline:
            raise ClusterError(
                f"run did not reach step {target} within "
                f"{timeout_secs:.0f}s (last seen: {got['step']})")
        logger.info("step %d/%d — next poll in %.0fs",
                    got["step"], target, poll_secs)
        time.sleep(poll_secs)


def run_until_step(backend: ClusterBackend, target: int,
                   poll_secs: float = 30.0,
                   timeout_secs: float = 24 * 3600.0) -> dict[str, Any]:
    """Launch training, follow the log to step ``target``, then stop
    the run — on EVERY exit path: a poll timeout or a Ctrl-C must not
    leave the cluster training (and, on cloud backends, billing)."""
    backend.run_train()
    try:
        return wait_until_step(backend, target, poll_secs, timeout_secs)
    finally:
        backend.kill_all()


# ---------------------------------------------------------------------------
# gcloud TPU-VM backend (argv builders refactored out of PodManager)
# ---------------------------------------------------------------------------

class GcloudTpuBackend(ClusterBackend):
    """The Cloud TPU realization: one slice resource, SSH fan-out via
    ``gcloud compute tpus tpu-vm ssh --worker=all``, scp downloads.
    ``cfg`` is a :class:`~.pod.PodConfig`; ``runner`` any executor with
    a ``run(argv, check=..., capture=..., verb=...)`` method (the
    ``pod.Runner`` compat shim or a bare :class:`CommandExecutor`)."""

    def __init__(self, cfg, runner):
        self.cfg = cfg
        self.runner = runner

    # -- argv builders (pure) -------------------------------------------

    def _base(self, *verb: str) -> list[str]:
        argv = ["gcloud", "compute", "tpus", "tpu-vm", *verb, self.cfg.name,
                "--zone", self.cfg.zone]
        if self.cfg.project:
            argv += ["--project", self.cfg.project]
        return argv

    def _ssh(self, command: str, worker: str = "all") -> list[str]:
        exports = "".join(f"export {k}={shlex.quote(v)}; "
                          for k, v in self.cfg.env.items())
        return self._base("ssh") + ["--worker", worker,
                                    "--command", exports + command]

    # -- lifecycle ------------------------------------------------------

    def create(self) -> None:
        """≙ launch (tf_ec2.py:796): create the slice, run setup."""
        argv = self._base("create") + [
            "--accelerator-type", self.cfg.accelerator_type,
            "--version", self.cfg.runtime_version]
        if self.cfg.spot:
            argv.append("--spot")
        self.runner.run(argv, verb="create")
        if self.cfg.setup_command:
            self.runner.run(self._ssh(self.cfg.setup_command), verb="exec")

    def delete(self, ignore_missing: bool = False) -> None:
        """≙ shutdown (tf_ec2.py:440)."""
        self.runner.run(self._base("delete") + ["--quiet"], verb="delete",
                        check=not ignore_missing)

    def status(self) -> dict[str, Any] | None:
        """≙ list_running/list_idle (tf_ec2.py:371-404): slice state
        plus whether python is running on any worker."""
        out = self.runner.run(self._base("describe") + ["--format", "json"],
                              capture=True, verb="status")
        # [d]… so the pattern never matches the ssh-spawned shell whose
        # own command line contains it (pgrep -f excludes only itself).
        probe = self.runner.run(
            self._ssh("pgrep -c -f '[d]istributedmnist_tpu.launch' || true"),
            capture=True, check=False, verb="status")
        if out is None:  # dry-run: both argvs recorded above, no result
            return None
        desc = json.loads(out.stdout)
        if probe is None or probe.returncode != 0:
            idle = None  # probe failed — unknown, NOT "idle" (a caller
            # keying deletion off idle must not kill a live run)
        else:
            idle = not any(line.strip() not in ("", "0")
                           for line in (probe.stdout or "").splitlines())
        return {"state": desc.get("state"), "idle": idle, "describe": desc}

    # -- work -----------------------------------------------------------

    def _launch_command(self) -> str:
        """The one nohup launch line — shared by the initial fan-out and
        per-worker restarts so the two can never drift."""
        outdir = shlex.quote(self.cfg.remote_outdir)
        log = shlex.quote(f"{self.cfg.remote_outdir}/train_stdout.log")
        return (f"mkdir -p {outdir} && cd ~ && "
                f"nohup {self.cfg.train_command} > {log} 2>&1 &")

    def run_train(self) -> None:
        """≙ run_tf (tf_ec2.py:445): same command on every worker —
        jax.distributed discovers the slice topology; no role/host
        templating exists."""
        self.runner.run(self._ssh(self._launch_command()), verb="run")

    def kill_all(self, worker: str = "all") -> None:
        """≙ kill_all_python / kill_python (tf_ec2.py:617-649)."""
        self.runner.run(self._ssh("pkill -9 -f python || true", worker=worker),
                        check=False, verb="kill")

    def restart_worker(self, k: int) -> None:
        """Kill + relaunch the train command on ONE worker host (the
        supervisor's recovery verb over SSH)."""
        self.kill_all(worker=str(k))
        self.runner.run(self._ssh(self._launch_command(), worker=str(k)),
                        verb="run")

    def exec_all(self, command: str, worker: str = "all") -> None:
        """≙ run_command (tf_ec2.py:841)."""
        self.runner.run(self._ssh(command, worker=worker), verb="exec")

    def download(self, local_dir: str | Path, remote_path: str | None = None,
                 worker: str = "0") -> None:
        """≙ download_outdir / download_file (tf_ec2.py:651-742)."""
        remote = remote_path or self.cfg.remote_outdir
        local_dir = Path(local_dir)
        local_dir.mkdir(parents=True, exist_ok=True)
        # scp's positional is <name>:<path>, not a bare name, so the
        # _base helper doesn't apply
        argv = ["gcloud", "compute", "tpus", "tpu-vm", "scp",
                "--zone", self.cfg.zone]
        if self.cfg.project:
            argv += ["--project", self.cfg.project]
        argv += ["--worker", worker, "--recurse",
                 f"{self.cfg.name}:{remote}", str(local_dir)]
        self.runner.run(argv, verb="download")

    def poll(self) -> dict[str, Any] | None:
        """Tail worker 0's ``train_log.jsonl`` (every host logs the same
        replicated metrics) and parse the newest record. ≙ the
        reference's master-log poll (tools/benchmark.py:24-34), against
        the structured log instead of a regex over freeform text."""
        log = shlex.quote(f"{self.cfg.remote_outdir}/train_log.jsonl")
        # -n 3, not 1: a torn final line must leave an intact record in
        # the window for parse_poll_output's backward scan
        out = self.runner.run(
            self._ssh(f"tail -n 3 {log} 2>/dev/null || true", worker="0"),
            capture=True, check=False, verb="poll")
        if out is None:
            return None
        return parse_poll_output(out.stdout)


# ---------------------------------------------------------------------------
# local process-cluster backend
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class LocalClusterConfig:
    """Declarative local cluster description (the LocalProcessCluster
    counterpart of ``PodConfig`` — same safe-JSON shape)."""

    name: str = "dmt-local"
    num_workers: int = 2
    workdir: str = "/tmp/dmt_local_cluster"
    setup_command: str = ""
    # runs with cwd = the worker's logdir; `train.train_dir=.` makes the
    # structured log land where status/poll/download expect it
    train_command: str = (
        "python -m distributedmnist_tpu.launch train "
        "train.train_dir=. data.dataset=synthetic data.batch_size=32 "
        "data.synthetic_train_size=256 data.synthetic_test_size=64 "
        "model.compute_dtype=float32 train.max_steps=50 "
        "train.log_every_steps=5 train.save_interval_steps=0")
    # Per-worker payload overrides keyed by STRING worker index (JSON
    # object keys): a mixed cluster — e.g. the serving topology, where
    # worker 0 is the checkpoint PUBLISHER (`launch train`) and
    # workers 1..N are serving replicas (`launch serve` following
    # ../worker0) — under one roster, one supervisor, one fault plan.
    # Workers not named here run train_command. Restarts respawn the
    # worker's OWN command; grown (reconfigure) workers get the
    # default.
    worker_commands: dict[str, str] = dataclasses.field(
        default_factory=dict)
    # Warm standbys (ROADMAP item 5): the command a PRE-BOOTED spare
    # process runs — it must honor the DMT_STANDBY_ACTIVATION protocol
    # (boot, precompile, touch <activation>.ready, park until the
    # activation file appears, then adopt the assigned logdir). "" =
    # train_command, which `launch train` realizes natively.
    standby_command: str = ""
    # One SHARED persistent compile cache threaded into every worker's
    # env (DMT_COMPILE_CACHE_DIR): a restarted worker hits warm
    # compiles from its predecessor's run instead of paying the full
    # XLA compile again. "" = <root>/compile_cache; disable with
    # compile_cache=false. An explicit DMT_COMPILE_CACHE_DIR in
    # cfg.env still wins.
    compile_cache: bool = True
    compile_cache_dir: str = ""
    env: dict[str, str] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_file(cls, path: str | Path) -> "LocalClusterConfig":
        d = json.loads(Path(path).read_text())
        unknown = set(d) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise ClusterError(f"unknown cluster config keys: "
                               f"{sorted(unknown)}")
        return cls(**d)

    @property
    def root(self) -> Path:
        return Path(self.workdir) / self.name

    def worker_dir(self, k: int) -> Path:
        return self.root / f"worker{k}"

    def standby_dir(self, j: int) -> Path:
        return self.root / f"standby{j}"

    def resolved_compile_cache_dir(self) -> Path | None:
        if not self.compile_cache:
            return None
        return (Path(self.compile_cache_dir) if self.compile_cache_dir
                else self.root / "compile_cache")

    def resolved_standby_command(self) -> str:
        return self.standby_command or self.train_command

    def command_for(self, k: int) -> str:
        return self.worker_commands.get(str(k), self.train_command)


class LocalProcessCluster(ClusterBackend):
    """The same lifecycle as real local subprocesses.

    Each worker is an actual detached OS process running
    ``cfg.train_command`` under ``JAX_PLATFORMS=cpu`` with cwd = its
    own logdir; every other verb (pgrep-equivalent status probe, tail
    poll, cp -r download, kill delete) executes as a real subprocess
    through the :class:`CommandExecutor`, so the fault plan and the
    command journal apply uniformly. The mock-free test realization of
    the backend contract — and a usable N-process trainer on any box.
    """

    def __init__(self, cfg: LocalClusterConfig,
                 executor: CommandExecutor | None = None):
        self.cfg = cfg
        self.exec = executor or CommandExecutor(
            journal=self.cfg.root / "command_journal.jsonl",
            retry=RetryPolicy(max_attempts=1))
        self._fault_fired: set[tuple[str, int]] = set()

    # -- state file -----------------------------------------------------

    @property
    def state_path(self) -> Path:
        return self.cfg.root / "state.json"

    def _read_state(self) -> dict[str, Any]:
        if self.exec.dry_run:
            # dry-run writes no state file; synthesize the worker list
            # from the config so every verb still records its argv
            return {"phase": "dry-run",
                    "workers": [{"worker": k, "pid": None,
                                 "logdir": str(self.cfg.worker_dir(k))}
                                for k in range(self.cfg.num_workers)]}
        if not self.state_path.exists():
            return {"phase": "absent", "workers": []}
        try:
            state = json.loads(self.state_path.read_text())
        except (json.JSONDecodeError, OSError) as e:
            # a state file garbled by a killed previous run must not
            # wedge every verb behind manual cleanup — treat it as
            # absent (create() rebuilds it) and leave the evidence in
            # the journal
            logger.warning("state file %s unreadable (%s) — treating the "
                           "cluster as absent", self.state_path, e)
            self.exec.journal({"event": "lifecycle", "action": "stale_state",
                               "cluster": self.cfg.name, "error": str(e)})
            return {"phase": "absent", "workers": []}
        if not isinstance(state.get("workers"), list):
            state["workers"] = []
        return state

    def _write_state(self, state: dict[str, Any]) -> None:
        if self.exec.dry_run:
            return  # dry-run records argv only — no on-disk mutation
        self.state_path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self.state_path.with_suffix(".tmp")
        tmp.write_text(json.dumps(state, indent=2))
        tmp.replace(self.state_path)

    # -- lifecycle ------------------------------------------------------

    def create(self) -> None:
        dirs = " ".join(shlex.quote(str(self.cfg.worker_dir(k)))
                        for k in range(self.cfg.num_workers))
        self.exec.run(["sh", "-c", f"mkdir -p {dirs}"], verb="create")
        self._write_state({"phase": "created",
                           "workers": [{"worker": k, "pid": None,
                                        "logdir": str(self.cfg.worker_dir(k))}
                                       for k in range(self.cfg.num_workers)]})
        if self.cfg.setup_command:
            self.exec_all(self.cfg.setup_command)

    def delete(self, ignore_missing: bool = False) -> None:
        """Kill every worker, mark the cluster deleted. Logdirs are
        retained (≙ the reference's shutdown, which terminated instances
        but kept the NFS outdir) — a caller wanting a clean slate
        removes ``cfg.root``."""
        self.kill_all()
        state = self._read_state()
        state["phase"] = "deleted"
        self._write_state(state)
        self.exec.journal({"event": "lifecycle", "action": "delete",
                           "cluster": self.cfg.name})

    def _worker_env(self, k: int) -> dict[str, str]:
        # a parent that forced a virtual device mesh (tests) must not
        # leak it into the workers — they boot the real 1-device CPU
        # platform
        from ..core.mesh import strip_forced_platform_env
        env = strip_forced_platform_env(dict(os.environ))
        env["JAX_PLATFORMS"] = "cpu"
        # workers run with cwd = their logdir, so the default
        # `python -m distributedmnist_tpu...` train command can only
        # resolve this package if its repo root is importable — put it
        # first on PYTHONPATH (a pip-installed copy is unaffected;
        # cfg.env below still overrides)
        repo_root = str(Path(__file__).resolve().parents[2])
        env["PYTHONPATH"] = os.pathsep.join(
            [repo_root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH")
                           else []))
        cache = self.cfg.resolved_compile_cache_dir()
        if cache is not None:
            # the shared warm-compile seam: every worker (and standby)
            # of this cluster reads/writes ONE persistent compile cache
            env["DMT_COMPILE_CACHE_DIR"] = str(cache)
        else:
            # compile_cache=false must mean COLD: an inherited ambient
            # cache dir (the bench's cold arm runs in the same shell
            # that exported it) would silently warm every "cold"
            # worker. jax reads its own env var at import, with no
            # enable_persistent_cache call needed, so it must go too.
            env.pop("DMT_COMPILE_CACHE_DIR", None)
            env.pop("JAX_COMPILATION_CACHE_DIR", None)
        env.update(self.cfg.env)
        env.update({"DMT_WORKER_INDEX": str(k),
                    "DMT_NUM_WORKERS": str(self.cfg.num_workers),
                    "DMT_WORKER_DIR": str(self.cfg.worker_dir(k))})
        # Disk-fault scripts arm INSIDE the worker's own durable-write
        # path (train/storage.py reads this at first shim op); firings
        # land in the worker's storage_faults.jsonl, which the chaos
        # fired-fault count and the storage_faults invariant read.
        # Per-incarnation-safe: a restarted worker re-arms the same
        # deterministic scripts (counters reset with the process).
        scripts = self.exec.fault_plan.disk_faults.get(k)
        if scripts:
            env["DMT_DISK_FAULTS"] = json.dumps({
                "worker": k, "faults": scripts,
                "journal": str(Path(self.cfg.worker_dir(k))
                               / "storage_faults.jsonl")})
        else:
            env.pop("DMT_DISK_FAULTS", None)
        return env

    def _pid_alive(self, pid: int) -> bool:
        probe = self.exec.run(["sh", "-c", f"kill -0 {pid} 2>/dev/null"],
                              verb="status", check=False, max_attempts=1)
        return probe is not None and probe.returncode == 0

    def _spawn_worker(self, w: dict[str, Any]) -> None:
        """Spawn ONE worker process and record its pid in ``w`` (shared
        by the initial ``run_train`` fan-out and per-worker restarts)."""
        k = w["worker"]
        logdir = Path(w["logdir"])
        logdir.mkdir(parents=True, exist_ok=True)
        command = self.cfg.command_for(k)
        log_fh = open(logdir / "train_stdout.log", "ab")
        try:
            proc = subprocess.Popen(
                ["sh", "-c", command],
                cwd=logdir, env=self._worker_env(k),
                stdout=log_fh, stderr=subprocess.STDOUT,
                start_new_session=True)
        finally:
            log_fh.close()  # the child holds its own descriptor
        w["pid"] = proc.pid
        # epoch timestamp of THIS incarnation's spawn: lets consumers
        # (the chaos drain) tell "hasn't logged since its restart —
        # still booting" from "logged, then stalled" by comparing the
        # worker's train_log.jsonl mtime against it
        w["spawned_at"] = time.time()
        self.exec.journal({"event": "spawn", "worker": k, "pid": proc.pid,
                           "command": command})

    def run_train(self) -> None:
        """Spawn one REAL detached process per worker (≙ run_tf's
        nohup-per-host, tf_ec2.py:445) — stdout/stderr to the worker's
        ``train_stdout.log``, pid recorded in the cluster state.

        Pids left in the state file by a previous killed driver are
        reaped first: a re-run over a stale ``state.json`` must neither
        double-spawn against still-live old workers nor require manual
        cleanup. (The reap is a best-effort ``kill -9``; a pid recycled
        by the OS since that run is the accepted local-tool risk.)"""
        state = self._read_state()
        if not state["workers"]:
            raise ClusterError("run_train before create: no workers")
        delay_s = self.exec.fault_plan.command_delay_s("run")
        for w in state["workers"]:
            if self.exec.dry_run:  # record the spawn argv, don't Popen
                self.exec.run(["sh", "-c", self.cfg.command_for(w["worker"])],
                              verb="run")
                continue
            if w.get("pid"):
                if self._pid_alive(w["pid"]):
                    self.exec.journal(
                        {"event": "lifecycle", "action": "stale_worker_reaped",
                         "worker": w["worker"], "pid": w["pid"]})
                self._kill_pid(w["pid"], "kill")
                w["pid"] = None
            if delay_s > 0:
                time.sleep(delay_s)
            self._spawn_worker(w)
        state["phase"] = "running"
        self._write_state(state)

    def restart_worker(self, k: int) -> None:
        """Respawn ONE worker in place (the supervisor's recovery verb):
        best-effort kill of any previous pid, then a fresh spawn of the
        same train command in the same logdir — the worker's own
        resume-from-checkpoint logic decides where it continues."""
        state = self._read_state()
        sel = self._select(state["workers"], str(k))
        if not sel:
            raise ClusterError(f"restart_worker({k}): no such worker")
        w = sel[0]
        if self.exec.dry_run:
            self.exec.run(["sh", "-c", self.cfg.command_for(k)], verb="run")
            return
        if w.get("pid"):
            self._kill_pid(w["pid"], "kill")
        self._spawn_worker(w)
        state["phase"] = "running"
        self._write_state(state)

    def stop_all(self, worker: str = "all") -> None:
        """Graceful drain: SIGTERM the worker process groups. A
        preemption-aware payload (`launch train`,
        train.handle_preemption) finishes its step, flushes a
        checkpoint, and exits resumable — the checkpoint-flush half of
        an elastic reconfigure. Callers bound the wait with
        :meth:`wait_drained` and fall back to :meth:`kill_all` for
        stragglers."""
        state = self._read_state()
        for w in self._select(state["workers"], worker):
            if w.get("pid"):
                pid = w["pid"]
                self.exec.run(
                    ["sh", "-c", f"kill -TERM -{pid} 2>/dev/null || "
                                 f"kill -TERM {pid} 2>/dev/null || true"],
                    verb="stop", check=False)

    def _group_live_count(self, pid: int) -> int:
        """Non-zombie processes still in ``pid``'s process group. The
        recorded pid is the ``sh -c`` LEADER (start_new_session=True
        makes it the pgid) and dash FORKS the payload: on a group
        SIGTERM the leader dies instantly while the python trainer is
        still flushing its preemption checkpoint — ``kill -0 <leader>``
        reads "drained" mid-flush and the straggler SIGKILL would land
        on the half-written save. Group membership is the truth a drain
        must wait on (zombies excluded: an exited-but-unreaped leader
        is not still flushing anything)."""
        probe = self.exec.run(
            ["sh", "-c", f"ps -eo pgid=,stat= | "
                         f"awk '$1 == {pid} && $2 !~ /Z/' | wc -l"],
            verb="status", check=False, max_attempts=1)
        if probe is None or probe.returncode != 0:
            return 0
        try:
            return int((probe.stdout or "").strip())
        except ValueError:
            return 0

    def wait_drained(self, timeout_s: float,
                     poll_secs: float = 0.5) -> bool:
        """Block until every worker's process GROUP has fully exited —
        leader AND all forked descendants — or the deadline passes.
        Returns True when fully drained. This is what makes
        ``stop_all`` → straggler-kill safe: only a group that kept
        members past the deadline eats the SIGKILL."""
        state = self._read_state()
        pids = [w["pid"] for w in state["workers"] if w.get("pid")]
        deadline = time.monotonic() + timeout_s
        while True:
            pids = [p for p in pids if self._group_live_count(p) > 0]
            if not pids:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_secs)

    # -- elastic world-size reconfiguration (ROADMAP item 2) ------------

    def reconfigure(self, new_num_workers: int,
                    survivors: list[int] | None = None) -> dict[str, Any]:
        """Reshape the roster WITHOUT spawning: shrink keeps the named
        ``survivors`` (logdirs, checkpoints, and worker ids untouched —
        ids need not stay contiguous, every verb iterates the roster),
        grow appends fresh ids whose logdirs are SEEDED with the first
        survivor's newest checkpoint artifacts so the new worker
        resumes at the last loadable step instead of step 0. The
        caller (the supervisor's :meth:`~.supervisor.ClusterSupervisor.
        reconfigure`) drains before and relaunches after; anything not
        surviving is killed here. Journaled as an
        ``event: "reconfigure"`` record — the causal license the
        cross-world resume invariant requires — and returned."""
        if new_num_workers < 1:
            raise ClusterError(
                f"reconfigure to {new_num_workers} workers: a cluster "
                "needs at least one")
        state = self._read_state()
        workers = state["workers"]
        if not workers:
            raise ClusterError("reconfigure before create: no workers")
        old_ids = [w["worker"] for w in workers]
        if survivors is None:
            survivors = old_ids[:new_num_workers]
        keep_set = set(survivors)
        unknown = keep_set - set(old_ids)
        if unknown:
            raise ClusterError(f"reconfigure: unknown survivor ids "
                               f"{sorted(unknown)} (roster: {old_ids})")
        if len(keep_set) > new_num_workers:
            raise ClusterError(
                f"reconfigure: {len(keep_set)} survivors > new world "
                f"{new_num_workers}")
        keep = [w for w in workers if w["worker"] in keep_set]
        dropped = [w for w in workers if w["worker"] not in keep_set]
        for w in dropped:  # nothing outside the new world may keep running
            if w.get("pid"):
                self._kill_pid(w["pid"], "kill")
            w["pid"] = None
        grown: dict[int, int] = {}
        next_id = (max(old_ids) + 1) if old_ids else 0
        seed_from = keep[0]["worker"] if keep else None
        while len(keep) < new_num_workers:
            k = next_id
            next_id += 1
            logdir = self.cfg.worker_dir(k)
            nw = {"worker": k, "pid": None, "logdir": str(logdir)}
            if not self.exec.dry_run:
                logdir.mkdir(parents=True, exist_ok=True)
            if seed_from is not None:
                # seed the grown worker's resume point: the survivor's
                # NEWEST checkpoint artifacts (resolved via the
                # checkpoint.json pointer — copying every retained
                # cadence save would multiply disk per grown worker
                # and leave stale steps as silent fallback candidates);
                # payloads without a pointer (the shell loops' bare
                # `ckpt` file) fall back to the glob
                src = next(w2["logdir"] for w2 in keep
                           if w2["worker"] == seed_from)
                pattern = "ckpt*"
                try:
                    ptr = json.loads(
                        (Path(src) / "checkpoint.json").read_text())
                    step = int(ptr["latest_step"])
                    pattern = f"ckpt-{step:08d}*"
                except (OSError, ValueError, KeyError, TypeError):
                    pass
                self.exec.run(
                    ["sh", "-c",
                     f"cp -p {shlex.quote(src)}/{pattern} "
                     f"{shlex.quote(str(logdir))}/ 2>/dev/null; "
                     f"cp -p {shlex.quote(src)}/checkpoint.json "
                     f"{shlex.quote(str(logdir))}/ 2>/dev/null; true"],
                    verb="reconfigure", check=False)
                grown[k] = seed_from
            keep.append(nw)
        state["workers"] = keep
        self.cfg = dataclasses.replace(self.cfg,
                                       num_workers=new_num_workers)
        self._write_state(state)
        rec = {"event": "reconfigure", "layer": "cluster",
               "action": "reshape",
               "old_world": len(old_ids), "new_world": new_num_workers,
               "old_workers": old_ids,
               "workers": [w["worker"] for w in keep],
               "dropped": [w["worker"] for w in dropped],
               "grown": {str(k): v for k, v in grown.items()}}
        self.exec.journal(rec)
        logger.info("reconfigured cluster %s: %d -> %d workers "
                    "(dropped %s, grown %s)", self.cfg.name, len(old_ids),
                    new_num_workers, rec["dropped"], sorted(grown))
        return rec

    # -- warm standbys (ROADMAP item 5) ---------------------------------

    def _spawn_standby(self, state: dict[str, Any]) -> dict[str, Any]:
        """Spawn ONE pre-booting spare process: it runs the standby
        command with ``DMT_STANDBY_ACTIVATION`` pointing at its own
        activation file, boots jax, precompiles, touches
        ``<activation>.ready`` and parks. Returns the standby record
        (appended to ``state["standbys"]``); the caller writes state."""
        slots = state.setdefault("standbys", [])
        # monotonic id from a state-level sequence — NOT max(live slots):
        # a back-fill after a promotion must never reuse a consumed
        # standby's dir, where a stale activation file would instantly
        # (and wrongly) activate the fresh spare onto the old assignment
        j = state.get("standby_seq", 0)
        state["standby_seq"] = j + 1
        sdir = self.cfg.standby_dir(j)
        sdir.mkdir(parents=True, exist_ok=True)
        activation = sdir / "activate.json"
        # stale protocol files from a previous cluster incarnation in
        # the same workdir would likewise fire the protocol early
        activation.unlink(missing_ok=True)
        Path(str(activation) + ".ready").unlink(missing_ok=True)
        env = self._worker_env(0)
        env.pop("DMT_WORKER_INDEX", None)
        env.pop("DMT_WORKER_DIR", None)
        env["DMT_STANDBY_ACTIVATION"] = str(activation)
        log_fh = open(sdir / "standby_stdout.log", "ab")
        try:
            proc = subprocess.Popen(
                ["sh", "-c", self.cfg.resolved_standby_command()],
                cwd=sdir, env=env, stdout=log_fh,
                stderr=subprocess.STDOUT, start_new_session=True)
        finally:
            log_fh.close()
        sb = {"standby": j, "pid": proc.pid, "dir": str(sdir),
              "activation": str(activation), "spawned_at": time.time()}
        slots.append(sb)
        self.exec.journal({"event": "spawn", "standby": j, "pid": proc.pid,
                           "command": self.cfg.resolved_standby_command()})
        return sb

    def _standby_ready(self, sb: dict[str, Any]) -> bool:
        """Parked and promotable: the process signalled ready (it has
        imported jax, built its trainer, precompiled) and is alive."""
        marker = Path(sb["activation"] + ".ready")
        return (marker.exists() and bool(sb.get("pid"))
                and self._pid_alive(sb["pid"]))

    def ensure_standbys(self, n: int) -> None:
        """Top the warm-standby pool up to ``n`` live spares. Spawning
        is async (the spare boots in the background); only a spare that
        reached its ready marker is promotable."""
        state = self._read_state()
        if not state["workers"]:
            raise ClusterError("ensure_standbys before create: no workers")
        if self.exec.dry_run:
            for _ in range(n):
                self.exec.run(["sh", "-c",
                               self.cfg.resolved_standby_command()],
                              verb="run")
            return
        slots = state.setdefault("standbys", [])
        dead = [sb for sb in slots
                if not (sb.get("pid") and self._pid_alive(sb["pid"]))]
        for sb in dead:
            slots.remove(sb)
            self.exec.journal({"event": "lifecycle",
                               "action": "standby_reaped",
                               "standby": sb["standby"], "pid": sb.get("pid")})
        for _ in range(max(0, n - len(slots))):
            self._spawn_standby(state)
        self._write_state(state)

    def promote_standby(self, k: int) -> bool:
        """Hand worker ``k``'s identity to a READY standby: kill any
        previous incarnation, write the activation file (atomically, so
        the parked process never reads a torn assignment), and record
        the standby's pid as the worker's. Returns False — caller falls
        back to a cold ``restart_worker`` — when no standby is ready.

        The worker's ``spawned_at`` is stamped with the PROMOTION time:
        per-incarnation clocks (the chaos drain's stall parking) must
        measure from when this process took over the logdir, not from
        when the spare originally booted — its old log silence was
        parking, not stalling."""
        if self.exec.dry_run:
            return False
        if str(k) in self.cfg.worker_commands:
            # mixed roster: this slot runs an OVERRIDDEN payload (e.g.
            # a serving replica in a publisher+replicas cluster), but
            # the parked spare runs the standby/default payload —
            # promoting it would silently swap the worker's role.
            # Cold respawn of the worker's OWN command is the correct
            # recovery. (The standby command legitimately differs from
            # train_command — it is the parked-protocol variant of the
            # DEFAULT payload, which is exactly what overridden slots
            # are not.)
            return False
        state = self._read_state()
        sel = self._select(state["workers"], str(k))
        if not sel:
            raise ClusterError(f"promote_standby({k}): no such worker")
        w = sel[0]
        ready = [sb for sb in state.get("standbys", [])
                 if self._standby_ready(sb)]
        if not ready:
            return False
        sb = ready[0]
        if w.get("pid"):
            self._kill_pid(w["pid"], "kill")
        activation = Path(sb["activation"])
        tmp = activation.with_suffix(".tmp")
        tmp.write_text(json.dumps({"train_dir": w["logdir"], "worker": k}))
        tmp.replace(activation)
        state["standbys"].remove(sb)
        w["pid"] = sb["pid"]
        w["spawned_at"] = time.time()
        w["promoted_from_standby"] = sb["standby"]
        state["phase"] = "running"
        # The activation file above is the commit point: the parked
        # process is already adopting worker k's logdir, so EVERYTHING
        # below is best-effort — an exception escaping here reads as
        # promoted=False to the supervisor, which would cold-respawn a
        # second trainer into the train_dir the live standby now owns.
        try:
            self._write_state(state)
            self.exec.journal({"event": "lifecycle",
                               "action": "promote_standby",
                               "worker": k, "standby": sb["standby"],
                               "pid": sb["pid"]})
        except Exception as e:
            logger.warning("promotion bookkeeping failed (%s: %s) — "
                           "promotion stands", type(e).__name__, e)
        # back-fill asynchronously: the pool heals while the promoted
        # process is already training; a failed spawn (fork/fd
        # pressure) must not unwind the promotion either.
        try:
            self._spawn_standby(state)
            self._write_state(state)
        except Exception as e:
            logger.warning("standby back-fill failed (%s) — pool not "
                           "replenished", e)
            try:
                self.exec.journal({"event": "lifecycle",
                                   "action": "standby_backfill_failed",
                                   "error": str(e)})
            except Exception:
                pass
        return True

    def measured_boot_s(self) -> float | None:
        """Observed spawn→first-log-record latency (max over workers
        whose first intact record postdates their recorded spawn) —
        what adaptive stall timeouts derive from instead of the
        hardcoded worst case. None when nothing measurable yet (no
        logs, records without timestamps, or logs predating the
        current incarnation)."""
        state = self._read_state()
        out: list[float] = []
        for w in state["workers"]:
            spawned = w.get("spawned_at")
            if not spawned:
                continue
            log = Path(w["logdir"]) / "train_log.jsonl"
            try:
                with open(log) as fh:
                    for line in fh:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        t = rec.get("time")
                        if isinstance(t, (int, float)) and t >= spawned:
                            out.append(t - spawned)
                        break  # first intact record decides
            except OSError:
                continue
        return max(out) if out else None

    def _select(self, workers: list[dict], worker: str) -> list[dict]:
        if worker == "all":
            return workers
        return [w for w in workers if w["worker"] == int(worker)]

    def _kill_pid(self, pid: int, verb: str) -> None:
        # the recorded pid is a session/process-group leader
        # (start_new_session=True) and `sh -c` FORKS the payload rather
        # than exec it — killing only the shell would orphan the real
        # worker, which then survives to keep training and writing.
        # Signal the whole group (negative pid), falling back to the
        # bare pid for processes that predate the group convention.
        self.exec.run(["sh", "-c", f"kill -9 -{pid} 2>/dev/null || "
                                   f"kill -9 {pid} 2>/dev/null || true"],
                      verb=verb, check=False)

    def kill_all(self, worker: str = "all") -> None:
        state = self._read_state()
        for w in self._select(state["workers"], worker):
            if w.get("pid"):
                self._kill_pid(w["pid"], "kill")
        if worker == "all":
            # parked spares die with the cluster — a standby that
            # outlives its run would hold jax memory forever
            for sb in state.get("standbys", []):
                if sb.get("pid"):
                    self._kill_pid(sb["pid"], "kill")

    def status(self) -> dict[str, Any] | None:
        """pgrep-equivalent liveness per worker — a REAL ``kill -0``
        subprocess per pid (≙ the idle probe the gcloud backend sends
        over SSH), so a worker killed mid-run surfaces as
        ``alive: False`` here."""
        if self.exec.dry_run:
            return None  # the backend contract's dry-run sentinel; the
            # liveness probes need real pids, so there is no argv to record
        state = self._read_state()
        workers = []
        for w in state["workers"]:
            # max_attempts=1 in the probe: a dead pid is not transient —
            # a retrying executor must not burn its budget observing it
            alive = bool(w.get("pid")) and self._pid_alive(w["pid"])
            workers.append({"worker": w["worker"], "pid": w.get("pid"),
                            "alive": alive, "logdir": w["logdir"],
                            "spawned_at": w.get("spawned_at")})
        standbys = [{"standby": sb["standby"], "pid": sb.get("pid"),
                     "alive": (bool(sb.get("pid"))
                               and self._pid_alive(sb["pid"])),
                     "ready": self._standby_ready(sb)}
                    for sb in state.get("standbys", [])]
        got = {"state": state["phase"].upper(),
               "workers": workers,
               "idle": not any(w["alive"] for w in workers)}
        if standbys:
            got["standbys"] = standbys
        return got

    def exec_all(self, command: str, worker: str = "all") -> None:
        state = self._read_state()
        for w in self._select(state["workers"], worker):
            self.exec.run(["sh", "-c", command], verb="exec",
                          cwd=w["logdir"], env=self._worker_env(w["worker"]))

    def download(self, local_dir: str | Path, remote_path: str | None = None,
                 worker: str = "0") -> None:
        """File-copy "download" of a worker's logdir — a real ``cp -r``
        subprocess (≙ the scp download path, tf_ec2.py:651-742)."""
        state = self._read_state()
        local_dir = Path(local_dir)
        local_dir.mkdir(parents=True, exist_ok=True)
        for w in self._select(state["workers"], worker):
            src = remote_path or w["logdir"]
            self.exec.run(["cp", "-r", str(src), str(local_dir)],
                          verb="download")

    def worker_progress(self) -> dict[int, int]:
        """Per-worker latest logged step ({worker: step}; -1 when a
        worker hasn't logged yet) — one real ``tail`` per worker. This
        is the stall-detection signal: a SIGSTOPped or wedged worker
        stays ``alive`` under the pid probe while its log stops moving,
        so liveness alone cannot see a hang."""
        state = self._read_state()
        out: dict[int, int] = {}
        for w in state["workers"]:
            log = Path(w["logdir"]) / "train_log.jsonl"
            res = self.exec.run(
                ["sh", "-c", f"tail -n 3 {shlex.quote(str(log))} "
                             f"2>/dev/null || true"],
                verb="progress", check=False, max_attempts=1)
            if res is None:  # dry-run
                continue
            out[w["worker"]] = parse_poll_output(res.stdout)["step"]
        return out

    def _latest_checkpoint_artifact(self, logdir: Path) -> Path | None:
        """The file a torn-write fault should hit: the pointer's
        latest_path when readable, else the newest ``ckpt-*`` data
        file."""
        try:
            d = json.loads((logdir / "checkpoint.json").read_text())
            target = logdir / d["latest_path"]
            if target.exists():
                return target
        except (OSError, json.JSONDecodeError, KeyError, TypeError):
            pass
        cands = [p for p in logdir.glob("ckpt-*")
                 if not p.name.endswith((".tmp", ".sha256"))]
        return max(cands, key=lambda p: p.name) if cands else None

    def _apply_poll_faults(self, state: dict[str, Any]
                           ) -> dict[int, int] | None:
        """Fire the step-triggered fault actions (each at most once per
        worker): kill → hang → corrupt-latest-checkpoint. Returns the
        worker-progress sweep it ran (None when no trigger was left to
        fire) so poll() can share it instead of re-spawning N tails.

        Worker-keyed triggers fire on the TARGET worker's own logged
        step, not worker 0's: worker boots skew by tens of seconds (a
        second jax process on a contended host), so "kill worker k at
        step s" keyed to another worker's log could fire while k is
        still booting — before it has done the work (e.g. saved the
        checkpoint a corrupt action wants to tear) the scenario is
        about."""
        plan = self.exec.fault_plan
        unfired = [(kind, mapping)
                   for kind, mapping in
                   (("kill", plan.kill_worker_at_step),
                    ("hang", plan.hang_worker_at_step),
                    ("corrupt", plan.corrupt_latest_checkpoint_at_step),
                    ("stall", plan.stall_worker_for_ms_at_step))
                   if any((kind, k) not in self._fault_fired
                          for k in mapping)]
        if not unfired:
            return None  # every trigger already fired — no tails
        prog = self.worker_progress()
        for k, s in plan.kill_worker_at_step.items():
            if prog.get(k, -1) >= s and ("kill", k) not in self._fault_fired:
                self._fault_fired.add(("kill", k))
                for w in self._select(state["workers"], str(k)):
                    if w.get("pid"):
                        self._kill_pid(w["pid"], "fault")
                        self.exec.journal(
                            {"event": "fault", "action": "kill_worker",
                             "worker": k, "pid": w["pid"],
                             "at_step": prog[k], "planned_step": s})
        for k, s in plan.hang_worker_at_step.items():
            if prog.get(k, -1) >= s and ("hang", k) not in self._fault_fired:
                self._fault_fired.add(("hang", k))
                for w in self._select(state["workers"], str(k)):
                    if w.get("pid"):
                        # stop the whole group: the payload is the
                        # shell's CHILD (see _kill_pid)
                        self.exec.run(
                            ["sh", "-c", f"kill -STOP -{w['pid']} "
                                         f"2>/dev/null || "
                                         f"kill -STOP {w['pid']} "
                                         f"2>/dev/null || true"],
                            verb="fault", check=False)
                        self.exec.journal(
                            {"event": "fault", "action": "hang_worker",
                             "worker": k, "pid": w["pid"],
                             "at_step": prog[k], "planned_step": s})
        for k, (s, ms) in plan.stall_worker_for_ms_at_step.items():
            if prog.get(k, -1) >= s and ("stall", k) not in self._fault_fired:
                self._fault_fired.add(("stall", k))
                for w in self._select(state["workers"], str(k)):
                    if not w.get("pid"):
                        continue
                    pid = w["pid"]
                    # STOP the whole group now; a detached subshell
                    # CONTs it after the stall window — the resume must
                    # not depend on the driver still polling (the
                    # whole point is a straggler that recovers on its
                    # OWN, racing any supervisor restart decision)
                    secs = ms / 1e3
                    self.exec.run(
                        ["sh", "-c",
                         f"kill -STOP -{pid} 2>/dev/null || "
                         f"kill -STOP {pid} 2>/dev/null; "
                         f"( sleep {secs}; "
                         f"kill -CONT -{pid} 2>/dev/null || "
                         f"kill -CONT {pid} 2>/dev/null ) "
                         f">/dev/null 2>&1 &"],
                        verb="fault", check=False)
                    self.exec.journal(
                        {"event": "fault", "action": "stall_worker",
                         "worker": k, "pid": pid, "stall_ms": ms,
                         "at_step": prog[k], "planned_step": s})
        for k, s in plan.corrupt_latest_checkpoint_at_step.items():
            if (prog.get(k, -1) >= s
                    and ("corrupt", k) not in self._fault_fired):
                self._fault_fired.add(("corrupt", k))
                for w in self._select(state["workers"], str(k)):
                    target = self._latest_checkpoint_artifact(
                        Path(w["logdir"]))
                    if target is None:
                        self.exec.journal(
                            {"event": "fault",
                             "action": "corrupt_latest_checkpoint",
                             "worker": k, "target": None,
                             "at_step": prog[k], "planned_step": s})
                        continue
                    targets = [target]
                    if target.name.endswith(".msgpack") and \
                            not target.name.endswith(".quant.msgpack"):
                        # the publish-time quantization pass writes a
                        # .quant sidecar next to the artifact — tear it
                        # TOO, so a serving replica on a quantized
                        # precision tier exercises the SIDECAR's digest
                        # refusal, not just the checkpoint's
                        quant = target.with_name(
                            target.name[:-len(".msgpack")]
                            + ".quant.msgpack")
                        if quant.exists():
                            targets.append(quant)
                    for tgt in targets:
                        keep = max(1, tgt.stat().st_size // 2)
                        self.exec.run(["truncate", "-s", str(keep),
                                       str(tgt)], verb="fault",
                                      check=False)
                        self.exec.journal(
                            {"event": "fault",
                             "action": "corrupt_latest_checkpoint",
                             "worker": k, "target": tgt.name,
                             "truncated_to": keep,
                             "at_step": prog[k], "planned_step": s})

    def poll(self) -> dict[str, Any] | None:
        """Tail worker 0's ``train_log.jsonl`` via a real subprocess;
        additionally the seam where the fault plan's step-triggered
        actions fire (the poll cadence is when the driver looks at the
        cluster — exactly when a lost worker becomes observable)."""
        state = self._read_state()
        if not state["workers"]:
            return {"step": -1, "record": None}
        log = Path(state["workers"][0]["logdir"]) / "train_log.jsonl"
        out = self.exec.run(
            ["sh", "-c", f"tail -n 3 {shlex.quote(str(log))} "
                         f"2>/dev/null || true"],
            verb="poll", check=False)
        if out is None:  # dry-run: tail argv recorded above
            return None
        got = parse_poll_output(out.stdout)
        if state["phase"] == "running":
            st = self.status()
            got["workers_alive"] = sum(w["alive"] for w in st["workers"])
            # the full per-worker snapshot rides along so a supervisor
            # polling every tick doesn't re-run N liveness probes it
            # already paid for here
            got["workers"] = st["workers"]
        prog = self._apply_poll_faults(state)
        if prog is not None:
            # share the fault hook's progress sweep with callers (the
            # supervisor) instead of letting them re-spawn N tails
            got["worker_progress"] = prog
        return got


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def make_backend(backend: str, config: str | None,
                 executor: CommandExecutor) -> ClusterBackend:
    """Backend factory — the pluggability seam the CLI and tests use."""
    if backend == "local":
        cfg = (LocalClusterConfig.from_file(config) if config
               else LocalClusterConfig())
        return LocalProcessCluster(cfg, executor)
    if backend == "gcloud":
        from .pod import PodConfig
        cfg = PodConfig.from_file(config) if config else PodConfig()
        return GcloudTpuBackend(cfg, executor)
    raise ClusterError(f"unknown backend {backend!r} "
                       "(choices: local, gcloud)")


def main(argv: list[str] | None = None) -> None:
    import argparse

    p = argparse.ArgumentParser(prog="distributedmnist_tpu.launch cluster")
    p.add_argument("action",
                   choices=["create", "delete", "status", "run", "kill-all",
                            "exec", "download", "poll", "supervise",
                            "reconfigure", "broker", "chaos"])
    p.add_argument("--backend", default="local", choices=["local", "gcloud"])
    p.add_argument("--config", default=None,
                   help="LocalClusterConfig / PodConfig JSON")
    p.add_argument("--fault-plan", default=None, help="FaultPlan JSON")
    p.add_argument("--journal", default=None,
                   help="command journal JSONL path (local backend "
                        "defaults to <workdir>/command_journal.jsonl)")
    p.add_argument("--dry-run", action="store_true",
                   help="record commands instead of executing")
    p.add_argument("--command", default=None, help="for exec")
    p.add_argument("--worker", default=None, help="worker index or 'all'")
    p.add_argument("--local-dir", default="./cluster_results",
                   help="for download")
    p.add_argument("--remote-path", default=None, help="for download")
    p.add_argument("--timeout-s", type=float, default=None,
                   help="per-command timeout")
    p.add_argument("--max-attempts", type=int, default=1,
                   help="retry budget for transient command failures")
    p.add_argument("--until-step", type=int, default=None, metavar="N",
                   help="for run/poll/supervise: follow train_log.jsonl and "
                        "return at step N (run/supervise also stop the "
                        "cluster)")
    # None → 5.0 for run/poll/supervise; chaos resolves a per-payload
    # default instead (0.2 shell / 1.0 train), so only an EXPLICIT
    # flag may override it
    p.add_argument("--poll-secs", type=float, default=None)
    p.add_argument("--poll-timeout-s", type=float, default=24 * 3600.0)
    p.add_argument("--supervisor-config", default=None,
                   help="for supervise: SupervisorConfig JSON (quorum, "
                        "restart budget/backoff, stall timeout); flags "
                        "below override it")
    p.add_argument("--quorum", type=int, default=None,
                   help="for supervise: min live workers to continue")
    p.add_argument("--max-restarts", type=int, default=None,
                   help="for supervise: restart budget per worker")
    p.add_argument("--restart-backoff-s", type=float, default=None,
                   help="for supervise: base restart backoff")
    p.add_argument("--stall-timeout-s", type=float, default=None,
                   help="for supervise: hang detection window (0 = off)")
    p.add_argument("--standby-workers", type=int, default=None,
                   help="for supervise/chaos: keep N pre-booted, "
                        "precompiled standby processes parked; a due "
                        "restart promotes one instead of cold-starting")
    p.add_argument("--elastic", action="store_true", default=None,
                   help="for supervise: below quorum with every restart "
                        "budget exhausted, SHRINK the world to the "
                        "survivors (drain → checkpoint-flush → relaunch "
                        "smaller, quorum rescaled) instead of aborting")
    p.add_argument("--min-workers", type=int, default=None,
                   help="for supervise: smallest world elastic shrink "
                        "may produce (below it the run aborts)")
    p.add_argument("--new-workers", type=int, default=None, metavar="M",
                   help="for reconfigure: the target world size (shrink "
                        "drops the highest ids / dead workers first; "
                        "grow seeds fresh workers from a survivor's "
                        "newest checkpoint)")
    p.add_argument("--target-worker", type=int, default=None,
                   help="for supervise: count progress toward "
                        "--until-step from THIS worker's log only "
                        "(mixed-payload clusters: the publisher, not a "
                        "serving replica's request counter)")
    p.add_argument("--seed", type=int, default=None,
                   help="for supervise/chaos: schedule + retry-jitter "
                        "seed, stamped on every journaled recovery/chaos "
                        "event so an episode is replayable from the "
                        "artifact alone")
    p.add_argument("--trials", type=int, default=None,
                   help="for chaos: number of seeded fault-schedule "
                        "trials")
    p.add_argument("--payload", default=None,
                   choices=["train", "shell", "serving"],
                   help="for chaos: real `launch train` workers (all "
                        "invariants incl. bitwise determinism), the "
                        "cheap shell loop (CI smoke), or the serving "
                        "tier under fire (publisher + serve replicas + "
                        "closed-loop load, serving invariants checked)")
    p.add_argument("--chaos-config", default=None,
                   help="for chaos: ChaosConfig JSON (flags above "
                        "override it)")
    p.add_argument("--no-shrink", action="store_true",
                   help="for chaos: skip minimizing failing schedules")
    p.add_argument("--serve-decode", action="store_true",
                   help="for chaos (payload=serving): decode replicas "
                        "(token streaming) instead of classifiers")
    p.add_argument("--network", action="store_true",
                   help="for chaos (payload=serving, requires "
                        "--serve-decode): transport faults via per-"
                        "replica chaos proxies (launch/netchaos.py) — "
                        "mid-stream reset + partition window every "
                        "trial — instead of process faults; invariant "
                        "13 (net_faults) replays the exactly-once "
                        "books")
    p.add_argument("--disk", action="store_true",
                   help="for chaos (payload=train): storage faults via "
                        "the workers' durable-write shim "
                        "(train/storage.py) — retry-exhausting ENOSPC, "
                        "torn write, and power-cut rename paired with "
                        "a kill every trial — instead of process "
                        "faults; invariant 14 (storage_faults) replays "
                        "the crash-consistency books")
    p.add_argument("--serve-command", default=None,
                   help="for broker: the serving payload a scaled-up "
                        "replica slot runs — also how the broker "
                        "recognizes which roster slots are serving "
                        "(command equality)")
    p.add_argument("--broker-config", default=None,
                   help="for broker: BrokerConfig JSON (thresholds, "
                        "hysteresis marks, cooldown, roster bounds)")
    p.add_argument("--loadgen-journal", default=None,
                   help="for broker: the loadgen.jsonl carrying "
                        "rolling-window pressure snapshots (defaults "
                        "to <workdir>/loadgen.jsonl)")
    p.add_argument("--warm-standbys", type=int, default=0,
                   help="for broker: pre-boot N parked serving spares; "
                        "a scale-up promotes one instead of paying a "
                        "cold jax boot")
    args = p.parse_args(argv)
    poll_secs = 5.0 if args.poll_secs is None else args.poll_secs

    if args.action == "chaos":
        # the campaign owns its clusters/executors (one per trial, all
        # local, fault plans generated from the seed) — flags that
        # would silently be discarded must error instead
        for flag, val in (("--backend", args.backend != "local"),
                          ("--dry-run", args.dry_run),
                          ("--fault-plan", args.fault_plan is not None),
                          ("--config", args.config is not None),
                          ("--journal", args.journal is not None),
                          ("--timeout-s", args.timeout_s is not None)):
            if val:
                p.error(f"{flag} does not apply to chaos — campaigns run "
                        "local clusters with seed-generated fault plans "
                        "(use --chaos-config)")
        from .chaos import ChaosConfig, run_campaign
        overrides = {"trials": args.trials, "seed": args.seed,
                     "until_step": args.until_step,
                     "payload": args.payload,
                     # the supervisor policy under test — same flags as
                     # `supervise`, mapped onto the campaign config
                     "quorum": args.quorum,
                     "max_restarts": args.max_restarts,
                     "restart_backoff_s": args.restart_backoff_s,
                     "stall_timeout_s": args.stall_timeout_s,
                     "standby_workers": args.standby_workers,
                     "poll_secs": args.poll_secs}
        overrides = {k: v for k, v in overrides.items() if v is not None}
        if args.no_shrink:
            overrides["shrink"] = False
        # store_true flags: only override when SET, so a chaos-config
        # file's own values survive the merge
        if args.serve_decode:
            overrides["serve_decode"] = True
        if args.network:
            overrides["network"] = True
        if args.disk:
            overrides["disk"] = True
        # merged before construction — __post_init__ validates
        # cross-field constraints, so flags can't land via replace()
        ccfg = (ChaosConfig.from_file(args.chaos_config, overrides=overrides)
                if args.chaos_config else ChaosConfig(**overrides))
        print(json.dumps(run_campaign(ccfg), default=str))
        return

    fault = FaultPlan.from_file(args.fault_plan) if args.fault_plan else None
    journal = args.journal
    if journal is None and args.backend == "local" and not args.dry_run:
        cfg0 = (LocalClusterConfig.from_file(args.config) if args.config
                else LocalClusterConfig())
        cfg0.root.mkdir(parents=True, exist_ok=True)
        journal = cfg0.root / "command_journal.jsonl"
    executor = CommandExecutor(
        journal=journal,
        retry=RetryPolicy(max_attempts=args.max_attempts, seed=args.seed),
        timeout_s=args.timeout_s, fault_plan=fault, dry_run=args.dry_run)
    backend = make_backend(args.backend, args.config, executor)

    if args.action == "create":
        backend.create()
    elif args.action == "delete":
        backend.delete()
    elif args.action == "status":
        print(json.dumps(backend.status(), indent=2))
    elif args.action == "run":
        if args.until_step is not None:
            print(json.dumps(run_until_step(
                backend, args.until_step, poll_secs=poll_secs,
                timeout_secs=args.poll_timeout_s)))
        else:
            backend.run_train()
    elif args.action in ("supervise", "reconfigure", "broker"):
        from .supervisor import ClusterSupervisor, SupervisorConfig
        if args.action in ("supervise", "broker") \
                and args.until_step is None:
            p.error(f"{args.action} requires --until-step")
        if args.action == "broker" and not args.serve_command:
            p.error("broker requires --serve-command (the serving "
                    "payload a scaled-up slot runs)")
        if args.action == "reconfigure" and args.new_workers is None:
            p.error("reconfigure requires --new-workers")
        scfg = (SupervisorConfig.from_file(args.supervisor_config)
                if args.supervisor_config else SupervisorConfig())
        overrides = {"quorum": args.quorum,
                     "max_restarts_per_worker": args.max_restarts,
                     "restart_backoff_s": args.restart_backoff_s,
                     "stall_timeout_s": args.stall_timeout_s,
                     "standby_workers": args.standby_workers,
                     "elastic": args.elastic,
                     "min_workers": args.min_workers,
                     "seed": args.seed}
        scfg = dataclasses.replace(
            scfg, **{k: v for k, v in overrides.items() if v is not None})
        sup = ClusterSupervisor(backend, scfg)
        if args.action == "reconfigure":
            # drain → reshape → relaunch; optionally supervise the
            # resized world to a target step in the same invocation
            rec = sup.reconfigure(args.new_workers, trigger="cli")
            if args.until_step is not None:
                try:
                    got = sup.supervise_until_step(
                        args.until_step, poll_secs=poll_secs,
                        timeout_secs=args.poll_timeout_s,
                        target_worker=args.target_worker)
                finally:
                    backend.kill_all()
                print(json.dumps({"reconfigure": rec, **got}))
            else:
                print(json.dumps({"reconfigure": rec,
                                  "summary": sup.summary()}))
        elif args.action == "broker":
            # supervise + demand-driven autoscaling: the broker rides
            # the supervise loop's per-tick callback, trading roster
            # slots on journaled load pressure (every move replayable
            # via the `autoscale` invariant)
            from ..core.config import BrokerConfig
            from .broker import ResourceBroker
            bcfg = (BrokerConfig(**json.loads(
                        Path(args.broker_config).read_text()))
                    if args.broker_config else BrokerConfig())
            journal_path = (Path(args.loadgen_journal)
                            if args.loadgen_journal
                            else getattr(backend, "cfg", None)
                            and backend.cfg.root / "loadgen.jsonl")
            broker = ResourceBroker(
                sup, bcfg, serve_command=args.serve_command,
                loadgen_journal=journal_path,
                warm_standbys=args.warm_standbys)
            broker.start()
            got = sup.run_until_step(
                args.until_step, poll_secs=poll_secs,
                timeout_secs=args.poll_timeout_s,
                target_worker=args.target_worker,
                on_tick=broker.tick)
            print(json.dumps({**got, "autoscale": broker.summary()},
                             default=str))
        else:
            print(json.dumps(sup.run_until_step(
                args.until_step, poll_secs=poll_secs,
                timeout_secs=args.poll_timeout_s,
                target_worker=args.target_worker)))
    elif args.action == "poll":
        if args.until_step is not None:
            print(json.dumps(wait_until_step(
                backend, args.until_step, poll_secs=poll_secs,
                timeout_secs=args.poll_timeout_s)))
        else:
            print(json.dumps(backend.poll()))
    elif args.action == "kill-all":
        backend.kill_all(worker=args.worker or "all")
    elif args.action == "exec":
        if not args.command:
            p.error("exec requires --command")
        backend.exec_all(args.command, worker=args.worker or "all")
    elif args.action == "download":
        backend.download(args.local_dir, args.remote_path,
                         worker=args.worker or "0")
    if args.dry_run:
        print(json.dumps([shlex.join(a) for a in executor.recorded],
                         indent=2))
    executor.close()
