"""Chaos campaign engine: stop imagining fault scenarios by hand.

The source paper's regime is synchronous training that *survives* dead
and slow workers (arXiv:1604.00981), and its descendants treat replica
loss as a routine runtime event with automatic recovery
(TF-Replicator, arXiv:1902.00465; TensorFlow fault tolerance,
arXiv:1605.08695). PRs 1–3 built both halves — injection
(:class:`~.exec.FaultPlan`) and recovery (:class:`~.supervisor.
ClusterSupervisor`, checkpoint fallback, NaN rollback) — but every
scenario so far was a hand-authored test. This module searches the
fault space mechanically:

* :class:`ChaosSchedule` — a SEEDED random composition of fault
  primitives (kill / hang / transient stall / corrupt-checkpoint /
  exec delay) over workers × step windows with bounded intensity.
  Same seed ⇒ same schedule: any journaled trial is replayable from
  its seed alone.
* :class:`ChaosCampaign` — runs N trials against a real
  :class:`~.cluster.LocalProcessCluster` under a
  :class:`~.supervisor.ClusterSupervisor`, plus one fault-free
  same-seed REFERENCE run, then replays every trial's artifacts
  through ``obsv/invariants.py`` — terminal-state legality, metrics-log
  splicing, bitwise exact-resume determinism vs the reference, journal
  causality, checkpoint-dir integrity.
* **Shrinking** — a failing schedule is greedily reduced (drop faults
  while the violation persists, re-running each candidate) and the
  minimal reproducer is emitted as a plain FaultPlan JSON anyone can
  rerun with ``cluster supervise --fault-plan``.

CLI: ``python -m distributedmnist_tpu.launch cluster chaos
--trials N --seed S --until-step M [--payload train|shell]``.
The campaign leaves ``chaos_report.jsonl`` (one record per trial:
schedule, outcome, invariant verdicts) under its workdir and prints
the one-line summary from ``obsv.journal.summarize_chaos`` last.
"""

from __future__ import annotations

import dataclasses
import json
import random
import shutil
import time
from pathlib import Path
from typing import Any

from ..core.log import get_logger
from ..obsv.invariants import check_run, shrink_faults
from ..obsv.schema import maybe_check_event
from .cluster import (ClusterError, LocalClusterConfig, LocalProcessCluster,
                      worker_logged_since_spawn,
                      worker_resumed_step_since_spawn)
from .exec import CommandExecutor, FaultPlan, RetryPolicy
from .supervisor import ClusterSupervisor, SupervisorConfig

logger = get_logger("chaos")

FAULT_KINDS = ("kill", "hang", "stall", "corrupt", "delay", "resize",
               "net_latency", "net_bandwidth", "net_reset",
               "net_blackhole", "net_partition",
               "disk_enospc_after_bytes", "disk_eio", "disk_slow_io_ms",
               "disk_torn_write_at_byte", "disk_crash_rename")

# schedule kind → the action name the worker's DiskFaultInjector
# journals when it fires (train/storage.py) — the fired-fault
# accounting and the storage_faults invariant both read firings from
# the per-worker storage_faults.jsonl under these names
DISK_FAULT_ACTIONS = {"disk_enospc_after_bytes": "disk_enospc",
                      "disk_eio": "disk_eio",
                      "disk_slow_io_ms": "disk_slow_io",
                      "disk_torn_write_at_byte": "disk_torn_write",
                      "disk_crash_rename": "disk_crash_rename"}

# The cheap non-jax payload (the supervisor tests' resuming shell loop):
# ~20 steps/s, a file "checkpoint" every 5 steps so restarts observably
# resume. {limit} = step bound. No real checkpoints → the determinism
# and integrity invariants report skipped, not fail.
_SHELL_PAYLOAD = ('i=$( [ -f ckpt ] && cat ckpt || echo 0 ); '
                  'echo $i >> boots.txt; '
                  'while [ $i -lt {limit} ]; do i=$((i+1)); '
                  'echo "{{\\"step\\": $i, \\"loss\\": 1.0}}" '
                  '>> train_log.jsonl; '
                  'if [ $((i % 5)) -eq 0 ]; then echo $i > ckpt; fi; '
                  'sleep 0.05; done')

# The real payload: an actual `launch train` worker — deterministic by
# construction (fixed seed, synthetic data, float32, exact-resume
# checkpoints), so a fully recovered trial must reproduce the
# reference bitwise. {max_steps}/{save} templated from the config.
# Runs a 2-replica simulated mesh with momentum and the ZeRO-1 sharded
# weight update ON — with the comm split into 2 layer-ordered buckets
# (parallel.comm_buckets, ISSUE 12) — so every campaign exercises
# replica-sharded optimizer state AND the bucketed-overlap collectives
# end-to-end: kill/corrupt/resume must round-trip the canonical
# checkpoint layout exactly, and invariant 3's opt-state digest covers
# it instead of reporting vacuously on a stateless SGD.
_TRAIN_PAYLOAD = (
    "python -m distributedmnist_tpu.launch train "
    "train.train_dir=. data.dataset=synthetic data.batch_size=32 "
    "data.synthetic_train_size=256 data.synthetic_test_size=64 "
    "model.compute_dtype=float32 mesh.simulate_devices=2 "
    "optim.momentum=0.9 parallel.shard_weight_update=true "
    "parallel.comm_buckets=2 "
    "train.max_steps={max_steps} "
    "train.log_every_steps=1 train.save_interval_steps={save} "
    "train.async_checkpoint=false train.save_results_period=0")

# Serving-mode publisher (worker 0 of a serving trial, and the serving
# campaign's fault-free reference): a deterministic single-device
# trainer whose job is to PUBLISH a stream of checkpoints across a
# wall window long enough for serving replicas to boot, hot-swap, and
# be faulted mid-traffic — train.step_pace_ms stretches the publish
# cadence without touching numerics, so the publisher still reproduces
# the reference bitwise.
_SERVE_PUBLISHER_PAYLOAD = (
    "python -m distributedmnist_tpu.launch train "
    "train.train_dir=. data.dataset=synthetic data.batch_size=32 "
    "data.synthetic_train_size=256 data.synthetic_test_size=64 "
    "model.compute_dtype=float32 "
    "train.max_steps={max_steps} train.step_pace_ms={pace} "
    "train.log_every_steps=1 train.save_interval_steps={save} "
    "train.async_checkpoint=false train.save_results_period=0")

# Serving replicas (workers 1..N of a serving trial): hot-follow the
# publisher's logdir. Their ``train_log.jsonl`` carries heartbeat
# records whose step is the terminal-outcome count, so the supervisor's
# liveness/stall/progress machinery applies unchanged.
_SERVE_PAYLOAD = (
    "python -m distributedmnist_tpu.launch serve "
    "--train_dir ../worker0 --serve-dir . --port 0 "
    "--poll-secs 0.2 --queue-depth {queue} --max-batch 8")

# Decode-mode publisher (serve_decode=true): the published model must
# be a dense-FFN causal LM for the replicas' incremental decode
# export — a compact transformer on the synthetic LM stream, float32
# and dense attention for CPU-affordable chaos trials, paced exactly
# like the classification publisher.
_DECODE_PUBLISHER_PAYLOAD = (
    "python -m distributedmnist_tpu.launch train "
    "train.train_dir=. data.dataset=synthetic_lm data.batch_size=32 "
    "data.synthetic_train_size=256 data.synthetic_test_size=64 "
    "data.use_native_pipeline=false "
    "model.name=transformer model.seq_len=64 model.model_dim=64 "
    "model.num_heads=4 model.num_layers=2 model.vocab_size=32 "
    "model.compute_dtype=float32 model.attention_impl=dense "
    "train.max_steps={max_steps} train.step_pace_ms={pace} "
    "train.log_every_steps=1 train.save_interval_steps={save} "
    "train.async_checkpoint=false train.save_results_period=0")


@dataclasses.dataclass(frozen=True)
class ChaosFault:
    """One scheduled fault. ``ms`` is the stall duration (kind=stall)
    or injected delay (kind=delay); ``verb`` names the delayed command
    class (kind=delay only, worker ignored); ``world`` the target
    world size (kind=resize only — cluster-level, worker ignored: the
    supervisor shrinks/grows the whole roster at the trigger step);
    ``net`` carries a network fault's script parameters as sorted
    key/value pairs (kind=net_* only — a tuple, not a dict, so the
    frozen dataclass stays hashable; ``worker`` is the PROXIED
    replica and ``step`` is unused: transport faults trigger on
    traffic/wall-time, not train steps). ``disk`` carries a storage
    fault's script parameters the same way (kind=disk_* only;
    ``step`` is the earliest train step the script may fire at — the
    worker's injector arms it against the next durable save at or
    after that step)."""

    kind: str
    worker: int = 0
    step: int = 0
    ms: float = 0.0
    verb: str = ""
    world: int = 0
    net: tuple[tuple[str, float], ...] = ()
    disk: tuple[tuple[str, Any], ...] = ()

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"kind": self.kind}
        if self.kind == "delay":
            d.update(verb=self.verb, ms=self.ms)
        elif self.kind == "resize":
            d.update(step=self.step, world=self.world)
        elif self.kind.startswith("net_"):
            d.update(worker=self.worker, **dict(self.net))
        elif self.kind.startswith("disk_"):
            d.update(worker=self.worker, step=self.step,
                     **dict(self.disk))
        else:
            d.update(worker=self.worker, step=self.step)
            if self.kind == "stall":
                d["ms"] = self.ms
        return d


@dataclasses.dataclass(frozen=True)
class ChaosSchedule:
    """A seeded fault composition for one trial."""

    seed: int
    trial: int
    faults: tuple[ChaosFault, ...]

    def to_fault_plan(self) -> FaultPlan:
        kill: dict[int, int] = {}
        hang: dict[int, int] = {}
        stall: dict[int, tuple[int, float]] = {}
        corrupt: dict[int, int] = {}
        delay: dict[str, float] = {}
        resize: tuple[int, int] | None = None
        net: dict[int, list[dict]] = {}
        disk: dict[int, list[dict]] = {}
        for f in self.faults:
            if f.kind == "kill":
                kill[f.worker] = f.step
            elif f.kind == "hang":
                hang[f.worker] = f.step
            elif f.kind == "stall":
                stall[f.worker] = (f.step, f.ms)
            elif f.kind == "corrupt":
                corrupt[f.worker] = f.step
            elif f.kind == "delay":
                delay[f.verb] = f.ms
            elif f.kind == "resize":
                resize = (f.step, f.world)
            elif f.kind.startswith("net_"):
                # one proxy script per proxied replica; the script
                # grammar is launch/netchaos.py's (kind sans prefix)
                net.setdefault(f.worker, []).append(
                    {"kind": f.kind[len("net_"):], **dict(f.net)})
            elif f.kind.startswith("disk_"):
                # per-worker storage scripts; the grammar is
                # train/storage.py's (kind sans prefix, at_step from
                # the fault's step axis)
                disk.setdefault(f.worker, []).append(
                    {"kind": f.kind[len("disk_"):], "at_step": f.step,
                     **dict(f.disk)})
            else:
                raise ClusterError(f"unknown chaos fault kind {f.kind!r}")
        return FaultPlan(kill_worker_at_step=kill,
                         hang_worker_at_step=hang,
                         stall_worker_for_ms_at_step=stall,
                         corrupt_latest_checkpoint_at_step=corrupt,
                         delay_ms=delay,
                         resize_world_at_step=resize,
                         net_faults=net,
                         disk_faults=disk)

    def to_json_dict(self) -> dict[str, Any]:
        return {"seed": self.seed, "trial": self.trial,
                "faults": [f.to_dict() for f in self.faults]}

    def describe(self) -> str:
        if not self.faults:
            return "fault-free"
        return " + ".join(
            (f"{f.kind}(verb={f.verb}, {f.ms:.0f}ms)" if f.kind == "delay"
             else f"{f.kind}(→{f.world}w@{f.step})" if f.kind == "resize"
             else f"{f.kind}(w{f.worker}: "
                  + ", ".join(f"{k}={v:g}" for k, v in f.net) + ")"
             if f.kind.startswith("net_")
             else f"{f.kind}(w{f.worker}@{f.step}: "
                  + ", ".join(f"{k}={v}" for k, v in f.disk) + ")"
             if f.kind.startswith("disk_")
             else f"{f.kind}(w{f.worker}@{f.step}"
                  + (f", {f.ms:.0f}ms)" if f.kind == "stall" else ")"))
            for f in self.faults)


def generate_schedule(seed: int, trial: int, num_workers: int,
                      step_window: tuple[int, int],
                      max_faults: int = 3, min_faults: int = 1,
                      stall_ms_range: tuple[float, float] = (500.0, 3000.0),
                      delay_prob: float = 0.15,
                      resize_worlds: tuple[int, ...] = (),
                      resize_prob: float = 0.5) -> ChaosSchedule:
    """Sample one bounded-intensity schedule. Deterministic in
    (seed, trial). At most one fault of each kind per worker (the
    FaultPlan dicts are worker-keyed). A ``corrupt`` draw always rides
    with a ``kill`` at the SAME step — the torn checkpoint must
    actually be HIT by a restarted worker's restore, not silently
    overwritten — so if that worker's kill was already armed elsewhere
    the corruption moves to the kill's step. ``max_faults`` bounds
    intensity UNITS (a corrupt+kill pair is one unit; the fault list
    may hold up to ``max_faults + 1`` entries).

    ``resize_worlds``: candidate world sizes for the sixth fault kind
    — at most one cluster-level ``resize`` per schedule, drawn with
    ``resize_prob`` when the candidate set is non-empty. Drawn AFTER
    every legacy draw, so any (seed, trial) schedule from a
    resize-less config is byte-identical to what it always was."""
    import random
    rng = random.Random(seed * 1_000_003 + trial)
    lo, hi = step_window
    hi = max(hi, lo)
    n = rng.randint(min_faults, max(min_faults, max_faults))
    combos = [(kind, w) for kind in ("kill", "hang", "stall", "corrupt")
              for w in range(num_workers)]
    rng.shuffle(combos)
    faults: list[ChaosFault] = []
    used: set[tuple[str, int]] = set()
    units = 0

    def arm(kind: str, w: int, step: int, ms: float = 0.0) -> bool:
        if (kind, w) in used:
            return False
        used.add((kind, w))
        faults.append(ChaosFault(kind=kind, worker=w, step=step, ms=ms))
        return True

    for kind, w in combos:
        if units >= n:
            break
        step = rng.randint(lo, hi)
        if kind == "stall":
            if ("hang", w) in used:
                continue  # the stall's timed SIGCONT would silently
                # resume the "permanent" hang — mutually exclusive
            units += arm(kind, w, step, ms=rng.uniform(*stall_ms_range))
        elif kind == "hang":
            if ("stall", w) in used:
                continue
            units += arm(kind, w, step)
        elif kind == "corrupt":
            paired_already = ("kill", w) in used
            if paired_already:
                # align with the worker's existing kill so the pairing
                # invariant (same step) holds regardless of draw order
                step = next(f.step for f in faults
                            if f.kind == "kill" and f.worker == w)
            if arm(kind, w, step):
                arm("kill", w, step)
                # the pair costs ONE unit total — the kill's unit was
                # already charged when it was drawn first
                units += 0 if paired_already else 1
        else:
            units += arm(kind, w, step)
    if rng.random() < delay_prob:
        faults.append(ChaosFault(
            kind="delay", verb=rng.choice(("poll", "status", "progress")),
            ms=rng.uniform(5.0, 50.0)))
    if resize_worlds and rng.random() < resize_prob:
        faults.append(ChaosFault(
            kind="resize", step=rng.randint(lo, hi),
            world=int(rng.choice(tuple(resize_worlds)))))
    return ChaosSchedule(seed=seed, trial=trial, faults=tuple(faults))


def generate_serving_schedule(seed: int, trial: int,
                              serve_workers: list[int],
                              serve_window: tuple[int, int],
                              publish_window: tuple[int, int],
                              max_faults: int = 3, min_faults: int = 1,
                              stall_ms_range: tuple[float, float]
                              = (1000.0, 4000.0)) -> ChaosSchedule:
    """Serving-mode schedules (deterministic in (seed, trial)); its
    own generator rather than a branch of the training one because the
    fault GRAMMAR differs:

    * ALWAYS one kill of a serving replica — mid-traffic replica loss
      is the scenario the tier exists for; every seeded serving trial
      must exercise the failover/restart/zero-drop path.
    * ALWAYS one corruption of the PUBLISHED checkpoint (worker 0's
      newest artifact), UNPAIRED with any kill: in the serving tier
      the torn publish is observed by the replicas' checkpoint
      FOLLOWERS on their next poll — nothing needs to die for the
      fault to be hit, unlike training, where only a restarted
      worker's restore reads the file.
    * Extra kills/hangs/stalls on serving replicas up to
      ``max_faults`` intensity units. Kill/hang/stall trigger steps
      are in HEARTBEAT units (terminal outcomes served by that
      replica); the corruption step is in publisher train steps.
    """
    import random
    rng = random.Random(seed * 2_000_003 + trial)
    s_lo, s_hi = serve_window
    p_lo, p_hi = publish_window
    faults: list[ChaosFault] = [
        ChaosFault(kind="kill", worker=rng.choice(list(serve_workers)),
                   step=rng.randint(s_lo, max(s_lo, s_hi))),
        ChaosFault(kind="corrupt", worker=0,
                   step=rng.randint(p_lo, max(p_lo, p_hi))),
    ]
    used = {("kill", faults[0].worker)}
    n = rng.randint(min_faults, max(min_faults, max_faults))
    combos = [(kind, w) for kind in ("kill", "hang", "stall")
              for w in serve_workers]
    rng.shuffle(combos)
    units = 1  # the mandatory kill; the mandatory corrupt rides free
    for kind, w in combos:
        if units >= n:
            break
        if (kind, w) in used:
            continue
        if kind == "stall" and ("hang", w) in used:
            continue  # the stall's timed SIGCONT would resume the hang
        if kind == "hang" and ("stall", w) in used:
            continue
        used.add((kind, w))
        step = rng.randint(s_lo, max(s_lo, s_hi))
        ms = rng.uniform(*stall_ms_range) if kind == "stall" else 0.0
        faults.append(ChaosFault(kind=kind, worker=w, step=step, ms=ms))
        units += 1
    return ChaosSchedule(seed=seed, trial=trial, faults=tuple(faults))


def generate_network_schedule(seed: int, trial: int,
                              serve_workers: list[int],
                              max_faults: int = 3, min_faults: int = 2,
                              reset_after_bytes: tuple[int, int]
                              = (450, 800),
                              partition_start_s: tuple[float, float]
                              = (1.0, 4.0),
                              partition_duration_s: tuple[float, float]
                              = (0.75, 2.0)) -> ChaosSchedule:
    """Network-mode schedules (deterministic in (seed, trial)); its own
    generator — and its own rng stream (K=3_000_003, disjoint from the
    training and serving arms') — because the fault GRAMMAR differs:

    * ALWAYS one mid-stream ``net_reset`` against a serving replica:
      ``after_bytes`` is drawn ABOVE any single meta/classifier
      response (≲400 bytes) and INSIDE a decode token stream's
      cumulative size (~70 bytes/token line), so on a decode replica
      the cut lands after tokens flowed and before the terminal —
      the exactly-once retry path the proxy exists to exercise.
    * ALWAYS one timed ``net_partition`` window, anchored at the
      proxied replica's first live connection so it opens under load.
    * Extra latency/bandwidth/blackhole scripts up to ``max_faults``
      intensity units, at most one of each kind per worker (a proxy
      script list holds one script per kind).

    All triggers are traffic- or wall-clock-based — network faults
    have no train-step axis."""
    import random
    rng = random.Random(seed * 3_000_003 + trial)
    faults: list[ChaosFault] = [
        ChaosFault(kind="net_reset",
                   worker=rng.choice(list(serve_workers)),
                   net=(("after_bytes",
                         rng.randint(*reset_after_bytes)),)),
        ChaosFault(kind="net_partition",
                   worker=rng.choice(list(serve_workers)),
                   net=(("duration_s",
                         round(rng.uniform(*partition_duration_s), 3)),
                        ("start_s",
                         round(rng.uniform(*partition_start_s), 3)))),
    ]
    used = {(f.kind, f.worker) for f in faults}
    n = rng.randint(min_faults, max(min_faults, max_faults))
    combos = [(kind, w)
              for kind in ("net_latency", "net_bandwidth",
                           "net_blackhole")
              for w in serve_workers]
    rng.shuffle(combos)
    units = 2  # the mandatory reset + partition
    for kind, w in combos:
        if units >= n:
            break
        if (kind, w) in used:
            continue
        used.add((kind, w))
        if kind == "net_latency":
            net = (("delay_ms", round(rng.uniform(10.0, 60.0), 1)),
                   ("jitter_ms", round(rng.uniform(0.0, 30.0), 1)))
        elif kind == "net_bandwidth":
            # floor well above a response size per second: the cap
            # slows the wire without starving the request deadline
            net = (("bytes_per_s", rng.randint(8_192, 65_536)),)
        else:
            net = (("conn", rng.randint(0, 4)),
                   ("hold_s", round(rng.uniform(1.0, 2.5), 3)))
        faults.append(ChaosFault(kind=kind, worker=w, net=net))
        units += 1
    return ChaosSchedule(seed=seed, trial=trial, faults=tuple(faults))


def generate_disk_schedule(seed: int, trial: int, num_workers: int,
                           step_window: tuple[int, int],
                           save_interval_steps: int,
                           max_faults: int = 4, min_faults: int = 3,
                           io_attempts: int | None = None
                           ) -> ChaosSchedule:
    """Disk-mode schedules (deterministic in (seed, trial)); its own
    generator — and its own rng stream (K=4_000_003, disjoint from the
    training, serving and network arms') — because the fault GRAMMAR
    differs:

    * ALWAYS one ``disk_enospc_after_bytes`` against a worker's
      checkpoint writes, with ``times`` = the writer's retry budget so
      every attempt of ONE cadence save hits a full disk: the save
      must fail all the way through, the worker must journal
      ``save_failed`` and keep training — the graceful-degradation
      path the storage shim exists for.
    * ALWAYS one ``disk_torn_write_at_byte``: a write that lands only
      a prefix. One firing is absorbed by the retry loop (journaled,
      save still lands); the retry-budget variant turns it into a
      second failed cadence — both are drawn.
    * ALWAYS one ``disk_crash_rename`` (the power-cut model: rename
      applied, data lost) aligned to a SAVE step, paired with a kill
      just after it — silent corruption is only observable when a
      restarted worker's restore walks the pointer into the corrupt
      artifact and falls back, so the pair rides together the way the
      training arm pairs corrupt+kill. ``times=2`` covers the race
      where the kill lands after one more cadence save: the next
      artifact is corrupted too, and the fallback walk is exercised
      regardless of poll latency. The ENOSPC script is kept off this
      worker so a skipped save cannot swallow the rename the crash
      needs.
    * Extra write-path ``disk_eio`` / ``disk_slow_io_ms`` scripts up
      to ``max_faults`` intensity units, at most one of each kind per
      worker.

    Disk triggers are on the TRAIN-STEP axis (``at_step`` arms the
    script against the next durable save at or after that step), so
    the step window is the training one."""
    import random
    if io_attempts is None:
        # the writer's retry budget IS the "exhaust every attempt"
        # threshold — read it from the one place it's defined so the
        # generator can't drift from the checkpoint writer
        from ..train.checkpoint import _IO_ATTEMPTS as io_attempts
    rng = random.Random(seed * 4_000_003 + trial)
    lo, hi = step_window
    hi = max(hi, lo)
    w_crash = rng.randrange(num_workers)
    w_enospc = rng.randrange(num_workers)
    if num_workers > 1 and w_enospc == w_crash:
        w_enospc = (w_crash + 1) % num_workers
    # align the crash_rename with an actual save cadence step so the
    # paired kill can land between the corrupted save and the next one
    save_steps = [s for s in range(lo, hi + 1)
                  if s % max(1, save_interval_steps) == 0] or [lo]
    crash_step = rng.choice(save_steps)
    torn_times = rng.choice((1, io_attempts))
    faults: list[ChaosFault] = [
        ChaosFault(kind="disk_enospc_after_bytes", worker=w_enospc,
                   step=rng.randint(lo, hi),
                   disk=(("bytes", rng.randint(0, 512)),
                         ("match", ".msgpack"),
                         ("times", io_attempts))),
        ChaosFault(kind="disk_torn_write_at_byte",
                   worker=rng.randrange(num_workers),
                   step=rng.randint(lo, hi),
                   disk=(("at_byte", rng.randint(64, 4096)),
                         ("match", ".msgpack"),
                         ("times", torn_times))),
        ChaosFault(kind="disk_crash_rename", worker=w_crash,
                   step=crash_step,
                   disk=(("match", ".msgpack"), ("times", 2))),
        ChaosFault(kind="kill", worker=w_crash, step=crash_step + 1),
    ]
    used = {(f.kind, f.worker) for f in faults}
    n = rng.randint(min_faults, max(min_faults, max_faults))
    combos = [(kind, w) for kind in ("disk_eio", "disk_slow_io_ms")
              for w in range(num_workers)]
    rng.shuffle(combos)
    units = 3  # the mandatory trio; the paired kill rides free
    for kind, w in combos:
        if units >= n:
            break
        if (kind, w) in used:
            continue
        used.add((kind, w))
        step = rng.randint(lo, hi)
        if kind == "disk_eio":
            # write-path EIO, one firing: absorbed by the retry loop
            # (journaled; the save still lands) — read-path EIO only
            # fires on a restore, which an unfaulted worker never runs
            disk = (("match", ".msgpack"), ("nth", 1), ("op", "write"),
                    ("times", 1))
        else:
            disk = (("match", ".msgpack"),
                    ("ms", round(rng.uniform(5.0, 40.0), 1)),
                    ("times", 2))
        faults.append(ChaosFault(kind=kind, worker=w, step=step,
                                 disk=disk))
        units += 1
    return ChaosSchedule(seed=seed, trial=trial, faults=tuple(faults))


def count_fired_faults(trial_dir: Path,
                       schedule: ChaosSchedule) -> dict[str, Any]:
    """Scheduled-vs-actually-fired accounting for one trial, from the
    command journal alone. PR 7 left "the kill lands after run-end →
    zero episodes, still green" indistinguishable from a real
    all-quiet run; this makes the distinction a report fact the
    nightly gate can assert on (``fired > 0``). Every injector
    journals its firing: worker faults as ``event: "fault"`` records,
    exec delays as ``injected_delay_ms`` on command records, the
    resize fault as the supervisor's ``event: "reconfigure"`` begin
    with ``trigger: "fault_plan"``, and disk faults as the WORKER
    process's own ``event: "fault"`` records in its
    ``storage_faults.jsonl`` (the injector lives inside the worker's
    durable-write path, not the supervisor)."""
    from ..obsv.report import load_jsonl
    records = load_jsonl(trial_dir / "command_journal.jsonl")
    fault_actions = {"kill": "kill_worker", "hang": "hang_worker",
                     "stall": "stall_worker",
                     "corrupt": "corrupt_latest_checkpoint"}
    fault_actions.update(DISK_FAULT_ACTIONS)
    fired_kw = {(r.get("action"), r.get("worker"))
                for r in records if r.get("event") == "fault"}
    if any(f.kind.startswith("disk_") for f in schedule.faults):
        for d in sorted(trial_dir.glob("worker*")):
            for r in load_jsonl(d / "storage_faults.jsonl"):
                if r.get("event") == "fault":
                    fired_kw.add((r.get("action"), r.get("worker")))
    delay_fired = any(r.get("event") == "command"
                      and r.get("injected_delay_ms")
                      for r in records)
    resize_fired = any(r.get("event") == "reconfigure"
                       and r.get("action") == "begin"
                       and r.get("trigger") == "fault_plan"
                       for r in records)
    out: dict[str, Any] = {"scheduled": len(schedule.faults), "fired": 0,
                           "unfired": []}
    for f in schedule.faults:
        if f.kind == "delay":
            fired = delay_fired
        elif f.kind == "resize":
            fired = resize_fired
        else:
            # net_* faults journal under their own kind name (the
            # proxy's action IS the schedule kind), so the identity
            # fallback covers them
            fired = (fault_actions.get(f.kind, f.kind),
                     f.worker) in fired_kw
        if fired:
            out["fired"] += 1
        else:
            out["unfired"].append(f.to_dict())
    return out


# ---------------------------------------------------------------------------
# campaign
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Campaign knobs (JSON-loadable like every launch config)."""

    name: str = "chaos"
    trials: int = 5
    seed: int = 0
    until_step: int = 40
    num_workers: int = 2
    workdir: str = "/tmp/dmt_chaos"
    # "train" = real `launch train` workers (all invariants apply,
    # incl. bitwise determinism); "shell" = the cheap 20-steps/s shell
    # loop (no real checkpoints: determinism reports skipped) — for CI
    # smoke and generator/checker development; "serving" = the online
    # serving tier under fire: worker 0 is a paced checkpoint PUBLISHER
    # (`launch train`), workers 1..serve_replicas are serving replicas
    # (`launch serve` hot-following ../worker0), a closed-loop load
    # generator drives traffic for the whole trial, and the three
    # serving invariants (exactly-one terminal outcome, never serve a
    # failed digest, monotone served step) replay alongside the
    # training ones on the publisher
    payload: str = "train"
    train_command: str = ""     # override; "" = built-in payload
    save_interval_steps: int = 5
    # -- serving mode ---------------------------------------------------
    serve_replicas: int = 2
    load_concurrency: int = 2
    request_deadline_s: float = 3.0
    publisher_pace_ms: float = 150.0   # publish-cadence stretch (wall only)
    serve_queue_depth: int = 32
    # kill/hang/stall triggers on serving replicas are in HEARTBEAT
    # units (terminal outcomes that replica produced)
    serve_fault_window: tuple[int, int] = (5, 40)
    # Per-replica serving precision tiers (quantized serving under
    # fire): entry i names replica i+1's serve.precision_tier; missing
    # entries default to fp32. Any non-fp32 entry makes the PUBLISHER
    # payload write the matching quant sidecars
    # (quant.publish_tiers), so the corrupt-published-artifact fault —
    # which tears the .quant sidecar alongside the checkpoint — also
    # exercises the sidecar's digest refusal on a live replica.
    # None/() = every replica full precision (historical behavior).
    serve_precision_tiers: tuple[str, ...] | None = None
    # Decode-mode serving trials: the publisher trains a compact
    # causal LM and the replicas run `launch serve --decode` —
    # continuous-batching streaming generation over the paged KV
    # cache, with the loadgen driving token prompts through the
    # generate path. Kill/hang/stall triggers stay in heartbeat units
    # (finished generations); the decode_swap invariant replays
    # alongside 7-9. Incompatible with non-fp32 precision tiers (the
    # decode graph serves full precision only).
    serve_decode: bool = False
    # --decode knobs threaded to every decode replica (kept small so
    # generations finish fast enough for chaos's heartbeat triggers,
    # and so prompt+generation fit the compact LM's seq_len=64
    # position table)
    decode_max_new_tokens: int = 16
    decode_max_prompt_len: int = 16
    decode_slots: int = 4
    # boot every serving replica as an N-rank tensor-parallel process
    # group (serve.tp_ranks): the kill-worker faults then hit a group
    # supervisor whose die-as-a-unit restart the serve_group invariant
    # replays — a half-dead TP group must never serve
    serve_tp_ranks: int = 1
    # network=true swaps the serving arm's process-fault grammar for
    # the TRANSPORT one (generate_network_schedule): every trial
    # interposes seeded chaos proxies (launch/netchaos.py) between the
    # load generator and the net-faulted replicas — always a
    # mid-stream reset plus a partition window under live load — and
    # the exactly-once net_faults invariant (13) replays alongside
    # 7-10. Requires payload=serving AND serve_decode=true: the
    # mandatory reset must cut a token STREAM mid-generation, and
    # only the decode wire protocol streams.
    network: bool = False
    # disk=true swaps the training arm's process-fault grammar for the
    # STORAGE one (generate_disk_schedule): every trial scripts
    # deterministic disk faults (ENOSPC budgets, EIO, torn writes,
    # power-cut renames, slow I/O) into the workers' own durable-write
    # path (train/storage.py, armed via the fault plan's disk_faults →
    # DMT_DISK_FAULTS), always including a retry-exhausting ENOSPC, a
    # torn write, and a crash_rename paired with a kill — and the
    # storage_faults invariant (14) replays alongside the training
    # ones. Requires payload=train: the faults target real checkpoint
    # saves, which the shell and serving payloads don't perform (the
    # serving arm's published-artifact corruption is the existing
    # ``corrupt`` fault).
    disk: bool = False
    # -- resource broker (serving mode only) ------------------------------
    # broker=true arms demand-driven autoscaling (launch/broker.py)
    # over the trial's roster: DONOR train workers join it
    # (broker_train_workers TOTAL trainers incl. the publisher — the
    # capacity the broker trades into serving slots, never the
    # publisher itself), the load generator drives a seeded bursty
    # diurnal trace (trough/peak concurrency phases with jittered
    # durations) with rolling-window pressure snapshots journaled, and
    # the ResourceBroker rides supervise_until_step's per-tick
    # callback. Every roster change must replay against the
    # "autoscale" invariant — the campaign's gate is at least one
    # scale-up AND one scale-back with dropped==0 throughout.
    broker: bool = False
    broker_train_workers: int = 2   # total trainers incl. the publisher
    broker_standbys: int = 0        # warm serving spares for scale-up
    broker_phases: int = 4          # diurnal phases; odd = trough first
    broker_low_concurrency: int = 1
    broker_high_concurrency: int = 8
    broker_phase_secs: float = 10.0
    broker_window_s: float = 3.0    # loadgen rolling-window width
    broker_config: dict | None = None  # BrokerConfig field overrides
    # -- straggler-discipline controller (train payload only) -------------
    # discipline_controller=true arms the adaptive straggler-discipline
    # controller (train/discipline.py) inside every train worker: the
    # payload runs quorum aggregation over a seeded synthetic SPIKE
    # straggler profile, so the per-window tail ratio the controller
    # reads derives from the run seed alone — trial and reference make
    # IDENTICAL decisions, the discipline traces match, and invariant 3
    # keeps its full bitwise claim (a mid-run restart resets the
    # controller's in-memory state, diverges the trace, and exercises
    # the epoch-splice path instead). Every parameter change must
    # replay against the "discipline" invariant — the campaign's gate
    # is at least one licensed change with zero flaps.
    discipline_controller: bool = False
    discipline_window_steps: int = 8
    discipline_cooldown_steps: int = 8
    discipline_spike_prob: float = 0.25
    discipline_spike_scale: float = 8.0
    # schedule intensity
    max_faults: int = 3
    min_faults: int = 1
    last_fault_frac: float = 0.5   # faults land in the run's first half
    stall_ms_range: tuple[float, float] | None = None  # None = per-payload
    # The sixth fault kind: elastic shrink/grow mid-run. 0 disables
    # (default — resize-less configs reproduce their historical
    # schedules exactly); the nightly chaos CI turns it on. Candidate
    # worlds None = auto: shrink to num_workers-1, plus grow to
    # num_workers+1 when warm standbys exist to absorb it.
    resize_prob: float = 0.0
    resize_worlds: tuple[int, ...] | None = None
    # supervisor policy under test
    quorum: int = 1
    max_restarts: int = 2
    restart_backoff_s: float = 0.3
    stall_timeout_s: float | None = None  # None = per-payload default
    standby_workers: int = 0              # pre-booted spares per trial
    poll_secs: float | None = None        # None = per-payload default
    # One persistent compile cache shared by the reference run and
    # every trial (<campaign root>/compile_cache): the reference pays
    # the cold compile once and every later worker boot — including
    # every restart the faults force — is warm. What makes the
    # boot-derived stall timeout below safe.
    share_compile_cache: bool = True
    # Adaptive stall timeout (train payload): once a run has MEASURED
    # its spawn→first-log boot cost, trials stop paying the hardcoded
    # 90 s worst case — detection drops to
    # max(floor, mult × measured_boot), still capped at 90 s. The
    # floor keeps a noisy fast measurement from turning boot jitter
    # into false hang detections.
    stall_timeout_floor_s: float = 20.0
    stall_timeout_boot_mult: float = 3.0
    trial_timeout_s: float = 900.0
    drain_timeout_s: float = 180.0
    # drain gives up early on live workers whose logs stop moving for
    # this long (a permanently-stopped straggler would otherwise hold
    # every such trial for the full drain timeout); generous enough for
    # a restarted worker's jax boot and a final save+eval tail
    drain_stall_s: float = 45.0
    # shrinking
    shrink: bool = True
    shrink_max_probes: int = 8

    def __post_init__(self) -> None:
        # the repo's knob contract: a typo is a typed error naming the
        # valid set at config build — not a replica crash-looping
        # against its restart budget mid-trial
        from ..core.config import SERVING_PRECISION_TIERS
        for t in (self.serve_precision_tiers or ()):
            if t not in SERVING_PRECISION_TIERS:
                raise ClusterError(
                    f"serve_precision_tiers names unknown tier {t!r}; "
                    f"valid tiers: "
                    f"{', '.join(SERVING_PRECISION_TIERS)}")
        if self.serve_decode and any(
                t and t != "fp32"
                for t in (self.serve_precision_tiers or ())):
            raise ClusterError(
                "serve_decode=true is incompatible with non-fp32 "
                "serve_precision_tiers: the decode service serves "
                "full precision only (quant sidecars hold weights for "
                "the one-shot predict export)")
        if self.network:
            if self.payload != "serving":
                raise ClusterError(
                    "network=true requires payload=serving: the chaos "
                    "proxies interpose on the serving wire protocol")
            if not self.serve_decode:
                raise ClusterError(
                    "network=true requires serve_decode=true: the "
                    "mandatory mid-stream reset must cut a decode "
                    "token stream, and only the decode protocol "
                    "streams multi-line responses")
            if self.broker:
                raise ClusterError(
                    "network=true is incompatible with broker=true: "
                    "the broker's traded roster would outgrow the "
                    "boot-time proxy set, leaving new replicas "
                    "unproxied mid-trial")
        if self.disk:
            if self.payload != "train":
                raise ClusterError(
                    "disk=true requires payload=train: storage faults "
                    "target the trainer's durable checkpoint writes, "
                    "which the shell and serving payloads don't "
                    "perform")
            if self.save_interval_steps < 2:
                raise ClusterError(
                    "disk=true requires save_interval_steps >= 2: the "
                    "crash_rename fault pairs with a kill one step "
                    "after the save it corrupts, so at least one step "
                    "must separate consecutive cadence saves")
        if self.broker:
            # the broker recognizes serving slots by command EQUALITY
            # with one uniform serving payload — a mixed-tier roster
            # (per-replica command suffixes) would misclassify every
            # non-fp32 replica as a trainer
            if self.payload != "serving":
                raise ClusterError(
                    "broker=true requires payload=serving: the broker "
                    "trades training slots for serving replicas")
            if any(t and t != "fp32"
                   for t in (self.serve_precision_tiers or ())):
                raise ClusterError(
                    "broker=true is incompatible with non-fp32 "
                    "serve_precision_tiers: the broker identifies "
                    "serving slots by payload equality, so the roster "
                    "must run one uniform serving command")
            if self.broker_train_workers < 2:
                raise ClusterError(
                    "broker=true requires broker_train_workers >= 2: "
                    "the publisher is never a scale-up victim, so at "
                    "least one donor trainer must exist for the broker "
                    "to trade")
        if self.discipline_controller:
            if self.payload != "train":
                raise ClusterError(
                    "discipline_controller=true requires payload=train: "
                    "the straggler-discipline controller lives in the "
                    "training step (quorum over a synthetic straggler "
                    "profile), not the shell or serving payloads")
            if self.train_command:
                raise ClusterError(
                    "discipline_controller=true is incompatible with a "
                    "train_command override: the controller knobs are "
                    "appended to the built-in train payload, and a "
                    "custom command owns its own sync.* flags")

    @classmethod
    def from_file(cls, path: str | Path,
                  overrides: dict | None = None) -> "ChaosConfig":
        # `--chaos-config` accepts a file path or inline JSON — a path
        # can't start with "{", so the sniff is unambiguous. CLI flag
        # overrides merge BEFORE construction: __post_init__ validates
        # cross-field constraints (broker requires payload=serving), so
        # the config must be built once, already merged.
        text = str(path)
        d = (json.loads(text) if text.lstrip().startswith("{")
             else json.loads(Path(path).read_text()))
        d.update(overrides or {})
        unknown = set(d) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise ClusterError(f"unknown chaos config keys: {sorted(unknown)}")
        if "stall_ms_range" in d and d["stall_ms_range"] is not None:
            d["stall_ms_range"] = tuple(d["stall_ms_range"])
        if "serve_fault_window" in d:
            d["serve_fault_window"] = tuple(d["serve_fault_window"])
        if "serve_precision_tiers" in d and \
                d["serve_precision_tiers"] is not None:
            d["serve_precision_tiers"] = tuple(
                str(t) for t in d["serve_precision_tiers"])
        if "resize_worlds" in d and d["resize_worlds"] is not None:
            d["resize_worlds"] = tuple(int(w) for w in d["resize_worlds"])
        return cls(**d)

    # -- per-payload defaults -------------------------------------------

    def resolved_poll_secs(self) -> float:
        return self.poll_secs if self.poll_secs is not None else (
            0.2 if self.payload == "shell" else 1.0)

    def resolved_stall_timeout_s(self,
                                 measured_boot_s: float | None = None
                                 ) -> float:
        if self.stall_timeout_s is not None:
            return self.stall_timeout_s
        if self.payload == "shell":
            return 2.5
        # the stall clock starts at the first poll, BEFORE the worker
        # has logged anything — the timeout must clear a full boot or
        # healthy boots read as hangs. With a MEASURED boot cost
        # (the reference run's spawn→first-log latency) the default
        # derives from reality instead of the hardcoded worst case: a
        # warm-cache boot of ~5 s detects a stalled worker in ~20 s,
        # not 90.
        if measured_boot_s is not None and measured_boot_s > 0:
            return min(90.0, max(self.stall_timeout_floor_s,
                                 self.stall_timeout_boot_mult
                                 * measured_boot_s))
        return 90.0

    def resolved_stall_ms_range(self) -> tuple[float, float]:
        if self.stall_ms_range is not None:
            return self.stall_ms_range
        # shell: straddle the stall timeout so the restart-vs-wait race
        # runs both ways; train: always below it (a transient straggler
        # the supervisor should WAIT out, never restart)
        return (500.0, 4000.0) if self.payload == "shell" else (
            2000.0, 8000.0)

    def resolved_resize_worlds(self) -> tuple[int, ...]:
        if self.resize_prob <= 0:
            return ()
        if self.resize_worlds is not None:
            return tuple(self.resize_worlds)
        worlds: list[int] = []
        if self.num_workers > 1:
            worlds.append(self.num_workers - 1)  # shrink
        if self.standby_workers > 0:
            worlds.append(self.num_workers + 1)  # warm grow
        return tuple(worlds)

    def resolved_train_command(self, measured_boot_s: float | None = None
                               ) -> str:
        if self.train_command:
            return self.train_command
        if self.payload == "shell":
            return _SHELL_PAYLOAD.format(limit=self.until_step + 20)
        if self.payload == "serving":
            # worker 0 AND the campaign reference: the paced publisher.
            # The pace ADAPTS to the measured boot (the reference run's
            # spawn→first-log cost): serving replicas pay roughly the
            # same jax boot the publisher did, so the publishing window
            # must outlast it with margin or a loaded box finishes the
            # trial before any replica ever serves — the same
            # derive-from-reality move the stall timeout makes.
            pace = self.publisher_pace_ms
            if measured_boot_s is not None and measured_boot_s > 0:
                floor = 2500.0 * measured_boot_s / max(1, self.until_step)
                pace = min(2000.0, max(pace, floor))
            if self.serve_decode:
                # decode trials publish a causal LM — no quant
                # sidecars (validated fp32-only above)
                return _DECODE_PUBLISHER_PAYLOAD.format(
                    max_steps=self.until_step, pace=round(pace, 1),
                    save=self.save_interval_steps)
            cmd = _SERVE_PUBLISHER_PAYLOAD.format(
                max_steps=self.until_step, pace=round(pace, 1),
                save=self.save_interval_steps)
            quant = self.resolved_quant_publish_tiers()
            if quant:
                # the publisher writes the sidecars the quantized
                # replicas prefer (also runs in the fault-free
                # reference — same payload, bitwise determinism holds:
                # sidecars never touch the train state)
                cmd += f" quant.publish_tiers={','.join(quant)}"
            return cmd
        cmd = _TRAIN_PAYLOAD.format(max_steps=self.until_step,
                                    save=self.save_interval_steps)
        if self.discipline_controller:
            # quorum over the seeded synthetic spike profile: the
            # controller's CDF signal derives from the run seed alone,
            # so the fault-free reference adapts identically and the
            # bitwise determinism claim survives the armed controller
            cmd += (
                " sync.mode=quorum sync.adaptive=true"
                f" sync.adaptive_window_steps={self.discipline_window_steps}"
                f" sync.adaptive_cooldown_steps="
                f"{self.discipline_cooldown_steps}"
                " sync.straggler_profile=spike"
                f" sync.straggler_spike_prob={self.discipline_spike_prob}"
                f" sync.straggler_spike_scale={self.discipline_spike_scale}")
        return cmd

    def resolved_quant_publish_tiers(self) -> tuple[str, ...]:
        """The distinct non-fp32 tiers any replica serves — what the
        publisher must write sidecars for (order-stable)."""
        tiers: list[str] = []
        for t in (self.serve_precision_tiers or ()):
            if t and t != "fp32" and t not in tiers:
                tiers.append(t)
        return tuple(tiers)

    def resolved_serve_command(self) -> str:
        """The uniform serving payload (fp32, no tier suffix) — the
        broker's serving-slot identity and the command every replica
        and warm standby runs under broker=true."""
        cmd = _SERVE_PAYLOAD.format(queue=self.serve_queue_depth)
        if self.serve_decode:
            cmd += (f" --decode --decode-slots {self.decode_slots}"
                    f" --max-new-tokens {self.decode_max_new_tokens}"
                    f" --max-prompt-len {self.decode_max_prompt_len}")
        if self.serve_tp_ranks > 1:
            cmd += f" --tp-ranks {self.serve_tp_ranks}"
        return cmd

    def resolved_donor_command(self,
                               measured_boot_s: float | None = None
                               ) -> str:
        """A donor trainer's payload: the publisher's command with a
        10× step budget so donors never finish inside the trial window
        (the broker reaps them by reshape, not the supervisor by
        restart). Safe for determinism — the LR schedule is an
        epoch-indexed staircase, independent of max_steps."""
        base = self.resolved_train_command(measured_boot_s)
        return base.replace(f"train.max_steps={self.until_step}",
                            f"train.max_steps={self.until_step * 10}")

    def resolved_worker_commands(self,
                                 measured_boot_s: float | None = None
                                 ) -> dict[str, str]:
        """Per-worker payload overrides — serving mode's mixed roster
        (publisher + replicas); empty for the uniform payloads.
        ``serve_precision_tiers`` entry i pins replica i+1's tier (a
        mixed fp32/int8 roster exercises both weight paths under one
        fault plan). Under broker=true the roster also carries donor
        trainers after the replicas — overridden slots the broker may
        trade for serving capacity."""
        if self.payload != "serving":
            return {}
        tiers = self.serve_precision_tiers or ()
        out: dict[str, str] = {}
        for k in range(1, 1 + self.serve_replicas):
            cmd = self.resolved_serve_command()
            tier = tiers[k - 1] if k - 1 < len(tiers) else ""
            if tier and tier != "fp32":
                cmd += f" --precision-tier {tier}"
            out[str(k)] = cmd
        if self.broker:
            donor = self.resolved_donor_command(measured_boot_s)
            for k in range(1 + self.serve_replicas,
                           self.trial_num_workers()):
                out[str(k)] = donor
        return out

    def trial_num_workers(self) -> int:
        if self.payload != "serving":
            return self.num_workers
        donors = max(0, self.broker_train_workers - 1) if self.broker \
            else 0
        return 1 + self.serve_replicas + donors

    def step_window(self) -> tuple[int, int]:
        lo = max(2, self.save_interval_steps + 1)
        return (lo, max(lo, int(self.until_step * self.last_fault_frac)))

    @property
    def root(self) -> Path:
        return Path(self.workdir) / self.name


def _merge_load_summaries(summaries: list[dict | None]) -> dict | None:
    """Fold the per-phase ``summarize_outcomes`` dicts of a diurnal
    load trace into one trial-level summary: counters SUM, tail
    latencies take the worst phase (the bound the chaos gate checks —
    a per-request-weighted percentile across phases would launder a
    bad burst through a long calm trough), serving evidence sets
    union. Phases that never ran (``None``) are skipped; all-``None``
    merges to ``None``."""
    real = [s for s in summaries if s]
    if not real:
        return None
    counters = ("issued", "terminal", "dropped", "responses",
                "rejected", "errors", "tokens_streamed")
    out: dict[str, Any] = {k: sum(int(s.get(k, 0)) for s in real)
                           for k in counters}
    if not out["tokens_streamed"]:
        del out["tokens_streamed"]
    by_reason: dict[str, int] = {}
    for s in real:
        for k, v in (s.get("by_reason") or {}).items():
            by_reason[k] = by_reason.get(k, 0) + int(v)
    out["by_reason"] = by_reason
    out["reject_rate"] = round(out["rejected"] / max(1, out["terminal"]),
                               4)
    out["duration_s"] = round(sum(float(s.get("duration_s", 0.0))
                                  for s in real), 3)
    out["throughput_rps"] = round(
        out["terminal"] / max(out["duration_s"], 1e-9), 2)
    out["model_steps_served"] = sorted(
        {st for s in real for st in s.get("model_steps_served", ())})
    out["tiers_served"] = sorted(
        {t for s in real for t in s.get("tiers_served", ())})
    for key in ("latency_ms", "ttft_ms", "inter_token_ms"):
        dists = [s[key] for s in real if s.get(key)]
        if dists:
            out[key] = {q: max(d[q] for d in dists if q in d)
                        for q in dists[0]}
    out["phases_merged"] = len(real)
    return out


class ChaosCampaign:
    """N seeded trials + a fault-free reference + invariant replay +
    failing-schedule shrinking, over real local worker processes."""

    def __init__(self, cfg: ChaosConfig):
        self.cfg = cfg
        self.reference_dir: Path | None = None
        # latest observed spawn→first-log cost (reference first, then
        # each completed trial): what resolved_stall_timeout_s derives
        # the trial detection window from
        self._measured_boot_s: float | None = None

    # -- one trial ------------------------------------------------------

    def _run_trial(self, rel: str, plan: FaultPlan, seed: int,
                   num_workers: int,
                   measured_boot_s: float | None = None,
                   serving: bool = False) -> dict[str, Any]:
        """Execute one supervised run under ``plan`` in
        ``<root>/<rel>``; returns the outcome record (also written to
        ``outcome.json`` there so the invariant replay is
        artifact-only). ``measured_boot_s``: a previous run's observed
        spawn→first-log cost — lets the stall timeout derive from the
        measured boot instead of the hardcoded worst case.

        ``serving``: the mixed serving roster (worker 0 publishes,
        workers 1..N serve) with the closed-loop load generator driving
        traffic for the whole supervised window; progress toward the
        target counts from the PUBLISHER only (a replica's heartbeat
        step is its request counter, not run progress)."""
        cfg = self.cfg
        target = cfg.until_step
        broker = None
        brokered = serving and cfg.broker
        lcfg = LocalClusterConfig(
            name=rel, num_workers=num_workers, workdir=str(cfg.root),
            train_command=cfg.resolved_train_command(measured_boot_s),
            worker_commands=(cfg.resolved_worker_commands(measured_boot_s)
                             if serving else {}),
            # brokered rosters park their warm spares on the SERVING
            # payload: a scale-up promotes one into the new slot with
            # its jax boot already paid
            standby_command=(cfg.resolved_serve_command()
                             if brokered and cfg.broker_standbys > 0
                             else ""),
            # ONE cache for the whole campaign, not per-trial: the
            # reference's cold compile warms every later boot
            compile_cache=cfg.share_compile_cache,
            compile_cache_dir=(str(cfg.root / "compile_cache")
                               if cfg.share_compile_cache else ""))
        executor = CommandExecutor(
            journal=lcfg.root / "command_journal.jsonl",
            retry=RetryPolicy(max_attempts=1, seed=seed),
            fault_plan=plan)
        cluster = LocalProcessCluster(lcfg, executor)
        scfg = SupervisorConfig(
            quorum=min(cfg.quorum, num_workers),
            max_restarts_per_worker=cfg.max_restarts,
            restart_backoff_s=cfg.restart_backoff_s,
            stall_timeout_s=cfg.resolved_stall_timeout_s(measured_boot_s),
            standby_workers=cfg.standby_workers,
            seed=seed)
        sup = ClusterSupervisor(cluster, scfg)
        if brokered:
            from ..core.config import BrokerConfig
            from .broker import ResourceBroker
            bcfg = BrokerConfig(**(cfg.broker_config or {}))
            broker = ResourceBroker(
                sup, bcfg, serve_command=cfg.resolved_serve_command(),
                loadgen_journal=lcfg.root / "loadgen.jsonl",
                warm_standbys=cfg.broker_standbys)
        outcome: dict[str, Any] = {
            "name": rel, "seed": seed, "target": target,
            "num_workers": num_workers,
            "fault_plan": plan.to_json_dict(),
            "supervisor": dataclasses.asdict(scfg),
            "train_command": lcfg.train_command,
            "reference_dir": (str(self.reference_dir)
                              if self.reference_dir else None),
        }
        t0 = time.monotonic()
        loadgen_thread: Any = None
        load_stop = None
        load_result: dict[str, Any] = {}
        proxies: dict[int, Any] = {}
        try:
            # inside the try: a spawn that fails halfway (fork pressure
            # mid-campaign) must still hit the kill_all/close below, or
            # already-spawned detached workers outlive the campaign
            cluster.create()
            cluster.run_train()
            if broker is not None:
                broker.start()  # provision the warm serving spares
            if plan.net_faults:
                # one seeded chaos proxy per net-faulted replica,
                # journaling its net_* firings into the same command
                # journal the process faults use; the loadgen below
                # routes those replicas' endpoints through the proxy
                # ports (upstreams re-resolve from serve.json per
                # connection, so replica restarts stay reachable)
                from .netchaos import start_proxies
                proxies = start_proxies(lcfg.root, plan.net_faults,
                                        journal=executor.journal,
                                        seed=seed)
            if serving:
                loadgen_thread, load_stop = self._start_loadgen(
                    lcfg, load_result, proxies=proxies)
            got = sup.supervise_until_step(
                target, poll_secs=cfg.resolved_poll_secs(),
                timeout_secs=cfg.trial_timeout_s,
                target_worker=0 if serving else None,
                on_tick=broker.tick if broker is not None else None)
            outcome.update(outcome="completed", step=got["step"])
            if serving:
                self._stop_serving(cluster, sup, num_workers,
                                   loadgen_thread, load_stop)
                loadgen_thread = None
            self._drain(cluster, sup)
            # the drain may have closed recovery episodes the
            # supervised loop left open (a worker restarted near
            # run-end finishes its jax boot DURING the drain) — the
            # outcome's recovery/MTTR summary must include them
            outcome["recovery"] = sup.summary()
            # spawn→first-log cost of THIS run's workers: the adaptive
            # stall timeout for later trials derives from it
            outcome["boot_s"] = cluster.measured_boot_s()
            # the world the trial ENDED at (a resize fault or elastic
            # shrink reshaped the roster mid-run; the reconfigure
            # invariant cross-checks this against the journal)
            st = cluster.status()
            if st is not None:
                outcome["final_world"] = len(st["workers"])
        except ClusterError as e:
            aborted = any(ev.get("action") == "below_quorum_abort"
                          for ev in sup.events)
            outcome.update(outcome="aborted" if aborted else "failed",
                           step=None, error=str(e),
                           recovery=sup.summary())
        finally:
            if loadgen_thread is not None:  # error path: stop the load
                load_stop.set()
                loadgen_thread.join(timeout=30)
            for p in proxies.values():
                p.stop()
            cluster.kill_all()
            executor.close()
        if serving:
            outcome["mode"] = "serving"
            if brokered:
                # the roster TRADED slots mid-run: the serving workers
                # are whichever dirs actually served (grown ids
                # included), not the boot-time range — the serving
                # invariants replay exactly these journals
                outcome["broker"] = True
                outcome["autoscale"] = (broker.summary()
                                        if broker is not None else None)
                outcome["serve_workers"] = sorted(
                    int(p.parent.name[len("worker"):])
                    for p in lcfg.root.glob("worker*/serve_log.jsonl"))
            else:
                outcome["serve_workers"] = list(range(1, num_workers))
            outcome["serving"] = load_result.get("summary")
            if load_result.get("phases") is not None:
                outcome["load_phases"] = load_result["phases"]
            # weight-swap-by-tier accounting over every replica's
            # serve journal (tier-less legacy swaps count as fp32) —
            # the evidence a quantized campaign arm actually served
            # its tier, and that sidecar digest refusals fired
            from ..obsv.journal import (summarize_net_chaos,
                                        summarize_serving_swaps)
            from ..obsv.report import load_jsonl
            serve_recs: list[dict] = []
            for k in outcome["serve_workers"]:
                serve_recs += load_jsonl(
                    lcfg.worker_dir(k) / "serve_log.jsonl", "serve")
            outcome["serve_swaps"] = summarize_serving_swaps(serve_recs)
            # network-fault evidence (None when the trial saw none):
            # proxy firings by kind, dedup-cache hits, retry
            # amplification — the chaos report's ``net`` slot
            outcome["net"] = summarize_net_chaos(lcfg.root)
        if cfg.discipline_controller and not serving:
            # worker 0's decision journal is the trial's discipline
            # evidence (every worker runs the identical seeded program,
            # so one trace represents them all; per-worker divergence
            # is the invariant's job, not the summary's)
            from ..obsv import schema as _schema
            from ..obsv.journal import summarize_discipline
            from ..obsv.report import load_jsonl
            outcome["discipline"] = summarize_discipline(load_jsonl(
                lcfg.worker_dir(0) / "train_log.jsonl",
                _schema.DISCIPLINE))
        outcome["duration_s"] = round(time.monotonic() - t0, 3)
        (lcfg.root / "outcome.json").write_text(
            json.dumps(outcome, indent=2, default=str))
        return outcome

    # -- serving-mode plumbing ------------------------------------------

    def _start_loadgen(self, lcfg: LocalClusterConfig,
                       load_result: dict[str, Any],
                       proxies: dict[int, Any] | None = None):
        """Launch the closed-loop load generator on a background
        thread: wait for the first replica to become ready (its
        ``serve.json`` + a meta answer), then drive traffic through
        the round-robin failover shim until told to stop. The
        per-request journal lands in ``<trial root>/loadgen.jsonl`` —
        the artifact the serving invariants replay.

        ``proxies``: network-mode chaos proxies keyed by proxied
        worker — those replicas' discovered endpoints are rewritten to
        the proxy's listen port, so every request to a net-faulted
        replica crosses its fault scripts."""
        import threading

        from ..servesvc.client import ServeClient, discover_endpoints
        from ..servesvc.loadgen import (make_input_fn, make_prompt_fn,
                                        run_load)
        cfg = self.cfg
        root = lcfg.root
        stop = threading.Event()

        def endpoints() -> list[dict]:
            eps = discover_endpoints(root)
            if not proxies:
                return eps
            out = []
            for e in eps:
                p = proxies.get(e.get("worker"))
                if p is not None and p.bound_port:
                    e = {**e, "host": p.listen_host,
                         "port": p.bound_port}
                out.append(e)
            return out

        def drive() -> None:
            client = ServeClient(endpoints,
                                 deadline_s=cfg.request_deadline_s,
                                 max_attempts=6,
                                 seed=cfg.seed)
            meta = None
            while meta is None and not stop.is_set():
                meta = client.meta(deadline_s=1.0)
                if meta is None:
                    time.sleep(0.5)
            if meta is None:
                load_result["summary"] = None  # nothing ever came up
                return
            if meta.get("decode"):
                # decode replicas: drive token prompts through the
                # streaming generate path (ttft/itl recorded per
                # request, tokens bounded so generations finish
                # inside heartbeat-trigger cadence)
                make_input = make_prompt_fn(meta["vocab_size"],
                                            meta["max_prompt_len"])
            else:
                make_input = make_input_fn(meta["input_shape"],
                                           meta["input_dtype"])
            decode = bool(meta.get("decode"))
            if not cfg.broker:
                load_result["summary"] = run_load(
                    client, None, cfg.load_concurrency, make_input,
                    journal_path=root / "loadgen.jsonl", stop_event=stop,
                    decode=decode)
                return
            # broker mode: a seeded bursty DIURNAL trace — trough and
            # peak concurrency phases with jittered durations, each a
            # run_load leg appending to the one shared loadgen.jsonl
            # with rolling-window pressure snapshots the broker reads.
            # A final trough leg holds until the trial ends so the
            # window stays fresh — the calm evidence the scale-back
            # needs.
            rng = random.Random(f"{cfg.seed}:{lcfg.name}:diurnal")
            snap = max(0.5, cfg.broker_window_s / 3.0)
            phases: list[dict[str, Any]] = []

            def leg(conc: int, phase_stop) -> dict[str, Any] | None:
                return run_load(
                    client, None, conc, make_input,
                    journal_path=root / "loadgen.jsonl",
                    stop_event=phase_stop, decode=decode,
                    window_s=cfg.broker_window_s, snapshot_every_s=snap)

            for i in range(max(0, cfg.broker_phases)):
                if stop.is_set():
                    break
                conc = (cfg.broker_low_concurrency if i % 2 == 0
                        else cfg.broker_high_concurrency)
                dur = cfg.broker_phase_secs * (0.8 + 0.4 * rng.random())
                phase_stop = threading.Event()

                def pace(deadline=time.monotonic() + dur, ps=phase_stop):
                    while time.monotonic() < deadline \
                            and not stop.is_set():
                        time.sleep(0.1)
                    ps.set()

                pacer = threading.Thread(target=pace, daemon=True,
                                         name=f"chaos-load-pace{i}")
                pacer.start()
                s = leg(conc, phase_stop)
                pacer.join(timeout=5)
                phases.append({"phase": i, "concurrency": conc,
                               "duration_s": round(dur, 3),
                               "summary": s})
            if not stop.is_set():
                s = leg(cfg.broker_low_concurrency, stop)
                phases.append({"phase": len(phases),
                               "concurrency": cfg.broker_low_concurrency,
                               "duration_s": None, "summary": s})
            load_result["summary"] = _merge_load_summaries(
                [p["summary"] for p in phases])
            load_result["phases"] = [
                {k: v for k, v in p.items() if k != "summary"}
                for p in phases]

        t = threading.Thread(target=drive, daemon=True, name="chaos-load")
        t.start()
        return t, stop

    def _stop_serving(self, cluster: LocalProcessCluster,
                      sup: ClusterSupervisor, num_workers: int,
                      loadgen_thread, load_stop) -> None:
        """Orderly serving teardown once the publisher hit its target:
        stop the offered load, then SIGTERM the replicas so their
        graceful drain sheds anything still queued with a TYPED reject
        (the zero-drop evidence), closing any recovery episodes their
        heartbeats can prove resumed."""
        load_stop.set()
        loadgen_thread.join(timeout=60)
        st = cluster.status()
        if st is not None and sup.open_episodes:
            for w in st["workers"]:
                if w["worker"] in sup.open_episodes:
                    resumed = worker_resumed_step_since_spawn(
                        w, events=("step", "heartbeat"))
                    if resumed is not None:
                        sup.close_episode(w["worker"], *resumed)
        # stop whatever the roster holds NOW (a brokered trial's ids
        # grow past the boot-time range), never worker 0 — the
        # publisher already finished and its final save must not race
        # a SIGTERM
        live = (sorted(w["worker"] for w in st["workers"])
                if st is not None else list(range(num_workers)))
        for k in live:
            if k != 0:
                cluster.stop_all(worker=str(k))
        cluster.wait_drained(15.0)

    # spawn-observation helpers: the logic moved to launch/cluster.py
    # (worker_logged_since_spawn / worker_resumed_step_since_spawn) so
    # the supervisor's reconfigure-resume watch shares it; these thin
    # delegates keep the established chaos-side names.

    @staticmethod
    def _logged_since_spawn(worker: dict) -> bool:
        return worker_logged_since_spawn(worker)

    @staticmethod
    def _resumed_step_since_spawn(worker: dict
                                  ) -> tuple[int, float | None] | None:
        return worker_resumed_step_since_spawn(worker)

    def _drain(self, cluster: LocalProcessCluster,
               sup: ClusterSupervisor | None = None) -> None:
        """The supervisor returns when the FASTEST worker hits the
        target; wait for the rest to finish their final save and exit
        before teardown, or the determinism check would compare
        checkpoints torn short by our own kill_all. Workers that died
        for good (exhausted budget) are not waited for, and a live
        worker whose log stops moving for a whole stall window (a
        permanently SIGSTOPped straggler past its restart budget —
        alive to kill -0 forever) is given up on early rather than
        riding out the full drain timeout.

        The stall clock is PER WORKER and does not start until that
        worker has logged at least one line since its own (re)spawn: a
        worker restarted near the end of the run spends a full jax boot
        (> drain_stall_s) producing no log movement, and the old global
        clock would kill it mid-boot — silently downgrading the trial
        to determinism-skipped (PR 4's known rough edge). A worker that
        never logs at all is still bounded by drain_timeout_s.

        With ``sup``, the drain also CLOSES recovery episodes the
        supervised loop left open: a worker restarted near run-end
        finishes its jax boot here, and the tick its first STEP record
        since its own spawn lands is its first-moved-step (the compile
        record alone is not a resume — see _resumed_step_since_spawn) —
        the ``resume`` event (with MTTR) would otherwise never be
        journaled and the trial would undercount its episodes."""
        deadline = time.monotonic() + self.cfg.drain_timeout_s
        stall_window = self.cfg.drain_stall_s
        last_progress: dict[int, Any] = {}
        moved_at: dict[int, float] = {}
        while time.monotonic() < deadline:
            st = cluster.status()
            if st is not None and sup is not None and sup.open_episodes:
                # swept BEFORE the all-dead return: a restarted worker
                # that resumed, finished, and exited between supervise
                # and the first drain tick still closes its episode
                for w in st["workers"]:
                    if w["worker"] in sup.open_episodes:
                        resumed = self._resumed_step_since_spawn(w)
                        if resumed is not None:
                            sup.close_episode(w["worker"], *resumed)
            if st is None or not any(w["alive"] for w in st["workers"]):
                return
            # below the all-dead return: the stall loop is prog's only
            # consumer, and every drain ends through that return — the
            # final tick must not pay the per-worker tail sweep
            prog = cluster.worker_progress()
            now = time.monotonic()
            stalled: list[bool] = []
            for w in st["workers"]:
                if not w["alive"]:
                    continue
                k = w["worker"]
                if k not in moved_at or prog.get(k) != last_progress.get(k):
                    last_progress[k] = prog.get(k)
                    moved_at[k] = now
                if not self._logged_since_spawn(w):
                    moved_at[k] = now  # booting: hold its clock at zero
                    stalled.append(False)
                else:
                    stalled.append(now - moved_at[k] >= stall_window)
            if stalled and all(stalled):
                logger.warning("drain: no log movement for %.0fs on any "
                               "live worker — giving up early",
                               stall_window)
                return
            time.sleep(self.cfg.resolved_poll_secs())
        logger.warning("drain timed out with workers still alive — "
                       "tearing down anyway")

    # -- the campaign ---------------------------------------------------

    def run(self) -> dict[str, Any]:
        from ..obsv.journal import summarize_disk_chaos
        cfg = self.cfg
        if cfg.root.exists():
            shutil.rmtree(cfg.root)  # stale trial state must not bleed in
        cfg.root.mkdir(parents=True, exist_ok=True)
        report_path = cfg.root / "chaos_report.jsonl"
        records: list[dict[str, Any]] = []

        # fault-free same-seed reference: ONE worker (every local
        # worker runs the identical independent program, so one
        # reference serves all of them)
        logger.info("chaos: reference run (fault-free, 1 worker)")
        ref = self._run_trial("reference", FaultPlan(), cfg.seed,
                              num_workers=1)
        if ref["outcome"] != "completed":
            raise ClusterError(
                f"chaos reference run did not complete: "
                f"{ref.get('error', ref['outcome'])} — no baseline to "
                "judge trials against")
        self.reference_dir = cfg.root / "reference" / "worker0"
        # the reference's measured boot (cold compile into the shared
        # cache) drives every trial's stall timeout; trials re-measure,
        # so warm boots keep tightening it
        self._measured_boot_s = ref.get("boot_s")
        if self._measured_boot_s:
            logger.info(
                "chaos: reference boot %.1fs → trial stall timeout %.1fs "
                "(was %.1fs un-measured)", self._measured_boot_s,
                cfg.resolved_stall_timeout_s(self._measured_boot_s),
                cfg.resolved_stall_timeout_s())

        reproducer: dict[str, Any] | None = None
        serving = cfg.payload == "serving"
        nw = cfg.trial_num_workers()
        for t in range(cfg.trials):
            if serving and cfg.broker and cfg.max_faults == 0:
                # broker-only campaign: the load trace IS the chaos —
                # a fault-free schedule isolates the autoscale path
                # (the gate: roster changes licensed, dropped==0)
                schedule = ChaosSchedule(seed=cfg.seed, trial=t,
                                         faults=())
            elif serving and cfg.network:
                # transport faults only: the proxies carry the whole
                # chaos load, so the protocol-hardening claims are
                # tested in isolation from process death
                schedule = generate_network_schedule(
                    cfg.seed, t, list(range(1, 1 + cfg.serve_replicas)),
                    max_faults=cfg.max_faults,
                    min_faults=max(2, cfg.min_faults))
            elif serving:
                # faults target the BOOT-TIME replicas only: a donor
                # trainer's slot may be traded away mid-run, and a
                # fault addressed to a dead id would no-op silently
                schedule = generate_serving_schedule(
                    cfg.seed, t, list(range(1, 1 + cfg.serve_replicas)),
                    cfg.serve_fault_window, cfg.step_window(),
                    max_faults=cfg.max_faults, min_faults=cfg.min_faults,
                    stall_ms_range=cfg.resolved_stall_ms_range())
            elif cfg.disk:
                # storage faults only: the workers' own durable-write
                # shims carry the whole chaos load, so the atomic-save
                # protocol's claims are tested in isolation from
                # supervisor-injected process faults (bar the one kill
                # the crash_rename pairing needs)
                schedule = generate_disk_schedule(
                    cfg.seed, t, cfg.num_workers, cfg.step_window(),
                    cfg.save_interval_steps,
                    max_faults=cfg.max_faults,
                    min_faults=max(3, cfg.min_faults))
            else:
                schedule = generate_schedule(
                    cfg.seed, t, cfg.num_workers, cfg.step_window(),
                    max_faults=cfg.max_faults, min_faults=cfg.min_faults,
                    stall_ms_range=cfg.resolved_stall_ms_range(),
                    resize_worlds=cfg.resolved_resize_worlds(),
                    resize_prob=cfg.resize_prob)
            logger.info("chaos trial %d/%d: %s", t + 1, cfg.trials,
                        schedule.describe())
            rel = f"trial{t:03d}"
            # the serving kwarg rides only when armed: train/shell
            # campaigns keep the historical _run_trial signature (test
            # harnesses subclass and override it)
            outcome = self._run_trial(rel, schedule.to_fault_plan(),
                                      cfg.seed, nw,
                                      measured_boot_s=self._measured_boot_s,
                                      **({"serving": True} if serving
                                         else {}))
            if outcome.get("boot_s"):
                # warm boots keep tightening (never loosening past the
                # cap) the next trial's detection window
                self._measured_boot_s = outcome["boot_s"]
            check = check_run(cfg.root / rel, outcome=outcome,
                              reference_dir=self.reference_dir)
            rec = {"event": "chaos_trial", "trial": t, "seed": cfg.seed,
                   "schedule": schedule.to_json_dict(),
                   "described": schedule.describe(),
                   "outcome": outcome["outcome"], "step": outcome.get("step"),
                   "target": cfg.until_step,
                   "duration_s": outcome["duration_s"],
                   # per-trial MTTR: detect→first-moved-step per episode
                   # (summarize_recovery_events), the chaos report's
                   # first-class recovery-latency metric
                   "mttr": (outcome.get("recovery") or {}).get("mttr"),
                   "boot_s": outcome.get("boot_s"),
                   "stall_timeout_s": (outcome.get("supervisor") or {})
                   .get("stall_timeout_s"),
                   # scheduled vs actually-fired: a fault that never
                   # landed (kill after run-end) must be visible, not
                   # silently green
                   "faults": count_fired_faults(cfg.root / rel, schedule),
                   # elastic world reshapes this trial performed
                   "reconfigures": ((outcome.get("recovery") or {})
                                    .get("reconfigure") or {}).get("count", 0),
                   "final_world": outcome.get("final_world"),
                   # serving mode: the load generator's one-line sweep
                   # summary (requests, dropped, p50/p99, rejects,
                   # model steps served) rides into the campaign report
                   "serving": outcome.get("serving"),
                   "serve_swaps": outcome.get("serve_swaps"),
                   # network-mode evidence (net_* firings by kind,
                   # dedup hits, retry percentiles); None off-mode
                   "net": outcome.get("net"),
                   # disk-mode evidence (storage-fault firings by
                   # action, failed/skipped saves, fallback restores);
                   # None off-mode
                   "disk": (summarize_disk_chaos(cfg.root / rel)
                            if cfg.disk else None),
                   "verdicts": check["verdicts"],
                   "violations": check["violations"]}
            if outcome.get("broker"):
                rec["broker"] = True
                rec["autoscale"] = outcome.get("autoscale")
            if outcome.get("discipline") is not None:
                rec["discipline"] = outcome["discipline"]
            if check["violations"] and cfg.shrink and reproducer is None:
                shrunk = self._shrink(t, schedule, check)
                rec["shrunk"] = shrunk
                reproducer = shrunk
            records.append(rec)
            # the one journal write that bypasses JsonlSink: same
            # debug-gated schema enforcement (obsv/schema.py)
            maybe_check_event(rec, source="chaos_report.jsonl")
            with open(report_path, "a") as fh:
                fh.write(json.dumps(rec, default=str) + "\n")

        from ..obsv.journal import summarize_chaos
        summary = summarize_chaos(report_path)
        summary["report_path"] = str(report_path)
        (cfg.root / "chaos_report.json").write_text(
            json.dumps(summary, default=str))
        return summary

    # -- shrinking ------------------------------------------------------

    def _shrink(self, trial: int, schedule: ChaosSchedule,
                check: dict[str, Any]) -> dict[str, Any]:
        """Greedily reduce the failing schedule: drop faults while the
        SAME invariant keeps failing (each probe is a full re-run +
        re-check), then emit the minimal reproducer FaultPlan JSON."""
        cfg = self.cfg
        violated = {v["invariant"] for v in check["violations"]}
        probes = [0]

        def still_fails(faults: tuple[ChaosFault, ...]) -> bool:
            cand = ChaosSchedule(seed=schedule.seed, trial=schedule.trial,
                                 faults=faults)
            rel = f"trial{trial:03d}_shrink{probes[0]:02d}"
            probes[0] += 1
            logger.info("shrink probe %s: %s", rel, cand.describe())
            outcome = self._run_trial(rel, cand.to_fault_plan(), cfg.seed,
                                      cfg.trial_num_workers(),
                                      measured_boot_s=self._measured_boot_s,
                                      **({"serving": True}
                                         if cfg.payload == "serving"
                                         else {}))
            got = check_run(cfg.root / rel, outcome=outcome,
                            reference_dir=self.reference_dir)
            return bool({v["invariant"] for v in got["violations"]}
                        & violated)

        minimal, spent = shrink_faults(schedule.faults, still_fails,
                                       max_probes=cfg.shrink_max_probes)
        mini = ChaosSchedule(seed=schedule.seed, trial=schedule.trial,
                             faults=minimal)
        plan_path = cfg.root / f"reproducer_trial{trial:03d}.json"
        plan_path.write_text(json.dumps(
            mini.to_fault_plan().to_json_dict(), indent=2))
        sched_path = cfg.root / f"reproducer_trial{trial:03d}_schedule.json"
        sched_path.write_text(json.dumps(mini.to_json_dict(), indent=2))
        return {"faults": [f.to_dict() for f in minimal],
                "described": mini.describe(),
                "invariants": sorted(violated), "probes": spent,
                "fault_plan_path": str(plan_path),
                "schedule_path": str(sched_path)}


def run_campaign(cfg: ChaosConfig) -> dict[str, Any]:
    return ChaosCampaign(cfg).run()
