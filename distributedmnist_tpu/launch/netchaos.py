"""Seeded per-endpoint TCP chaos proxy for the serving tier.

arXiv:1604.00981 treats slow links and slow workers as one phenomenon;
TF-Replicator (arXiv:1902.00465) argues the fault model must cover the
transport, not just the process. This module is the transport half of
the chaos harness: a :class:`ChaosProxy` interposed between clients
and ONE serving replica's socket, executing a small script of network
faults — added latency/jitter, a bandwidth cap, a connection reset
after N bytes (mid-stream for decode), a half-open blackhole, and a
timed bidirectional partition window — each deterministic in the
chaos run's ``(seed, trial)`` and journaled as a schema-declared
``event:"fault" action:"net_*"`` record (``obsv/schema.py``) so the
replay invariants can license exactly what they observe.

The proxy is transparent to the protocol: it re-resolves its upstream
from the replica's ``serve.json`` on EVERY accepted connection, so a
replica restarted onto a fresh ephemeral port keeps being reachable
through the same proxy port — the client never learns the difference.

Fault script grammar (one dict per fault, the ``net_faults`` value of
``launch.exec.FaultPlan`` keyed by the proxied worker):

``{"kind": "latency", "delay_ms": d, "jitter_ms": j}``
    delay every request-direction chunk by ``d + U[0, j)`` ms (seeded).
``{"kind": "bandwidth", "bytes_per_s": r}``
    pace response-direction forwarding at ``r`` bytes/s.
``{"kind": "reset", "after_bytes": n}``
    cut the FIRST connection whose response stream passes ``n`` bytes
    — exactly at byte ``n``, with an RST (SO_LINGER 0) — so a decode
    stream dies mid-generation, after tokens flowed, before the
    terminal. Fires once.
``{"kind": "blackhole", "conn": c, "hold_s": h}``
    accept connection ordinal ``c`` and never speak: no upstream, no
    bytes, socket held open ``h`` seconds (the half-open peer a
    client-side deadline must bound). Fires once.
``{"kind": "partition", "start_s": s, "duration_s": d}``
    a bidirectional partition window ``[s, s+d)`` seconds after the
    proxy accepts its FIRST connection (not after start() — replicas
    spend a long jax boot serving nothing, and the window must land
    under live load): live connections are torn down and new ones
    refused for the duration. Journaled when the window opens,
    whether or not traffic was in flight at that instant.
"""

from __future__ import annotations

import json
import random
import socket
import struct
import threading
import time
from pathlib import Path
from typing import Callable

from ..core.log import get_logger

logger = get_logger("netchaos")

NET_FAULT_KINDS = ("latency", "bandwidth", "reset", "blackhole",
                   "partition")

# poll granularity for every blocking socket op inside the proxy — no
# recv/accept ever blocks unbounded (the same discipline graftcheck's
# ``net`` checker enforces on the protocol ends)
_TICK_S = 0.25
_UPSTREAM_CONNECT_TIMEOUT_S = 2.0
_CHUNK = 65536


class NetChaosError(RuntimeError):
    """A malformed net-fault script."""


def serve_json_resolver(serve_json: str | Path
                        ) -> Callable[[], tuple[str, int] | None]:
    """Upstream resolver reading a replica's ``serve.json`` ready file
    — re-read per connection, so restarts onto new ports are followed;
    torn/missing files resolve to None (the connection is refused and
    the client's failover retries)."""
    path = Path(serve_json)

    def resolve() -> tuple[str, int] | None:
        try:
            d = json.loads(path.read_text())
            return d["host"], int(d["port"])
        except (OSError, ValueError, KeyError):
            return None

    return resolve


def _validate_scripts(scripts: list[dict]) -> list[dict]:
    out = []
    for s in scripts:
        kind = s.get("kind")
        if kind not in NET_FAULT_KINDS:
            raise NetChaosError(
                f"unknown net fault kind {kind!r} — valid kinds: "
                f"{NET_FAULT_KINDS}")
        out.append(dict(s))
    return out


class ChaosProxy:
    """One seeded fault-injecting TCP proxy in front of one replica.

    ``journal`` is any callable taking one record dict (e.g.
    ``CommandExecutor.journal``); every fault firing lands there as a
    schema-declared ``event:"fault" action:"net_*"`` record carrying
    the proxied ``worker`` — the same shape process faults use, so the
    ``serve_outcomes`` faulted-replica exemption and invariant 13
    license them with no special cases.
    """

    def __init__(self, resolve_upstream, scripts: list[dict], *,
                 worker: int, journal=None, seed: int = 0,
                 listen_host: str = "127.0.0.1"):
        if isinstance(resolve_upstream, (str, Path)):
            resolve_upstream = serve_json_resolver(resolve_upstream)
        elif isinstance(resolve_upstream, tuple):
            ep = (resolve_upstream[0], int(resolve_upstream[1]))
            resolve_upstream = lambda: ep  # noqa: E731
        self._resolve = resolve_upstream
        self.scripts = _validate_scripts(scripts)
        self.worker = int(worker)
        self._journal_fn = journal
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self._jlock = threading.Lock()
        self.listen_host = listen_host
        self.bound_port: int | None = None
        self._lsock: socket.socket | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conn_lock = threading.Lock()
        self._open_socks: set[socket.socket] = set()
        self._conn_count = 0
        self._fired: set[str] = set()
        self._reset_done = False
        self._partition_until = 0.0
        self._started_at = 0.0
        self._first_conn = threading.Event()

    # -- bookkeeping ---------------------------------------------------

    def _journal(self, record: dict) -> None:
        if self._journal_fn is None:
            return
        with self._jlock:
            self._journal_fn(record)

    def _fire_once(self, key: str, record: dict) -> None:
        """Journal a continuously-applied fault's record exactly once."""
        with self._conn_lock:
            if key in self._fired:
                return
            self._fired.add(key)
        self._journal(record)

    @property
    def fired(self) -> set[str]:
        return set(self._fired)

    def _script(self, kind: str) -> dict | None:
        for s in self.scripts:
            if s["kind"] == kind:
                return s
        return None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> int:
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind((self.listen_host, 0))
        lsock.listen(128)
        lsock.settimeout(_TICK_S)
        self._lsock = lsock
        self.bound_port = lsock.getsockname()[1]
        self._started_at = time.monotonic()
        t = threading.Thread(target=self._accept_loop,
                             name=f"netchaos-w{self.worker}", daemon=True)
        t.start()
        self._threads.append(t)
        part = self._script("partition")
        if part is not None:
            pt = threading.Thread(target=self._partition_timer,
                                  args=(float(part["start_s"]),
                                        float(part["duration_s"])),
                                  daemon=True)
            pt.start()
            self._threads.append(pt)
        logger.info("chaos proxy for worker %d on %s:%d (%d scripts)",
                    self.worker, self.listen_host, self.bound_port,
                    len(self.scripts))
        return self.bound_port

    def stop(self) -> None:
        self._stop.set()
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass
        with self._conn_lock:
            socks = list(self._open_socks)
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- partition window ----------------------------------------------

    def _partition_timer(self, start_s: float, duration_s: float) -> None:
        # the clock arms at FIRST live traffic, not proxy boot: the
        # window exists to cut a link the client is actually using
        while not self._stop.is_set() and not self._first_conn.wait(
                timeout=_TICK_S):
            pass
        if not self._stop.wait(timeout=start_s):
            with self._conn_lock:
                self._partition_until = time.monotonic() + duration_s
                socks = list(self._open_socks)
            for s in socks:
                _abort(s)
            self._journal({"event": "fault", "action": "net_partition",
                           "worker": self.worker, "time": time.time(),
                           "start_s": start_s, "duration_s": duration_s,
                           "conns_dropped": len(socks)})

    def _partitioned(self) -> bool:
        return time.monotonic() < self._partition_until

    # -- data path -----------------------------------------------------

    def _register(self, s: socket.socket) -> None:
        with self._conn_lock:
            self._open_socks.add(s)

    def _unregister(self, s: socket.socket) -> None:
        with self._conn_lock:
            self._open_socks.discard(s)

    def _accept_loop(self) -> None:
        assert self._lsock is not None
        while not self._stop.is_set():
            try:
                conn, _addr = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._conn_lock:
                n = self._conn_count
                self._conn_count += 1
            self._first_conn.set()
            if self._partitioned():
                _abort(conn)
                continue
            t = threading.Thread(target=self._handle_conn,
                                 args=(conn, n), daemon=True)
            t.start()
            self._threads.append(t)

    def _handle_conn(self, conn: socket.socket, n: int) -> None:
        conn.settimeout(_TICK_S)
        self._register(conn)
        try:
            bh = self._script("blackhole")
            if bh is not None and n == int(bh.get("conn", 0)) \
                    and f"blackhole:{n}" not in self._fired:
                self._fire_once(f"blackhole:{n}", {
                    "event": "fault", "action": "net_blackhole",
                    "worker": self.worker, "time": time.time(),
                    "hold_s": float(bh.get("hold_s", 5.0)), "conn": n})
                self._hold_half_open(conn, float(bh.get("hold_s", 5.0)))
                return
            ep = self._resolve()
            if ep is None:
                _abort(conn)
                return
            try:
                up = socket.create_connection(
                    ep, timeout=_UPSTREAM_CONNECT_TIMEOUT_S)
            except OSError:
                _abort(conn)
                return
            up.settimeout(_TICK_S)
            self._register(up)
            done = threading.Event()
            t = threading.Thread(target=self._pump_up,
                                 args=(conn, up, n, done), daemon=True)
            t.start()
            try:
                self._pump_down(up, conn, n)
            finally:
                done.set()
                for s in (up, conn):
                    try:
                        s.close()
                    except OSError:
                        pass
                self._unregister(up)
                t.join(timeout=5.0)
        finally:
            self._unregister(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _hold_half_open(self, conn: socket.socket, hold_s: float) -> None:
        """The half-open peer: the socket stays open, nothing is ever
        sent or read — the far end's deadline must bound the stall."""
        end = time.monotonic() + hold_s
        while not self._stop.is_set() and time.monotonic() < end:
            time.sleep(min(_TICK_S, max(0.0, end - time.monotonic())))

    def _pump_up(self, conn: socket.socket, up: socket.socket, n: int,
                 done: threading.Event) -> None:
        """client → server, with the latency fault applied."""
        lat = self._script("latency")
        while not self._stop.is_set() and not done.is_set():
            if self._partitioned():
                break
            try:
                chunk = conn.recv(_CHUNK)
            except socket.timeout:
                continue
            except OSError:
                break
            if not chunk:
                break
            if lat is not None:
                with self._rng_lock:
                    jit = self._rng.random() * float(
                        lat.get("jitter_ms", 0.0))
                delay = (float(lat.get("delay_ms", 0.0)) + jit) / 1e3
                self._fire_once("latency", {
                    "event": "fault", "action": "net_latency",
                    "worker": self.worker, "time": time.time(),
                    "delay_ms": float(lat.get("delay_ms", 0.0)),
                    "jitter_ms": float(lat.get("jitter_ms", 0.0)),
                    "conn": n})
                time.sleep(delay)
            try:
                up.sendall(chunk)
            except OSError:
                break
        try:
            up.shutdown(socket.SHUT_WR)
        except OSError:
            pass

    def _pump_down(self, up: socket.socket, conn: socket.socket,
                   n: int) -> None:
        """server → client, with bandwidth pacing and the mid-stream
        reset applied."""
        bw = self._script("bandwidth")
        rst = self._script("reset")
        passed = 0
        while not self._stop.is_set():
            if self._partitioned():
                _abort(conn)
                return
            try:
                chunk = up.recv(_CHUNK)
            except socket.timeout:
                continue
            except OSError:
                return
            if not chunk:
                return
            if rst is not None and not self._reset_done:
                cut = int(rst["after_bytes"])
                if passed + len(chunk) > cut:
                    with self._conn_lock:
                        if self._reset_done:
                            cut = -1
                        else:
                            self._reset_done = True
                    if cut >= 0:
                        head = chunk[:max(0, cut - passed)]
                        if head:
                            try:
                                conn.sendall(head)
                            except OSError:
                                pass
                        passed += len(head)
                        self._journal({
                            "event": "fault", "action": "net_reset",
                            "worker": self.worker, "time": time.time(),
                            "after_bytes": int(rst["after_bytes"]),
                            "bytes_passed": passed,
                            "mid_stream": passed > 0, "conn": n})
                        _abort(conn)
                        return
            try:
                conn.sendall(chunk)
            except OSError:
                return
            passed += len(chunk)
            if bw is not None:
                self._fire_once("bandwidth", {
                    "event": "fault", "action": "net_bandwidth",
                    "worker": self.worker, "time": time.time(),
                    "bytes_per_s": int(bw["bytes_per_s"]), "conn": n})
                time.sleep(len(chunk) / float(int(bw["bytes_per_s"])))


def _abort(s: socket.socket) -> None:
    """Close with RST (SO_LINGER 0) — the far end sees ECONNRESET, not
    a graceful FIN, which is what a real partition/reset looks like."""
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     struct.pack("ii", 1, 0))
    except OSError:
        pass
    try:
        s.close()
    except OSError:
        pass


def start_proxies(cluster_root: str | Path,
                  net_faults: dict[int, list[dict]], *,
                  journal=None, seed: int = 0) -> dict[int, ChaosProxy]:
    """One proxy per net-faulted worker, upstream-resolved from that
    worker's ``serve.json`` under ``cluster_root``. Returns
    ``{worker: started proxy}`` — callers route client endpoints for
    those workers through ``proxy.bound_port`` and ``stop()`` each when
    the trial ends."""
    root = Path(cluster_root)
    out: dict[int, ChaosProxy] = {}
    for worker, scripts in sorted(net_faults.items()):
        p = ChaosProxy(root / f"worker{worker}" / "serve.json", scripts,
                       worker=worker, journal=journal,
                       seed=seed * 7_000_003 + worker)
        p.start()
        out[worker] = p
    return out
