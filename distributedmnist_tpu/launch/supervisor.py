"""Self-healing cluster supervisor: fault *injection* → fault *recovery*.

PR 1 gave the cluster engine a fault plan (kill / hang / corrupt a
worker mid-run) and a fail-fast driver: :func:`~.cluster.wait_until_step`
raises the moment every worker is gone, and a single lost worker simply
stalls the synchronous run. The source paper's whole regime
(arXiv:1604.00981 backup workers) and the systems it grew into
(TF-Replicator, arXiv:1902.00465 §"automatic recovery"; TensorFlow,
arXiv:1605.08695 §fault tolerance) treat replica loss as a *runtime
event to recover from*, not a terminal condition. This module is that
layer:

* **Liveness tracking** — per-worker alive/dead from ``status()`` on
  every poll tick, plus per-worker log *progress* (``worker_progress``)
  so a hung worker (SIGSTOP, wedged I/O — alive to ``kill -0``, silent
  in its log) is detected by stall timeout, the failure liveness probes
  structurally cannot see.
* **Automatic restart** — a dead or hung worker is restarted through
  ``backend.restart_worker`` under a bounded per-worker budget with
  exponential backoff (a worker that dies on boot must not be respawned
  in a hot loop). The restarted process resumes from its latest
  *loadable* checkpoint — the worker's own Trainer handles
  corrupt-latest fallback (train/checkpoint.py), so a checkpoint torn
  at the worst moment costs one checkpoint interval, not the run.
* **Degraded-quorum continuation** — the run stays up while
  ``workers_alive >= quorum`` (the cluster-level analogue of the
  k-of-n aggregation masks in ``parallel/policies.py``): a worker whose
  restart budget is exhausted degrades the cluster instead of killing
  the run; only dropping below quorum — with nothing left to restart —
  raises.
* **Structured recovery events** — every transition (detect → restart →
  resume, quorum changes, budget exhaustion) is journaled as an
  ``event: "recovery"`` record in the same command journal the executor
  writes, so ``obsv.journal.summarize_recovery`` reconstructs the whole
  episode from the artifact alone.
"""

from __future__ import annotations

import dataclasses
import json
import time
from pathlib import Path
from typing import Any

from ..core.log import get_logger
from .cluster import (ClusterBackend, ClusterError,
                      worker_resumed_step_since_spawn)

logger = get_logger("supervisor")


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Recovery policy knobs (JSON-loadable, like the cluster configs).

    ``quorum``: minimum live workers for the run to be considered
    healthy enough to continue — the all-or-nothing fail-fast of plain
    ``wait_until_step`` is ``quorum == num_workers`` with
    ``max_restarts_per_worker == 0``.
    """

    quorum: int = 1
    max_restarts_per_worker: int = 3
    restart_backoff_s: float = 0.5
    restart_backoff_mult: float = 2.0
    max_restart_backoff_s: float = 30.0
    # 0 disables hang detection; otherwise a worker whose log makes no
    # progress for this long (while the pid stays alive) is killed and
    # restarted under the same budget
    stall_timeout_s: float = 0.0
    # Warm standbys (ROADMAP item 5): keep N pre-booted spare processes
    # parked (imported jax, mesh up, train step precompiled) on
    # backends that support them; a due restart PROMOTES a ready spare
    # — handing it the dead worker's logdir to resume from — instead of
    # cold-starting, and the pool back-fills asynchronously. Promotions
    # ride the same per-worker restart budget (journaled as
    # ``action: "restart", via: "standby"``). 0 = off.
    standby_workers: int = 0
    # The run's schedule seed (chaos campaigns / `--seed`): stamped on
    # every recovery event so a journaled episode is replayable from
    # the artifact alone — the seed regenerates the fault schedule and
    # the jitter sequence that produced it. None = unseeded run.
    seed: int | None = None
    # -- elastic world-size reconfiguration (ROADMAP item 2) ----------
    # Below quorum with every restart budget exhausted, an elastic run
    # RESHAPES instead of aborting: survivors are drained (SIGTERM →
    # checkpoint flush), the backend roster shrinks to them, quorum
    # rescales (see rescaled_quorum), and the run relaunches as the
    # smaller world resuming from the last loadable step. Off by
    # default — aborting is the safe answer when nobody opted in.
    elastic: bool = False
    # smallest world an elastic shrink may produce; fewer survivors
    # than this aborts exactly as a non-elastic run would
    min_workers: int = 1
    # bound on reconfigures per supervised run (a crash-looping world
    # must not shrink one worker at a time forever)
    max_reconfigures: int = 2
    # how long a graceful drain (SIGTERM → flush → exit) may take
    # before stragglers are killed outright
    reconfigure_drain_s: float = 30.0

    def __post_init__(self) -> None:
        if self.quorum < 1:
            raise ClusterError(f"quorum must be >= 1, got {self.quorum}")
        if self.max_restarts_per_worker < 0:
            raise ClusterError("max_restarts_per_worker must be >= 0")
        if self.min_workers < 1:
            raise ClusterError(f"min_workers must be >= 1, "
                               f"got {self.min_workers}")

    def rescaled_quorum(self, new_world: int) -> int:
        """The effective quorum for a resized world, clamped into
        ``[1, new_world]``: a 3→2 shrink with quorum=3 must not abort
        the instant it relaunches (the quorum was specified against
        the OLD world). Journaled on every reconfigure so the policy
        actually applied is artifact-visible; re-specify explicitly by
        supervising the resized cluster with a fresh config if a
        different policy is wanted."""
        return max(1, min(self.quorum, new_world))

    @classmethod
    def from_file(cls, path: str | Path) -> "SupervisorConfig":
        d = json.loads(Path(path).read_text())
        unknown = set(d) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise ClusterError(
                f"unknown supervisor config keys: {sorted(unknown)}")
        return cls(**d)

    def backoff_s(self, restarts_so_far: int) -> float:
        return min(self.max_restart_backoff_s,
                   self.restart_backoff_s
                   * self.restart_backoff_mult ** restarts_so_far)


class ClusterSupervisor:
    """Wraps any :class:`~.cluster.ClusterBackend` and keeps its run
    alive through worker loss, hangs, and checkpoint corruption."""

    def __init__(self, backend: ClusterBackend,
                 cfg: SupervisorConfig | None = None):
        self.backend = backend
        self.cfg = cfg or SupervisorConfig()
        self.events: list[dict[str, Any]] = []
        self._restarts: dict[int, int] = {}
        # open recovery episodes: restarted workers whose own log has
        # not moved yet, plus the MTTR anchors (wall clock, matching
        # event "time" stamps) their eventual resume closes with.
        # Instance state, not supervise-locals: a run that reaches its
        # target while a restarted worker is still mid-jax-boot leaves
        # the episode OPEN, and the caller (the chaos drain — exactly
        # the window where that worker finishes booting) closes it via
        # close_episode so its MTTR is still journaled.
        self._watch_resume: set[int] = set()
        self._detect_t: dict[int, float] = {}
        self._respawn_t: dict[int, float] = {}
        # open world-reshape transition: set by reconfigure(), closed
        # (with the drain→first-moved-step latency) when a relaunched
        # worker's OWN first step record lands — the MTTR analogue for
        # a world change. Survives into supervise_until_step so a
        # manual reconfigure-then-supervise flow still closes it.
        self._reconf_open: dict[str, Any] | None = None
        self.reconfigures = 0

    # -- event plumbing -------------------------------------------------

    def _record(self, rec: dict[str, Any]) -> None:
        if self.cfg.seed is not None:
            rec.setdefault("seed", self.cfg.seed)
        self.events.append(rec)
        ex = getattr(self.backend, "exec", None)
        if ex is not None and hasattr(ex, "journal"):
            ex.journal(rec)

    def _event(self, action: str, **fields: Any) -> None:
        logger.info("recovery: %s %s", action,
                    {k: v for k, v in fields.items() if k != "time"})
        self._record({"event": "recovery", "layer": "supervisor",
                      "action": action, "time": time.time(), **fields})

    def _reconf_event(self, action: str, **fields: Any) -> None:
        """World-reshape transitions get their OWN journal event type
        (``event: "reconfigure"``) — the causal license the chaos
        cross-world resume invariant requires: a run whose world
        changed without one of these fails replay."""
        logger.info("reconfigure: %s %s", action,
                    {k: v for k, v in fields.items() if k != "time"})
        self._record({"event": "reconfigure", "layer": "supervisor",
                      "action": action, "time": time.time(), **fields})

    def _mttr_fields(self, k: int, at: float | None = None
                     ) -> dict[str, Any]:
        """The detect→respawned→first-moved-step latencies a resume
        event closes its recovery episode with — MTTR as a first-class
        journal fact (obsv.journal.summarize_recovery_events computes
        the percentiles). ``at`` is when the first moved step actually
        HAPPENED where the caller knows it (the step record's own
        timestamp) — observation time is quantized to the poll cadence
        and would overstate short episodes by up to a whole tick."""
        out: dict[str, Any] = {}
        now = time.time() if at is None else at
        if k in self._detect_t:
            out["detected_at"] = self._detect_t[k]
            out["mttr_s"] = round(now - self._detect_t[k], 3)
        if k in self._respawn_t:
            out["respawned_at"] = self._respawn_t[k]
            out["resume_after_respawn_s"] = round(
                now - self._respawn_t[k], 3)
        return out

    @property
    def open_episodes(self) -> set[int]:
        """Workers restarted during the last supervised run whose own
        log has not been seen moving yet — episodes without a closing
        ``resume`` event. Non-empty after a run that completed while a
        restart was still booting; the caller closes them from its own
        post-run observation window (:meth:`close_episode`)."""
        return set(self._watch_resume)

    def close_episode(self, k: int, step: int | None = None,
                      at: float | None = None) -> None:
        """Journal the ``resume`` that closes worker ``k``'s open
        recovery episode — called by whoever observes the restarted
        worker's first log movement AFTER the supervised loop returned
        (the chaos drain). ``at``: the first moved step's own wall
        timestamp when the caller holds the record (else observation
        time). No-op for workers without an open episode, so callers
        can sweep unconditionally."""
        if k in self._watch_resume:
            self._watch_resume.discard(k)
            self._event("resume", worker=k, step=step,
                        **self._mttr_fields(k, at))

    def summary(self) -> dict[str, Any]:
        """Aggregate this run's recovery episode — the SAME aggregation
        ``obsv.journal.summarize_recovery`` applies to the journal,
        over the in-memory events, plus the live restart counters and
        any world-reshape transitions."""
        from ..obsv.journal import (summarize_reconfigure_events,
                                    summarize_recovery_events)
        recovery = [e for e in self.events
                    if e.get("event", "recovery") == "recovery"]
        out = {**summarize_recovery_events(recovery),
               "restarts_by_worker": dict(self._restarts)}
        reconf = [e for e in self.events
                  if e.get("event") == "reconfigure"]
        if reconf:
            out["reconfigure"] = summarize_reconfigure_events(reconf)
        return out

    # -- elastic world-size reconfiguration (ROADMAP item 2) ------------

    def _can_reconfigure(self) -> bool:
        """Whether the backend actually OVERRIDES the elastic verb.
        ``hasattr`` is useless here — the base class defines
        ``reconfigure`` (raising NotImplementedError), and discovering
        that AFTER draining the survivors would turn a clean
        below-quorum abort into a dead cluster with no journal."""
        fn = getattr(type(self.backend), "reconfigure", None)
        return (callable(fn)
                and fn is not ClusterBackend.reconfigure)

    def reconfigure(self, new_num_workers: int, trigger: str = "manual",
                    survivors: list[int] | None = None,
                    poll_secs: float = 0.5) -> dict[str, Any]:
        """Drain → reshape → relaunch: the cluster resizes itself
        instead of aborting (the TF-Replicator ending of the source
        paper's backup-workers story).

        1. **Drain**: live workers get SIGTERM (``stop_all``) — a
           preemption-aware trainer finishes its step, flushes a
           checkpoint, and exits resumable; stragglers past
           ``cfg.reconfigure_drain_s`` are killed (their latest cadence
           save is the resume point).
        2. **Reshape**: ``backend.reconfigure`` keeps the survivors
           (shrink prefers LIVE workers when none are named) or grows
           fresh seeded workers; quorum rescales per
           ``cfg.rescaled_quorum`` and the effective value is
           journaled.
        3. **Relaunch**: grown slots promote a ready warm standby when
           the backend has one; everything else respawns cold. Each
           worker's own resume-from-checkpoint logic — the
           mesh-portable ``restore_for_topology`` path — decides where
           it continues.

        Every transition is journaled as ``event: "reconfigure"``
        (begin → relaunched → resume) with old/new world, trigger, and
        the MTTR-style drain→first-moved-step latency closed by the
        supervise loop (or :meth:`close_reconfigure`)."""
        backend = self.backend
        st = backend.status() or {"workers": []}
        roster = st.get("workers", [])
        old_world = len(roster)
        if survivors is None:
            if new_num_workers >= old_world:
                survivors = [w["worker"] for w in roster]  # grow: keep all
            else:
                # shrink: prefer live workers, then lowest ids
                alive_ids = sorted(w["worker"] for w in roster
                                   if w.get("alive"))
                dead_ids = sorted(w["worker"] for w in roster
                                  if not w.get("alive"))
                survivors = sorted(
                    (alive_ids + dead_ids)[:new_num_workers])
        t0 = time.time()
        new_q = self.cfg.rescaled_quorum(new_num_workers)
        # open recovery episodes are SUPERSEDED by the world reshape:
        # the drain/relaunch below replaces any in-flight restart, so
        # no per-worker resume will ever close them — journal the
        # supersede so summarize_mttr files them as neither recovered
        # nor unrecovered (the transition's own reconfigure_s carries
        # the latency evidence from here on)
        for k in sorted(self._watch_resume):
            self._event("episode_superseded", worker=k,
                        by="reconfigure", trigger=trigger)
        self._watch_resume.clear()
        self._detect_t.clear()
        self._respawn_t.clear()
        self._reconf_event("begin", old_world=old_world,
                           new_world=new_num_workers, trigger=trigger,
                           quorum=self.cfg.quorum, effective_quorum=new_q,
                           survivors=sorted(survivors))
        # (1) graceful drain, bounded. The wait must cover the whole
        # process GROUP where the backend can tell (wait_drained): the
        # recorded pid is a shell leader that dies to the group SIGTERM
        # instantly while the trainer behind it is still flushing its
        # preemption checkpoint — a status()-only wait would SIGKILL
        # that flush mid-write and lose the resume point.
        if hasattr(backend, "stop_all"):
            backend.stop_all()
            if hasattr(backend, "wait_drained"):
                backend.wait_drained(self.cfg.reconfigure_drain_s,
                                     poll_secs)
            else:
                deadline = time.monotonic() + self.cfg.reconfigure_drain_s
                while time.monotonic() < deadline:
                    st2 = backend.status()
                    if st2 is None or not any(w.get("alive")
                                              for w in st2["workers"]):
                        break
                    time.sleep(poll_secs)
        # straggler kill is PER WORKER: kill_all("all") also reaps
        # parked standbys, and the warm grow path below needs them
        # alive to promote
        if roster:
            for w in roster:
                backend.kill_all(worker=str(w["worker"]))
        else:
            backend.kill_all()
        # (2) reshape + quorum rescale
        rec = backend.reconfigure(new_num_workers, survivors=survivors)
        if new_q != self.cfg.quorum:
            self.cfg = dataclasses.replace(self.cfg, quorum=new_q)
        # (3) relaunch — standbys first for GROWN slots (the warm grow
        # path: a parked, precompiled spare adopts the seeded logdir)
        grown = {int(k) for k in (rec.get("grown") or {})}
        via: dict[int, str] = {}
        for k in rec.get("workers", []):
            promoted = False
            if k in grown and hasattr(backend, "promote_standby"):
                try:
                    promoted = bool(backend.promote_standby(k))
                except Exception as e:
                    if not isinstance(e, NotImplementedError):
                        logger.warning(
                            "standby promotion for grown worker %d "
                            "failed (%s: %s) — cold spawn", k,
                            type(e).__name__, e)
                    promoted = False
            if not promoted:
                backend.restart_worker(k)
            via[k] = "standby" if promoted else "respawn"
        drain_s = round(time.time() - t0, 3)
        self._reconf_event("relaunched", old_world=old_world,
                           new_world=new_num_workers, trigger=trigger,
                           drain_s=drain_s, workers=sorted(via),
                           via={str(k): v for k, v in via.items()},
                           grown=sorted(grown))
        self.reconfigures += 1
        self._reconf_open = {"t0": t0, "old_world": old_world,
                             "new_world": new_num_workers,
                             "trigger": trigger, "workers": set(via)}
        return rec

    def close_reconfigure(self, k: int, step: int | None = None,
                          at: float | None = None) -> None:
        """Journal the ``resume`` closing the open reconfigure
        transition: the FIRST relaunched worker whose own step record
        lands defines the drain→first-moved-step latency (the world
        change is over once the resized world trains). No-op without
        an open transition."""
        ro = self._reconf_open
        if not ro or k not in ro["workers"]:
            return
        now = time.time() if at is None else at
        self._reconf_open = None
        self._reconf_event("resume", worker=k, step=step,
                           old_world=ro["old_world"],
                           new_world=ro["new_world"],
                           trigger=ro["trigger"],
                           reconfigure_s=round(now - ro["t0"], 3))

    # -- the supervised run ---------------------------------------------

    def run_until_step(self, target: int, poll_secs: float = 1.0,
                       timeout_secs: float = 24 * 3600.0,
                       target_worker: int | None = None,
                       on_tick: Any = None) -> dict[str, Any]:
        """Launch training and supervise it to ``target`` steps; the
        cluster is stopped on EVERY exit path (success, below-quorum
        failure, timeout, Ctrl-C)."""
        self.backend.run_train()
        try:
            return self.supervise_until_step(target, poll_secs, timeout_secs,
                                             target_worker=target_worker,
                                             on_tick=on_tick)
        finally:
            self.backend.kill_all()

    def supervise_until_step(self, target: int, poll_secs: float = 1.0,
                             timeout_secs: float = 24 * 3600.0,
                             target_worker: int | None = None,
                             on_tick: Any = None) -> dict[str, Any]:
        """Supervise the running cluster until ``target`` progress.

        ``target_worker``: count progress toward the target from ONE
        worker's log only (liveness/stall/restart still cover every
        worker). What a mixed-payload cluster needs — a serving
        topology's replicas heartbeat their request counts into the
        same progress channel, and the run is over when the
        PUBLISHER's train step hits the target, not when some busy
        replica has served ``target`` requests. None = the fastest
        worker (the historical behavior).

        ``on_tick``: an optional ``callable(poll_dict) -> bool`` run
        once per poll tick, after the target check and before failure
        detection — the seam the resource broker (launch/broker.py)
        plugs into. It runs ON the supervise thread, so a roster change
        it performs cannot race this loop's per-worker trackers; a
        True return declares the roster changed and resets them (the
        same discipline as this loop's own reconfigures)."""
        cfg = self.cfg
        deadline = time.monotonic() + timeout_secs
        pending_restart: dict[int, float] = {}  # worker -> due monotonic
        exhausted: set[int] = set()
        last_alive: int | None = None
        # hang detection state: last observed step + when it changed
        last_progress: dict[int, int] = {}
        last_progress_t: dict[int, float] = {}
        # fresh episode state per supervised run (instance-level so a
        # post-run caller can close episodes the run left open);
        # _reconf_open deliberately survives — a manual reconfigure
        # followed by supervise still closes its transition here
        self._watch_resume = set()
        self._detect_t = {}
        self._respawn_t = {}
        watch_resume = self._watch_resume

        # the elastic resize fault (FaultPlan.resize_world_at_step):
        # cluster-level, so the SUPERVISOR executes it — the backend's
        # poll hook only sees single workers
        resize: tuple[int, int] | None = None
        ex = getattr(self.backend, "exec", None)
        if ex is not None and getattr(ex, "fault_plan", None) is not None:
            resize = ex.fault_plan.resize_world_at_step
        resize_fired = False

        def reset_roster_state() -> None:
            """After a reconfigure the roster changed under the loop:
            every per-worker tracker restarts from the relaunched
            world's own observations (a survivor's pre-drain log tail
            must not read as progress, a dropped worker's exhausted
            budget must not linger)."""
            nonlocal last_alive
            pending_restart.clear()
            exhausted.clear()
            last_progress.clear()
            last_progress_t.clear()
            watch_resume.clear()
            last_alive = None

        if (cfg.standby_workers > 0
                and hasattr(self.backend, "ensure_standbys")):
            # async: the spares boot jax + precompile in the background
            # while the run proceeds; only READY spares get promoted.
            # The pool is an OPTIMIZATION — a spawn failure (fork
            # pressure under a chaos campaign, exhausted fds) must
            # degrade to standby-less cold restarts, never abort the
            # run the standbys exist to protect.
            try:
                self.backend.ensure_standbys(cfg.standby_workers)
                self._event("standbys_requested",
                            count=cfg.standby_workers)
            except Exception as e:
                logger.warning("could not provision standbys (%s: %s) — "
                               "continuing without the warm pool",
                               type(e).__name__, e)
                self._event("standbys_unavailable",
                            error=f"{type(e).__name__}: {e}")

        def schedule_restart(k: int, now: float) -> None:
            """Shared dead/hung bookkeeping: a worker entering recovery
            is no longer awaiting resume; within budget it gets a
            backed-off restart slot, past it the cluster degrades."""
            watch_resume.discard(k)
            n_prior = self._restarts.get(k, 0)
            if n_prior >= cfg.max_restarts_per_worker:
                exhausted.add(k)
                self._event("restart_budget_exhausted", worker=k,
                            restarts=n_prior)
            else:
                backoff = cfg.backoff_s(n_prior)
                pending_restart[k] = now + backoff
                self._event("restart_scheduled", worker=k,
                            attempt=n_prior + 1, backoff_s=backoff)

        can_progress = hasattr(self.backend, "worker_progress")
        while True:
            got = self.backend.poll()
            if got is None:  # dry-run backend: argvs recorded, nothing to do
                return {"step": target, "record": None, "dry_run": True}
            # target progress = the FASTEST worker's log when the backend
            # can report per-worker progress (poll tails only worker 0 —
            # a degraded run whose permanently-lost worker IS worker 0
            # must still be able to finish on the survivors); reuse the
            # sweep poll() already ran for its fault triggers when it
            # attached one
            progress = got.get("worker_progress")
            if progress is None and can_progress:
                progress = self.backend.worker_progress()
            now = time.monotonic()
            # ---- per-worker log movement: resume attribution ----------
            # BEFORE the target check: an episode whose restarted
            # worker's log moves on the very tick the run completes
            # must still get its closing resume (and MTTR) journaled
            moved: set[int] = set()
            if progress is not None:
                for k, step_k in progress.items():
                    if step_k != last_progress.get(k):
                        last_progress[k] = step_k
                        last_progress_t[k] = now
                        moved.add(k)
                        if k in watch_resume and step_k >= 0:
                            # the restarted worker's own log moved: THIS
                            # step (not worker 0's) is where it resumed
                            self.close_episode(k, step_k)
            # ---- open reconfigure transition: first-moved-step -------
            if self._reconf_open is not None:
                snapshot = got.get("workers") or []
                closed_by_log = False
                for w in snapshot:
                    if (w.get("worker") in self._reconf_open["workers"]
                            and w.get("logdir")):
                        closed_by_log = True
                        r = worker_resumed_step_since_spawn(w)
                        if r is not None:
                            self.close_reconfigure(w["worker"], *r)
                            break
                if not closed_by_log and progress is not None:
                    # backends without logdir evidence (scripted tests):
                    # any tracked worker's log movement counts
                    for k in sorted(moved):
                        if (k in self._reconf_open["workers"]
                                and progress.get(k, -1) >= 0):
                            self.close_reconfigure(k, progress[k])
                            break
            if target_worker is not None:
                # poll()'s headline step is worker 0's tail; only trust
                # it for the target when worker 0 IS the target worker
                best_step = (progress or {}).get(
                    target_worker,
                    got["step"] if target_worker == 0 else -1)
            else:
                best_step = got["step"]
                if progress:
                    best_step = max(best_step, *progress.values())
            if best_step >= target:
                if progress is None and watch_resume:
                    # no per-worker log signal on this backend: a
                    # restarted worker that shows alive at completion
                    # counts as resumed (same rule as the in-run
                    # fallback below)
                    final = got.get("workers")
                    if final is None:
                        final = (self.backend.status() or {}).get(
                            "workers", [])
                    for w in final:
                        if w.get("alive"):
                            self.close_episode(w["worker"], got["step"])
                self._event("target_reached", step=best_step)
                got["step"] = best_step
                got["recovery"] = self.summary()
                return got
            # ---- elastic resize fault (after the target check: a run
            # that already finished does not resize) -------------------
            if (resize is not None and not resize_fired
                    and best_step >= resize[0]):
                resize_fired = True
                if (self.reconfigures < cfg.max_reconfigures
                        and self._can_reconfigure()):
                    self.reconfigure(resize[1], trigger="fault_plan",
                                     poll_secs=min(poll_secs, 0.5))
                    cfg = self.cfg  # quorum may have rescaled
                    reset_roster_state()
                    time.sleep(poll_secs)
                    continue
            # ---- broker tick ------------------------------------------
            # A True return declares the roster changed under us: the
            # per-worker trackers describe workers that may no longer
            # exist, so they reset exactly as after this loop's own
            # reconfigures. A broken callback must not take down the
            # supervision it rides on.
            if on_tick is not None:
                try:
                    tick_changed = bool(on_tick(got))
                except Exception:
                    logger.exception("on_tick callback failed — "
                                     "supervision continues without it "
                                     "this tick")
                    tick_changed = False
                if tick_changed:
                    cfg = self.cfg
                    reset_roster_state()
                    time.sleep(poll_secs)
                    continue
            # reuse the liveness snapshot poll() already took this tick
            # (LocalProcessCluster attaches it); only backends that
            # don't get the separate status() sweep
            workers = got.get("workers")
            if workers is None:
                workers = (self.backend.status() or {}).get("workers", [])
            alive = {w["worker"]: w["alive"] for w in workers}
            n_alive = sum(alive.values())

            # ---- detect newly dead workers ----------------------------
            for k, is_alive in alive.items():
                if is_alive or k in pending_restart or k in exhausted:
                    continue
                self._detect_t[k] = time.time()
                self._event("detect", worker=k, at_step=got["step"],
                            kind="dead")
                schedule_restart(k, now)

            # ---- hang detection over workers whose log did NOT move --
            if progress is not None:
                for k, step_k in progress.items():
                    if (k not in moved
                            and cfg.stall_timeout_s > 0
                            and alive.get(k) and k not in pending_restart
                            and k not in exhausted
                            and now - last_progress_t.get(k, now)
                            >= cfg.stall_timeout_s):
                        self._detect_t[k] = time.time()
                        self._event("detect", worker=k, at_step=got["step"],
                                    kind="hung", stalled_at=step_k)
                        # a hung pid must die before its slot restarts
                        self.backend.kill_all(worker=str(k))
                        schedule_restart(k, now)
            elif watch_resume:
                # no progress signal on this backend: a restarted worker
                # that shows alive again counts as resumed
                for k in list(watch_resume):
                    if alive.get(k):
                        self.close_episode(k, got["step"])

            # ---- perform due restarts ---------------------------------
            for k in [k for k, due in pending_restart.items() if now >= due]:
                del pending_restart[k]
                self._restarts[k] = self._restarts.get(k, 0) + 1
                # standby fast path first: promoting a parked,
                # precompiled spare skips process boot AND compile; no
                # ready spare (or no backend support) → cold respawn
                promoted = False
                if (cfg.standby_workers > 0
                        and hasattr(self.backend, "promote_standby")):
                    try:
                        promoted = bool(self.backend.promote_standby(k))
                    except Exception as e:
                        # the fast path failing (torn activation file,
                        # spawn pressure) must not cost the restart
                        # itself — fall through to the cold respawn
                        if not isinstance(e, NotImplementedError):
                            logger.warning(
                                "standby promotion for worker %d failed "
                                "(%s: %s) — cold respawn", k,
                                type(e).__name__, e)
                        promoted = False
                if not promoted:
                    try:
                        self.backend.restart_worker(k)
                    except NotImplementedError:
                        exhausted.add(k)
                        self._event("restart_budget_exhausted", worker=k,
                                    restarts=self._restarts[k] - 1,
                                    reason="backend cannot restart workers")
                        continue
                self._respawn_t[k] = time.time()
                extra = {}
                if k in self._detect_t:
                    extra["detected_at"] = self._detect_t[k]
                    extra["respawn_s"] = round(
                        self._respawn_t[k] - self._detect_t[k], 3)
                self._event("restart", worker=k,
                            attempt=self._restarts[k], at_step=got["step"],
                            via="standby" if promoted else "respawn",
                            **extra)
                watch_resume.add(k)
                last_progress_t[k] = time.monotonic()

            # ---- quorum accounting ------------------------------------
            if n_alive != last_alive:
                if last_alive is not None or n_alive < len(alive):
                    self._event("quorum_transition", workers_alive=n_alive,
                                num_workers=len(alive), quorum=cfg.quorum,
                                degraded=n_alive < len(alive))
                last_alive = n_alive
            # abort only when BELOW quorum with no recovery in flight:
            # pending_restart covers scheduled-not-yet-performed restarts,
            # watch_resume the just-restarted workers this tick's (stale)
            # liveness snapshot predates — aborting on that snapshot
            # would kill the run right after the restart that saved it
            if (workers and n_alive < cfg.quorum
                    and not pending_restart and not watch_resume):
                # elastic shrink: permanent capacity loss reshapes the
                # world to the survivors instead of degraded-quorum
                # forever / an abort — the cluster resizes itself
                survivors = sorted(k for k, a in alive.items() if a)
                if (cfg.elastic
                        and self.reconfigures < cfg.max_reconfigures
                        and len(survivors) >= cfg.min_workers
                        and len(survivors) < len(alive)
                        and self._can_reconfigure()):
                    self.reconfigure(len(survivors),
                                     trigger="below_quorum",
                                     survivors=survivors,
                                     poll_secs=min(poll_secs, 0.5))
                    cfg = self.cfg  # quorum rescaled for the new world
                    reset_roster_state()
                    time.sleep(poll_secs)
                    continue
                self._event("below_quorum_abort", workers_alive=n_alive,
                            quorum=cfg.quorum)
                raise ClusterError(
                    f"{n_alive} live workers < quorum {cfg.quorum} and no "
                    f"restarts remain (budget "
                    f"{cfg.max_restarts_per_worker}/worker exhausted for "
                    f"{sorted(exhausted)}) at step {got['step']}")

            if time.monotonic() >= deadline:
                raise ClusterError(
                    f"supervised run did not reach step {target} within "
                    f"{timeout_secs:.0f}s (last seen: {got['step']})")
            logger.info("step %d/%d — %d/%d alive (quorum %d) — next poll "
                        "in %.1fs", got["step"], target, n_alive,
                        len(alive) or 0, cfg.quorum, poll_secs)
            time.sleep(poll_secs)
