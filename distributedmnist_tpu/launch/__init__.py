from .sweep import load_sweep_configs, run_experiment, run_sweep, write_report

__all__ = ["load_sweep_configs", "run_experiment", "run_sweep", "write_report"]
