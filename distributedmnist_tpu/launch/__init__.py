from .cluster import (ClusterBackend, ClusterError, GcloudTpuBackend,
                      LocalClusterConfig, LocalProcessCluster, run_until_step,
                      wait_until_step)
from .exec import (BinaryNotFoundError, CommandExecutor, ExecError,
                   ExecResult, FaultPlan, RetryPolicy)
from .sweep import load_sweep_configs, run_experiment, run_sweep, write_report

__all__ = ["BinaryNotFoundError", "ClusterBackend", "ClusterError",
           "CommandExecutor", "ExecError",
           "ExecResult", "FaultPlan", "GcloudTpuBackend", "LocalClusterConfig",
           "LocalProcessCluster", "RetryPolicy", "load_sweep_configs",
           "run_experiment", "run_sweep", "run_until_step",
           "wait_until_step", "write_report"]
