"""Experiment sweep runner + report generation.

≙ the reference's benchmark harness (tools/benchmark.py): it launched
EC2 clusters per cfg file, polled the master's stdout with a regex
until step N, SCP'd logs home, re-parsed them, and plotted
(tools/benchmark.py:17-58,265-292). Here an experiment is an
ExperimentConfig, runs are in-process (or one SPMD program over a
slice), metrics are structured from the start, and the "download +
regex" stage does not exist.

A sweep directory of config files (JSON / python literals — the safe
replacement for the reference's eval()'d cfg/, SURVEY §5.6) maps to
the reference's ``cfg/50_workers`` and ``cfg/time_cdf_cfgs`` grids.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Iterable

from ..core.config import ExperimentConfig
from ..core.log import JsonlSink, get_logger

logger = get_logger("sweep")


def run_experiment(cfg: ExperimentConfig, results_dir: str | Path,
                   datasets=None, fresh: bool = True) -> dict[str, Any]:
    """Run one experiment to max_steps; return (and persist) a result
    record: final metrics, eval accuracy, step-time CDF stats.

    ``fresh`` (default): force ``train.resume=False`` so a leftover
    checkpoint in the run dir (an aborted attempt, or a re-run with a
    raised step budget) can't splice the record — a silent resume
    reports ``steps`` = final step while ``wall_seconds`` and the
    timing arrays cover only the post-resume tail (measured: two
    interval-sweep rows shipped with '—' timing columns that way).
    Pass ``fresh=False`` only for a deliberately resumable long run.

    ≙ run_tf_and_download_files + stats parsing
    (tools/benchmark.py:36-163) collapsed into a function call.
    """
    from ..core.mesh import ensure_mesh
    from ..train.loop import Trainer  # deferred: heavy jax import chain

    # force/restore the device set this config expects, so a sweep can
    # mix simulated-mesh configs (quorum50) with ambient-mesh ones
    ensure_mesh(cfg.mesh.simulate_devices)

    results_dir = Path(results_dir) / cfg.name
    results_dir.mkdir(parents=True, exist_ok=True)
    cfg = cfg.override({"train.train_dir": str(results_dir / "train")})
    if fresh:
        cfg = cfg.override({"train.resume": False})
    cfg.save(results_dir / "config.json")

    t0 = time.time()
    trainer = Trainer(cfg, datasets=datasets)
    summary = trainer.run()
    wall = time.time() - t0
    final_eval = trainer.evaluate("test")

    record = {
        "name": cfg.name,
        "mode": cfg.sync.mode,
        "num_replicas": trainer.topo.num_replicas,
        "aggregate_k": cfg.sync.num_replicas_to_aggregate,
        "interval_ms": cfg.sync.interval_ms,
        "straggler_profile": cfg.sync.straggler_profile,
        "steps": summary["final_step"],
        "updates_applied": summary["updates_applied"],
        "wall_seconds": wall,
        "examples_per_sec": summary["last_metrics"].get("examples_per_sec"),
        "final_loss": summary["last_metrics"].get("loss"),
        "final_train_acc": summary["last_metrics"].get("train_acc"),
        "test_accuracy": final_eval["accuracy"],
        "test_loss": final_eval["loss"],
        "timing": summary["timing"],
    }
    (results_dir / "result.json").write_text(json.dumps(record, indent=2))
    try:
        from ..obsv.report import generate_report
        generate_report(results_dir / "train", None, results_dir / "figures",
                        name=cfg.name)
    except Exception as e:  # reporting is best-effort, never fails a sweep
        logger.warning("per-experiment report skipped: %s", e)
    logger.info("experiment %s: test_acc=%.4f, %.1f ex/s, p99 barrier=%.3fms",
                cfg.name, record["test_accuracy"],
                record["examples_per_sec"] or -1,
                record["timing"]["barrier"].get("p99", float("nan")))
    return record


def load_sweep_configs(path: str | Path) -> list[ExperimentConfig]:
    """Load every config file in a sweep directory (sorted), or a
    single file (≙ benchmark.py use_dir/select_files, :281-292)."""
    path = Path(path)
    files = ([path] if path.is_file() else
             sorted(p for p in path.iterdir()
                    if p.suffix in (".json", ".cfg", ".py") and p.is_file()))
    cfgs = [ExperimentConfig.from_file(f) for f in files]
    names = [c.name for c in cfgs]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate experiment names in sweep: {names}")
    return cfgs


def run_sweep(configs: Iterable[ExperimentConfig], results_dir: str | Path,
              datasets=None) -> list[dict[str, Any]]:
    """≙ plot_figs' experiment loop (tools/benchmark.py:265-279)."""
    results_dir = Path(results_dir)
    results_dir.mkdir(parents=True, exist_ok=True)
    records = []
    with JsonlSink(results_dir / "sweep_results.jsonl") as sink:
        for cfg in configs:
            rec = run_experiment(cfg, results_dir, datasets=datasets)
            sink.write(rec)
            records.append(rec)
    write_report(records, results_dir)
    return records


def write_report(records: list[dict[str, Any]], results_dir: str | Path,
                 loss_threshold: float = 1.5) -> Path:
    """Markdown summary table + optional CDF/convergence plots
    (≙ the matplotlib figures, tools/benchmark.py:165-263).

    Two convergence-speed views per experiment: steps to reach
    ``loss_threshold`` (per-step quality — nearly discipline-invariant,
    since any masked mean is an unbiased gradient) and MODELED time to
    reach it (cumulative slowest-contributor barrier — where quorum
    k<n wins by not waiting for straggling backups, the tradeoff the
    reference's Experiment A measures on real EC2 stragglers)."""
    import numpy as np

    from ..obsv.report import (load_jsonl, modeled_step_durations_ms,
                               steps_to_loss)

    results_dir = Path(results_dir)
    lines = [
        "# Sweep report", "",
        f"| name | mode | k | steps | updates | test acc | "
        f"steps→loss≤{loss_threshold:g} | modeled s→loss≤{loss_threshold:g} "
        f"| modeled barrier p50/p99 (ms) | ex/s | full-barrier p99 (ms) |",
        "|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    step_series = {
        r["name"]: load_jsonl(
            results_dir / r["name"] / "train" / "train_log.jsonl", "step")
        for r in records}
    for r in records:
        b = r["timing"]["barrier"]
        steps = step_series[r["name"]]
        to_loss = steps_to_loss(steps, loss_threshold)
        st_path = results_dir / r["name"] / "train" / "step_times.npy"
        durations = modeled_step_durations_ms(
            steps, np.load(st_path) if st_path.exists() else None)
        if durations is not None and len(durations):
            modeled_sec = (float(np.cumsum(durations)[to_loss - 1]) / 1e3
                           if to_loss is not None else None)
            d50, d99 = np.percentile(durations, [50, 99])
            modeled_col = (f"{modeled_sec:.1f}" if modeled_sec is not None
                           else "—")
            pct_col = f"{d50:.0f} / {d99:.0f}"
        else:
            modeled_col, pct_col = "—", "—"
        lines.append(
            f"| {r['name']} | {r['mode']} | {r['aggregate_k']} | {r['steps']} "
            f"| {r['updates_applied']} | {r['test_accuracy']:.4f} "
            f"| {to_loss if to_loss is not None else '—'} "
            f"| {modeled_col} | {pct_col} "
            f"| {r['examples_per_sec'] or 0:.0f} "
            f"| {b.get('p99', 0):.3f} |")
    report = results_dir / "report.md"
    report.write_text("\n".join(lines) + "\n")
    try:
        from ..obsv.report import plot_group_overlays, plot_sweep
        plot_sweep(records, results_dir)
        plot_group_overlays(records, results_dir, step_series=step_series)
    except Exception as e:  # plotting is best-effort, never fails a sweep
        logger.warning("plotting skipped: %s", e)
    return report
