"""TPU pod-slice lifecycle management.

≙ the reference's EC2 orchestrator ``tools/tf_ec2.py`` — boto3 spot
launches, paramiko SSH fan-out, role templating, NFS setup, SCP
downloads, and an 11-subcommand dispatch (:828-856). On Cloud TPU the
shape collapses: a pod slice is ONE resource (no per-role instances —
every host runs the same SPMD program, so the reference's
PS_HOSTS/WORKER_HOSTS/TASK_ID/JOB_NAME templating, :493-534,
disappears), SSH fan-out is ``gcloud compute tpus tpu-vm ssh
--worker=all``, and downloads are ``gcloud ... scp``.

Subcommand parity map (reference dispatch table → here; "exec" in the
coverage column = the verb is exercised by tests as a real executed
subprocess — against a stubbed ``gcloud`` on PATH here, and as real
local worker processes on ``launch/cluster.py``'s LocalProcessCluster):

  launch                 → create            (tf_ec2.py:796, :237-271)  [exec]
  shutdown               → delete            (:440)                     [exec]
  clean_launch_and_run   → clean-launch-run  (:806)                     [argv]
  run_tf                 → run               (:445)                     [exec]
  kill_all_python        → kill-all          (:637)                     [exec]
  kill_python            → kill-all --worker (:617)                     [exec]
  list_idle_instances    → status (idle = no python running, :371-402)  [exec]
  list_running_instances → status            (:404)                     [exec]
  run_command            → exec              (:841)                     [exec]
  download_outdir        → download          (:651-697)                 [exec]
  download_file          → download --file   (:699-742)                 [argv]

The argv builders and every verb now live in
:class:`~.cluster.GcloudTpuBackend` — one of the pluggable
:class:`~.cluster.ClusterBackend` realizations — and ``PodManager``
is the thin TPU-facing surface over it. Every action goes through a
``Runner`` (a compat shim over :class:`~.exec.CommandExecutor`) that
either executes the ``gcloud`` CLI or records the exact argv (dry-run)
— the test seam, and also how a human can audit what would run. No
cloud SDK is imported; environments without ``gcloud`` get a clear
error only when a command is actually executed.
"""

from __future__ import annotations

import dataclasses
import json
import shlex
import subprocess
from pathlib import Path
from typing import Any, Sequence

from ..core.log import get_logger
from .cluster import ClusterError, GcloudTpuBackend
from . import cluster as cluster_lib
from .exec import (BinaryNotFoundError, CommandExecutor, ExecError,
                   RetryPolicy)

logger = get_logger("pod")


class PodError(RuntimeError):
    pass


@dataclasses.dataclass(frozen=True)
class PodConfig:
    """Declarative slice description (≙ the cluster_specs half of a
    ``Cfg`` literal, tools/tf_ec2.py:27-147 — as safe JSON, not
    eval()'d python)."""

    name: str = "dmt-pod"
    zone: str = "us-central2-b"
    accelerator_type: str = "v4-8"
    runtime_version: str = "tpu-ubuntu2204-base"
    project: str | None = None
    spot: bool = False                      # ≙ spot-instance launch path
    setup_command: str = ""                 # run once after create
    train_command: str = ("python -m distributedmnist_tpu.launch train "
                          "--config configs/basic.json")
    remote_outdir: str = "/tmp/dmt_train"   # ≙ Cfg nfs_mount_point outdir
    env: dict[str, str] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_file(cls, path: str | Path) -> "PodConfig":
        d = json.loads(Path(path).read_text())
        unknown = set(d) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise PodError(f"unknown pod config keys: {sorted(unknown)}")
        return cls(**d)


class Runner(CommandExecutor):
    """Executes argv lists, or records them under dry_run.

    The historical pod seam, now a shim over
    :class:`~.exec.CommandExecutor`: same ``run(argv, check, capture)``
    call shape and ``recorded`` audit list, with the executor's
    timeout / journal / fault seams available underneath. No retries
    by default — gcloud verbs are not assumed idempotent; opt in with
    ``Runner(retry=RetryPolicy(max_attempts=3))``.
    """

    def __init__(self, dry_run: bool = False, **kw):
        kw.setdefault("retry", RetryPolicy(max_attempts=1))
        super().__init__(dry_run=dry_run, **kw)

    def run(self, argv: Sequence[str], check: bool = True,  # type: ignore[override]
            capture: bool = False, **kw) -> subprocess.CompletedProcess | None:
        try:
            res = super().run(argv, check=check, capture=capture, **kw)
        except BinaryNotFoundError as e:
            raise PodError(
                f"{argv[0]!r} not found — pod management needs the "
                "gcloud CLI on PATH (or use --dry-run to inspect "
                "commands)") from e
        except ExecError as e:
            raise PodError(str(e)) from e
        if res is None:  # dry-run
            return None
        return subprocess.CompletedProcess(
            args=res.argv,
            returncode=124 if res.timed_out else res.returncode,
            stdout=res.stdout, stderr=res.stderr)


class PodManager:
    """All pod actions as methods; argv construction (pure, in
    :class:`GcloudTpuBackend`) is separate from execution, so every
    action is testable via Runner(dry_run=True) — and executable for
    real against a stubbed ``gcloud`` on PATH."""

    def __init__(self, cfg: PodConfig, runner: Runner | None = None):
        self.cfg = cfg
        self.runner = runner or Runner()
        self.backend = GcloudTpuBackend(cfg, self.runner)

    # -- lifecycle ------------------------------------------------------

    def create(self) -> None:
        """≙ launch (tf_ec2.py:796): create the slice, run setup."""
        self.backend.create()

    def delete(self) -> None:
        """≙ shutdown (tf_ec2.py:440)."""
        self.backend.delete()

    def status(self) -> dict[str, Any] | None:
        """≙ list_running/list_idle (tf_ec2.py:371-404): slice state
        plus whether python is running on any worker."""
        return self.backend.status()

    # -- work -----------------------------------------------------------

    def run_train(self) -> None:
        """≙ run_tf (tf_ec2.py:445): same command on every worker."""
        self.backend.run_train()

    def kill_all(self, worker: str = "all") -> None:
        """≙ kill_all_python / kill_python (tf_ec2.py:617-649)."""
        self.backend.kill_all(worker=worker)

    def exec(self, command: str, worker: str = "all") -> None:
        """≙ run_command (tf_ec2.py:841)."""
        self.backend.exec_all(command, worker=worker)

    def download(self, local_dir: str | Path, remote_path: str | None = None,
                 worker: str = "0") -> None:
        """≙ download_outdir / download_file (tf_ec2.py:651-742)."""
        self.backend.download(local_dir, remote_path, worker=worker)

    def clean_launch_and_run(self) -> None:
        """≙ clean_launch_and_run (tf_ec2.py:806): delete-if-exists →
        create → run."""
        self.backend.delete(ignore_missing=True)
        self.create()
        self.run_train()

    # -- progress -------------------------------------------------------

    def poll(self) -> dict[str, Any] | None:
        """One progress probe: tail the remote ``train_log.jsonl``
        (worker 0 — every host logs the same replicated metrics) and
        parse the newest record. ≙ the reference's master-log poll
        (tools/benchmark.py:24-34). Returns {"step", "record"} — step
        is -1 when the log does not exist yet. Dry-run returns None
        (argv recorded)."""
        return self.backend.poll()

    def wait_until_step(self, target: int, poll_secs: float = 30.0,
                        timeout_secs: float = 24 * 3600.0) -> dict[str, Any]:
        """Block until the remote run reaches ``target`` steps
        (≙ benchmark.py's run-until-step-N loop :24-34). Dry-run
        records exactly one poll argv and returns immediately."""
        try:
            return cluster_lib.wait_until_step(self.backend, target,
                                               poll_secs, timeout_secs)
        except ClusterError as e:
            raise PodError(f"remote {e}") from None

    def run_until_step(self, target: int, poll_secs: float = 30.0,
                       timeout_secs: float = 24 * 3600.0) -> dict[str, Any]:
        """Launch training, follow the remote log to step ``target``,
        then stop the run — the reference's benchmark driver shape
        (launch → poll ssh'd log → kill at N, tools/benchmark.py:24-44).
        The cluster is stopped on EVERY exit — a poll timeout or a
        Ctrl-C must not leave the pod training (and billing)."""
        try:
            return cluster_lib.run_until_step(self.backend, target,
                                              poll_secs, timeout_secs)
        except ClusterError as e:
            raise PodError(f"remote {e}") from None


def main(argv: list[str] | None = None) -> None:
    import argparse

    p = argparse.ArgumentParser(prog="distributedmnist_tpu.launch pod")
    p.add_argument("action",
                   choices=["create", "delete", "status", "run", "kill-all",
                            "exec", "download", "clean-launch-run", "poll"])
    p.add_argument("--config", default=None, help="PodConfig JSON")
    p.add_argument("--dry-run", action="store_true",
                   help="print gcloud commands instead of executing")
    p.add_argument("--journal", default=None,
                   help="command journal JSONL path")
    p.add_argument("--timeout-s", type=float, default=None,
                   help="per-gcloud-command timeout")
    p.add_argument("--max-attempts", type=int, default=1,
                   help="retry budget for transient gcloud failures")
    p.add_argument("--command", default=None, help="for exec")
    p.add_argument("--worker", default=None, help="worker index or 'all'")
    p.add_argument("--local-dir", default="./pod_results", help="for download")
    p.add_argument("--remote-path", default=None, help="for download")
    p.add_argument("--until-step", type=int, default=None, metavar="N",
                   help="for run/poll: follow the remote train_log.jsonl "
                        "and return at step N (run also stops the remote "
                        "run, ≙ tools/benchmark.py:24-44)")
    p.add_argument("--poll-secs", type=float, default=30.0,
                   help="poll cadence for --until-step")
    args = p.parse_args(argv)

    cfg = PodConfig.from_file(args.config) if args.config else PodConfig()
    mgr = PodManager(cfg, Runner(
        dry_run=args.dry_run, journal=args.journal,
        timeout_s=args.timeout_s,
        retry=RetryPolicy(max_attempts=args.max_attempts)))
    if args.action == "create":
        mgr.create()
    elif args.action == "delete":
        mgr.delete()
    elif args.action == "status":
        print(json.dumps(mgr.status(), indent=2))
    elif args.action == "run":
        if args.until_step is not None:
            print(json.dumps(mgr.run_until_step(args.until_step,
                                                poll_secs=args.poll_secs)))
        else:
            mgr.run_train()
    elif args.action == "poll":
        if args.until_step is not None:
            print(json.dumps(mgr.wait_until_step(args.until_step,
                                                 poll_secs=args.poll_secs)))
        else:
            print(json.dumps(mgr.poll()))
    elif args.action == "kill-all":
        mgr.kill_all(worker=args.worker or "all")
    elif args.action == "exec":
        if not args.command:
            p.error("exec requires --command")
        mgr.exec(args.command, worker=args.worker or "all")
    elif args.action == "download":
        mgr.download(args.local_dir, args.remote_path,
                     worker=args.worker or "0")
    elif args.action == "clean-launch-run":
        mgr.clean_launch_and_run()
    if args.dry_run:
        print(json.dumps([shlex.join(a) for a in mgr.runner.recorded],
                         indent=2))
    mgr.runner.close()
