"""TPU pod-slice lifecycle management.

≙ the reference's EC2 orchestrator ``tools/tf_ec2.py`` — boto3 spot
launches, paramiko SSH fan-out, role templating, NFS setup, SCP
downloads, and an 11-subcommand dispatch (:828-856). On Cloud TPU the
shape collapses: a pod slice is ONE resource (no per-role instances —
every host runs the same SPMD program, so the reference's
PS_HOSTS/WORKER_HOSTS/TASK_ID/JOB_NAME templating, :493-534,
disappears), SSH fan-out is ``gcloud compute tpus tpu-vm ssh
--worker=all``, and downloads are ``gcloud ... scp``.

Subcommand parity map (reference dispatch table → here):

  launch                 → create            (tf_ec2.py:796, :237-271)
  shutdown               → delete            (:440)
  clean_launch_and_run   → clean-launch-run  (:806)
  run_tf                 → run               (:445)
  kill_all_python        → kill-all          (:637)
  kill_python            → kill-all --worker (:617)
  list_idle_instances    → status (idle = no python running, :371-402)
  list_running_instances → status            (:404)
  run_command            → exec              (:841)
  download_outdir        → download          (:651-697)
  download_file          → download --file   (:699-742)

Every action goes through a ``Runner`` that either executes the
``gcloud`` CLI or records the exact argv (dry-run) — the test seam,
and also how a human can audit what would run. No cloud SDK is
imported; environments without ``gcloud`` get a clear error only when
a command is actually executed.
"""

from __future__ import annotations

import dataclasses
import json
import shlex
import subprocess
from pathlib import Path
from typing import Any, Sequence

from ..core.log import get_logger

logger = get_logger("pod")


class PodError(RuntimeError):
    pass


@dataclasses.dataclass(frozen=True)
class PodConfig:
    """Declarative slice description (≙ the cluster_specs half of a
    ``Cfg`` literal, tools/tf_ec2.py:27-147 — as safe JSON, not
    eval()'d python)."""

    name: str = "dmt-pod"
    zone: str = "us-central2-b"
    accelerator_type: str = "v4-8"
    runtime_version: str = "tpu-ubuntu2204-base"
    project: str | None = None
    spot: bool = False                      # ≙ spot-instance launch path
    setup_command: str = ""                 # run once after create
    train_command: str = ("python -m distributedmnist_tpu.launch train "
                          "--config configs/basic.json")
    remote_outdir: str = "/tmp/dmt_train"   # ≙ Cfg nfs_mount_point outdir
    env: dict[str, str] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_file(cls, path: str | Path) -> "PodConfig":
        d = json.loads(Path(path).read_text())
        unknown = set(d) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise PodError(f"unknown pod config keys: {sorted(unknown)}")
        return cls(**d)


class Runner:
    """Executes argv lists, or records them under dry_run."""

    def __init__(self, dry_run: bool = False):
        self.dry_run = dry_run
        self.recorded: list[list[str]] = []

    def run(self, argv: Sequence[str], check: bool = True,
            capture: bool = False) -> subprocess.CompletedProcess | None:
        argv = list(argv)
        self.recorded.append(argv)
        if self.dry_run:
            logger.info("DRY-RUN: %s", shlex.join(argv))
            return None
        try:
            return subprocess.run(argv, check=check, text=True,
                                  capture_output=capture)
        except FileNotFoundError as e:
            raise PodError(
                f"{argv[0]!r} not found — pod management needs the gcloud "
                "CLI on PATH (or use --dry-run to inspect commands)") from e
        except subprocess.CalledProcessError as e:
            raise PodError(f"command failed ({e.returncode}): "
                           f"{shlex.join(argv)}") from e


class PodManager:
    """All pod actions as methods; argv construction is pure, so every
    action is testable via Runner(dry_run=True)."""

    def __init__(self, cfg: PodConfig, runner: Runner | None = None):
        self.cfg = cfg
        self.runner = runner or Runner()

    # -- argv builders (pure) -------------------------------------------

    def _base(self, *verb: str) -> list[str]:
        argv = ["gcloud", "compute", "tpus", "tpu-vm", *verb, self.cfg.name,
                "--zone", self.cfg.zone]
        if self.cfg.project:
            argv += ["--project", self.cfg.project]
        return argv

    def _ssh(self, command: str, worker: str = "all") -> list[str]:
        exports = "".join(f"export {k}={shlex.quote(v)}; "
                          for k, v in self.cfg.env.items())
        return self._base("ssh") + ["--worker", worker,
                                    "--command", exports + command]

    # -- lifecycle ------------------------------------------------------

    def create(self) -> None:
        """≙ launch (tf_ec2.py:796): create the slice, run setup."""
        argv = self._base("create") + [
            "--accelerator-type", self.cfg.accelerator_type,
            "--version", self.cfg.runtime_version]
        if self.cfg.spot:
            argv.append("--spot")
        self.runner.run(argv)
        if self.cfg.setup_command:
            self.runner.run(self._ssh(self.cfg.setup_command))

    def delete(self) -> None:
        """≙ shutdown (tf_ec2.py:440)."""
        self.runner.run(self._base("delete") + ["--quiet"])

    def status(self) -> dict[str, Any] | None:
        """≙ list_running/list_idle (tf_ec2.py:371-404): slice state
        plus whether python is running on any worker."""
        out = self.runner.run(self._base("describe") + ["--format", "json"],
                              capture=True)
        # [d]… so the pattern never matches the ssh-spawned shell whose
        # own command line contains it (pgrep -f excludes only itself).
        probe = self.runner.run(
            self._ssh("pgrep -c -f '[d]istributedmnist_tpu.launch' || true"),
                                capture=True, check=False)
        if out is None:  # dry-run: both argvs recorded above, no result
            return None
        desc = json.loads(out.stdout)
        if probe is None or probe.returncode != 0:
            idle = None  # probe failed — unknown, NOT "idle" (a caller
            # keying deletion off idle must not kill a live run)
        else:
            idle = not any(line.strip() not in ("", "0")
                           for line in (probe.stdout or "").splitlines())
        return {"state": desc.get("state"), "idle": idle, "describe": desc}

    # -- work -----------------------------------------------------------

    def run_train(self) -> None:
        """≙ run_tf (tf_ec2.py:445): same command on every worker —
        jax.distributed discovers the slice topology; no role/host
        templating exists."""
        outdir = shlex.quote(self.cfg.remote_outdir)
        log = shlex.quote(f"{self.cfg.remote_outdir}/train_stdout.log")
        self.runner.run(self._ssh(
            f"mkdir -p {outdir} && cd ~ && "
            f"nohup {self.cfg.train_command} > {log} 2>&1 &"))

    def kill_all(self, worker: str = "all") -> None:
        """≙ kill_all_python / kill_python (tf_ec2.py:617-649)."""
        self.runner.run(self._ssh("pkill -9 -f python || true", worker=worker),
                        check=False)

    def exec(self, command: str, worker: str = "all") -> None:
        """≙ run_command (tf_ec2.py:841)."""
        self.runner.run(self._ssh(command, worker=worker))

    def download(self, local_dir: str | Path, remote_path: str | None = None,
                 worker: str = "0") -> None:
        """≙ download_outdir / download_file (tf_ec2.py:651-742)."""
        remote = remote_path or self.cfg.remote_outdir
        local_dir = Path(local_dir)
        local_dir.mkdir(parents=True, exist_ok=True)
        # scp's positional is <name>:<path>, not a bare name, so the
        # _base helper doesn't apply
        argv = ["gcloud", "compute", "tpus", "tpu-vm", "scp",
                "--zone", self.cfg.zone]
        if self.cfg.project:
            argv += ["--project", self.cfg.project]
        argv += ["--worker", worker, "--recurse",
                 f"{self.cfg.name}:{remote}", str(local_dir)]
        self.runner.run(argv)

    def clean_launch_and_run(self) -> None:
        """≙ clean_launch_and_run (tf_ec2.py:806): delete-if-exists →
        create → run."""
        self.runner.run(self._base("delete") + ["--quiet"], check=False)
        self.create()
        self.run_train()

    # -- progress -------------------------------------------------------

    def poll(self) -> dict[str, Any] | None:
        """One progress probe: tail the remote ``train_log.jsonl``
        (worker 0 — every host logs the same replicated metrics) and
        parse the newest record. ≙ the reference's master-log poll that
        greps ``Step N`` out of the remote stdout
        (tools/benchmark.py:24-34), against the structured log instead
        of a regex over freeform text.

        Returns {"step", "record"} — step is -1 when the log does not
        exist yet (run still booting). Dry-run returns None (argv
        recorded).
        """
        log = shlex.quote(f"{self.cfg.remote_outdir}/train_log.jsonl")
        out = self.runner.run(
            self._ssh(f"tail -n 1 {log} 2>/dev/null || true", worker="0"),
            capture=True, check=False)
        if out is None:
            return None
        line = (out.stdout or "").strip().splitlines()
        if not line:
            return {"step": -1, "record": None}
        try:
            record = json.loads(line[-1])
        except json.JSONDecodeError:
            return {"step": -1, "record": None}  # torn write — next poll
        return {"step": int(record.get("step", -1)), "record": record}

    def wait_until_step(self, target: int, poll_secs: float = 30.0,
                        timeout_secs: float = 24 * 3600.0) -> dict[str, Any]:
        """Block until the remote run reaches ``target`` steps
        (≙ benchmark.py's run-until-step-N loop :24-34). Dry-run
        records exactly one poll argv and returns immediately."""
        import time as _time
        deadline = _time.monotonic() + timeout_secs
        while True:
            got = self.poll()
            if got is None:  # dry-run
                return {"step": target, "record": None, "dry_run": True}
            if got["step"] >= target:
                return got
            if _time.monotonic() >= deadline:
                raise PodError(
                    f"remote run did not reach step {target} within "
                    f"{timeout_secs:.0f}s (last seen: {got['step']})")
            logger.info("step %d/%d — next poll in %.0fs",
                        got["step"], target, poll_secs)
            _time.sleep(poll_secs)

    def run_until_step(self, target: int, poll_secs: float = 30.0,
                       timeout_secs: float = 24 * 3600.0) -> dict[str, Any]:
        """Launch training, follow the remote log to step ``target``,
        then stop the run — the reference's benchmark driver shape
        (launch → poll ssh'd log → kill at N, tools/benchmark.py:24-44).
        """
        self.run_train()
        try:
            return self.wait_until_step(target, poll_secs, timeout_secs)
        finally:
            # stop the remote run on EVERY exit — a poll timeout or a
            # Ctrl-C must not leave the pod training (and billing)
            self.kill_all()


def main(argv: list[str] | None = None) -> None:
    import argparse

    p = argparse.ArgumentParser(prog="distributedmnist_tpu.launch pod")
    p.add_argument("action",
                   choices=["create", "delete", "status", "run", "kill-all",
                            "exec", "download", "clean-launch-run", "poll"])
    p.add_argument("--config", default=None, help="PodConfig JSON")
    p.add_argument("--dry-run", action="store_true",
                   help="print gcloud commands instead of executing")
    p.add_argument("--command", default=None, help="for exec")
    p.add_argument("--worker", default=None, help="worker index or 'all'")
    p.add_argument("--local-dir", default="./pod_results", help="for download")
    p.add_argument("--remote-path", default=None, help="for download")
    p.add_argument("--until-step", type=int, default=None, metavar="N",
                   help="for run/poll: follow the remote train_log.jsonl "
                        "and return at step N (run also stops the remote "
                        "run, ≙ tools/benchmark.py:24-44)")
    p.add_argument("--poll-secs", type=float, default=30.0,
                   help="poll cadence for --until-step")
    args = p.parse_args(argv)

    cfg = PodConfig.from_file(args.config) if args.config else PodConfig()
    mgr = PodManager(cfg, Runner(dry_run=args.dry_run))
    if args.action == "create":
        mgr.create()
    elif args.action == "delete":
        mgr.delete()
    elif args.action == "status":
        print(json.dumps(mgr.status(), indent=2))
    elif args.action == "run":
        if args.until_step is not None:
            print(json.dumps(mgr.run_until_step(args.until_step,
                                                poll_secs=args.poll_secs)))
        else:
            mgr.run_train()
    elif args.action == "poll":
        if args.until_step is not None:
            print(json.dumps(mgr.wait_until_step(args.until_step,
                                                 poll_secs=args.poll_secs)))
        else:
            print(json.dumps(mgr.poll()))
    elif args.action == "kill-all":
        mgr.kill_all(worker=args.worker or "all")
    elif args.action == "exec":
        if not args.command:
            p.error("exec requires --command")
        mgr.exec(args.command, worker=args.worker or "all")
    elif args.action == "download":
        mgr.download(args.local_dir, args.remote_path,
                     worker=args.worker or "0")
    elif args.action == "clean-launch-run":
        mgr.clean_launch_and_run()
    if args.dry_run:
        print(json.dumps([shlex.join(a) for a in mgr.runner.recorded],
                         indent=2))
