"""Framework CLI.

≙ the reference's launch surface: ``tools/tf_ec2.py``'s subcommand
dispatch (:828-867) and the templated per-role SSH commands it
generated (:109-146). On TPU there are no roles to template — every
host runs the same program — so the CLI reduces to:

  python -m distributedmnist_tpu.launch train --config cfg.json [k=v ...]
  python -m distributedmnist_tpu.launch eval  --train_dir DIR
  python -m distributedmnist_tpu.launch sweep --configs DIR --results DIR
  python -m distributedmnist_tpu.launch cluster run --until-step N [--backend local]
  python -m distributedmnist_tpu.launch report --train_dir DIR --out DIR
  python -m distributedmnist_tpu.launch devices

Dotted overrides (``sync.mode=quorum``) take the place of the ~25
tf.app.flags (src/distributed_train.py:36-99).
"""

from __future__ import annotations

import argparse
import json
import sys


def _load_cfg_and_bringup(args):
    """Parse config BEFORE touching any jax API, then bring the
    platform up: a simulated N-device CPU mesh when the config asks for
    one, multi-host discovery otherwise."""
    from ..core.config import ExperimentConfig, parse_cli_overrides
    from ..core.mesh import initialize_distributed, simulate_devices

    cfg = (ExperimentConfig.from_file(args.config) if args.config
           else ExperimentConfig())
    cfg = cfg.override(parse_cli_overrides(getattr(args, "overrides", [])))
    if cfg.mesh.simulate_devices > 0:
        simulate_devices(cfg.mesh.simulate_devices)
    else:
        initialize_distributed()  # multi-host bring-up before backend init
    # persistent compile cache (cfg.compile / DMT_COMPILE_CACHE_DIR):
    # a restarted worker reuses its predecessor's compiles instead of
    # paying the full XLA compile again on every recovery
    from ..core.compile_cache import enable_persistent_cache
    enable_persistent_cache(cfg.compile)
    return cfg


def _park_standby(trainer, activation: str) -> None:
    """The warm-standby protocol (ROADMAP item 5): precompile, signal
    readiness by touching ``<activation>.ready``, then PARK until the
    supervisor's promotion writes the activation file (atomic rename —
    never read torn) naming the dead worker's train_dir, and adopt it.
    The parked process has already paid import, mesh bring-up and the
    train-step compile, so promotion→first-moved-step is data-path
    time only."""
    import json as _json
    import os as _os
    import time as _time
    from pathlib import Path

    try:
        trainer.precompile()
    except Exception as e:  # park anyway: a warm PROCESS still beats a
        # cold boot even if the compile must happen at first step
        print(f"standby precompile failed ({type(e).__name__}: {e}); "
              "parking warm-process only", file=sys.stderr)
    act = Path(activation)
    act.parent.mkdir(parents=True, exist_ok=True)
    ready = act.with_name(act.name + ".ready")
    ready.write_text(_json.dumps({"pid": _os.getpid(),
                                  "ready_at": _time.time()}))
    while not act.exists():
        _time.sleep(0.1)
    assignment = _json.loads(act.read_text())
    trainer.adopt_train_dir(assignment["train_dir"])


def _park_serve_standby(activation: str) -> str:
    """The serving-payload half of the warm-standby protocol: the
    parked spare has already paid process boot, jax import and the
    publish-dir config wait (everything before this call in
    ``_serve``); it signals ready, parks, and on promotion returns the
    assigned worker logdir to use as serve_dir — the replica then
    binds there and writes its endpoint card where
    ``discover_endpoints`` looks. The assignment's ``train_dir`` key
    names the ADOPTED logdir (the protocol's field name, shared with
    the trainer's parking path)."""
    import json as _json
    import os as _os
    import time as _time
    from pathlib import Path

    act = Path(activation)
    act.parent.mkdir(parents=True, exist_ok=True)
    ready = act.with_name(act.name + ".ready")
    ready.write_text(_json.dumps({"pid": _os.getpid(),
                                  "ready_at": _time.time()}))
    while not act.exists():
        _time.sleep(0.1)
    assignment = _json.loads(act.read_text())
    return assignment["train_dir"]


def _train(args) -> None:
    import os

    cfg = _load_cfg_and_bringup(args)
    from ..train.loop import Trainer

    trainer = Trainer(cfg)
    activation = os.environ.get("DMT_STANDBY_ACTIVATION")
    if activation:
        _park_standby(trainer, activation)
    summary = trainer.run()
    if summary.get("preempted"):
        # a flushed, resumable stop (SIGTERM/SIGINT mid-run): exit with
        # the distinct resumable code so a supervisor restarts us
        # instead of treating this as a crash; skip eval — the process
        # was asked to leave
        print(json.dumps({"summary": {k: v for k, v in summary.items()
                                      if k != "timing"}}, default=str))
        sys.exit(cfg.train.resumable_exit_code)
    result = trainer.evaluate("test")
    print(json.dumps({"summary": {k: v for k, v in summary.items() if k != "timing"},
                      "test": result}, default=str))


def _eval(args) -> None:
    from ..core.config import EvalConfig
    from .. import evalsvc

    ecfg = EvalConfig(eval_interval_secs=args.eval_interval_secs,
                      eval_dir=args.eval_dir, run_once=args.run_once,
                      max_evals=args.max_evals)
    evalsvc.Evaluator(args.train_dir, ecfg,
                      single_device=args.single_device).run()


def _serve(args) -> None:
    """The serving-replica payload (`launch serve`): hot-follow the
    publish dir's checkpoints and serve inference over a local socket
    — the process the cluster's serving payload verb spawns. Runs on
    ONE ambient device (no simulated mesh, no collectives), adopting
    the model/config from the checkpoint itself like the evaluator.
    ``--decode`` swaps the workload inside the replica contract from
    one-shot classification to continuous-batching autoregressive
    decode (streaming tokens, paged KV cache).

    Honors ``DMT_STANDBY_ACTIVATION`` like ``launch train``: a serving
    spare pays the import + config wait up front, parks ready, and on
    promotion adopts the ASSIGNED worker logdir as its serve_dir — the
    warm pool the resource broker promotes scale-up replicas from."""
    import dataclasses
    import os

    from ..servesvc.server import ServingReplica, wait_for_run_config

    cfg = wait_for_run_config(args.train_dir)
    activation = os.environ.get("DMT_STANDBY_ACTIVATION")
    if activation:
        args.serve_dir = _park_serve_standby(activation)
    tp_ranks = (args.tp_ranks if args.tp_ranks is not None
                else cfg.serve.tp_ranks)
    if tp_ranks > 1 and args.tp_rank is None:
        # TP group supervisor: re-invoke this very command once per
        # rank (rank 0 = the real replica owning the socket, ranks>0 =
        # shard-verifying followers) and babysit them die-as-a-unit
        import sys

        from ..servesvc.tp_group import ServeGroup, default_spawn_fn
        spawn = default_spawn_fn(sys.argv[1:], args.serve_dir, tp_ranks)
        ServeGroup(args.serve_dir, tp_ranks, spawn,
                   max_restarts=cfg.serve.tp_group_max_restarts,
                   poll_secs=cfg.serve.tp_group_poll_secs).run_forever()
        return
    if args.tp_rank is not None and args.tp_rank > 0:
        from ..servesvc.tp_group import run_rank_follower
        run_rank_follower(args.train_dir, args.serve_dir, args.tp_rank,
                          tp_ranks,
                          poll_secs=cfg.serve.tp_group_poll_secs)
        return
    overrides = {k: getattr(args, k) for k in
                 ("host", "port", "max_batch", "queue_depth",
                  "batch_window_ms", "poll_secs", "default_deadline_ms",
                  "precision_tier", "compute_dtype", "tp_ranks")
                 if getattr(args, k) is not None}
    scfg = dataclasses.replace(cfg.serve, **overrides)
    if args.decode:
        from ..servesvc.decode import DecodeReplica
        d_over = {k: getattr(args, k) for k in
                  ("decode_slots", "max_new_tokens", "max_prompt_len",
                   "swap_policy", "attention_kernel")
                  if getattr(args, k) is not None}
        dcfg = dataclasses.replace(cfg.decode, **d_over)
        DecodeReplica(args.train_dir, serve_dir=args.serve_dir,
                      scfg=scfg, dcfg=dcfg, cfg=cfg).serve_forever()
        return
    ServingReplica(args.train_dir, serve_dir=args.serve_dir,
                   scfg=scfg, cfg=cfg).serve_forever()


def _serve_load(args) -> None:
    """Closed-loop load generator (`launch serve-load`): drive a
    serving cluster through the round-robin failover shim, journal
    every request's terminal outcome, print the latency summary."""
    import time as _time

    from ..servesvc.client import ServeClient, discover_endpoints
    from ..servesvc.loadgen import make_input_fn, run_load

    if args.endpoints:
        eps = [tuple(e.rsplit(":", 1)) for e in args.endpoints.split(",")]
        eps = [(h, int(p)) for h, p in eps]
        endpoints_fn = lambda: eps  # noqa: E731
    elif args.cluster_root:
        root = args.cluster_root
        endpoints_fn = lambda: discover_endpoints(root)  # noqa: E731
    else:
        raise SystemExit("serve-load needs --endpoints or --cluster-root")
    client = ServeClient(endpoints_fn, deadline_s=args.deadline_s,
                         max_attempts=args.max_attempts)
    deadline = _time.time() + args.ready_timeout_s
    meta = None
    while meta is None and _time.time() < deadline:
        meta = client.meta(deadline_s=2.0)
        if meta is None:
            _time.sleep(0.5)
    if meta is None:
        raise SystemExit(f"no serving replica became ready within "
                         f"{args.ready_timeout_s:.0f}s")
    make_input = make_input_fn(meta["input_shape"], meta["input_dtype"])
    summary = run_load(client, args.requests, args.concurrency,
                       make_input, journal_path=args.out)
    print(json.dumps(summary))


def _sweep(args) -> None:
    from ..core.mesh import initialize_distributed
    initialize_distributed()
    from .sweep import load_sweep_configs, run_sweep

    cfgs = load_sweep_configs(args.configs)
    if args.only:
        cfgs = [c for c in cfgs if c.name in set(args.only.split(","))]
    records = run_sweep(cfgs, args.results)
    print(json.dumps([{k: r[k] for k in ("name", "test_accuracy",
                                         "examples_per_sec")}
                      for r in records]))


def _report(args) -> None:
    from ..obsv.report import generate_report

    stats = generate_report(args.train_dir, args.eval_dir, args.out,
                            name=args.name)
    print(json.dumps(stats, indent=2))


def _fetch(args) -> None:
    """Download + verify the REAL archives (≙ maybe_download,
    src/mnist_data.py:176-187, plus the digest pinning the reference
    never had). The one-command path from a fixture cache to verified
    real data: the day this box has egress,
    ``launch fetch --verify`` upgrades the cache and rewrites
    PROVENANCE.md to say so — the 99%-on-real-MNIST oracle is then
    ``launch train --config configs/repro/mnist_99.json`` away."""
    import hashlib
    import time
    from pathlib import Path

    from ..data import datasets as DS

    root = Path(args.data_dir)
    dataset = args.dataset
    pins = DS._PINNED_SHA256.get(dataset, {})

    def list_stranded():
        """*.quarantine files left by an interrupted earlier run
        (killed between quarantine and restore)."""
        return (sorted(p.name for p in root.glob("*.quarantine"))
                if root.is_dir() else [])

    def recover(stranded):
        """Put a stranded file back when its slot is still empty,
        discard it when the slot was re-filled — either way no
        *.quarantine survives into this run's bookkeeping. Must run
        under the fetch lock: a LIVE peer's quarantine files are
        indistinguishable from stranded ones."""
        for name in stranded:
            aside = root / name
            orig = aside.with_name(name[: -len(".quarantine")])
            if orig.exists():
                aside.unlink()
            else:
                aside.rename(orig)

    def build_plan(recovered):
        plan = []
        for key, names in DS._IDX_FILES.items():
            gz = names[0] + ".gz"
            cached = DS._find_idx(root, names)
            if cached is None and any(n in recovered
                                      or n + ".gz" in recovered
                                      for n in names):
                # dry-run only: a real fetch recovers the stranded file
                # first, so "missing" would misstate what it will do
                plan.append({"file": gz, "cached": None,
                             "status": "stranded quarantine (a "
                                       "non-dry-run fetch recovers it "
                                       "before planning)",
                             "pinned_sha256": pins.get(gz),
                             "mirrors": [b + gz
                                         for b in DS._IDX_MIRRORS[dataset]]})
                continue
            status = "missing"
            if cached is not None:
                if cached.name in pins:
                    got = hashlib.sha256(cached.read_bytes()).hexdigest()
                    status = ("verified" if got == pins[cached.name]
                              else "DIGEST MISMATCH")
                else:
                    status = ("cached, not digest-verifiable "
                              "(fixture or raw idx)")
            plan.append({"file": gz,
                         "cached": str(cached) if cached else None,
                         "status": status, "pinned_sha256": pins.get(gz),
                         "mirrors": [b + gz
                                     for b in DS._IDX_MIRRORS[dataset]]})
        return plan

    if args.dry_run:
        # zero mutation (and no lock): stranded files are reported and
        # annotated in the plan, not recovered
        stranded = list_stranded()
        plan = build_plan({x[: -len(".quarantine")] for x in stranded})
        print(json.dumps({"dataset": dataset, "data_dir": str(root),
                          "plan": plan,
                          "stranded_quarantine": stranded}, indent=2))
        return

    # Everything that mutates the cache — stranded recovery, planning
    # against the recovered state, quarantine, download, and the
    # commit/rollback — runs under an exclusive per-data-dir flock.
    # The rollback deletes every known-name file that postdates this
    # run's snapshot, which would destroy archives a concurrent peer
    # installed, and an unlocked recovery would un-quarantine a live
    # peer's files mid-fetch. The lock lives under the system temp dir
    # (keyed on the resolved cache path) so the cache itself stays
    # byte-identical across a failed fetch; it therefore serializes
    # same-HOST fetches only — distinct hosts sharing one NFS dir fall
    # back to maybe_download's atomic per-file installs, as before
    # (flock over NFS is not dependable anyway).
    import fcntl
    import tempfile
    root.mkdir(parents=True, exist_ok=True)
    lock_name = ("dmt_fetch_"
                 + hashlib.sha256(str(root.resolve()).encode())
                 .hexdigest()[:16] + ".lock")
    import os as _os
    # O_CREAT|O_RDWR with 0o666 (not open(..., "w")): on a shared
    # machine a second user must be able to open the SAME lock file —
    # "w" would both truncate and fail on the other user's 0644 file
    lock_fd = _os.open(Path(tempfile.gettempdir()) / lock_name,
                       _os.O_CREAT | _os.O_RDWR, 0o666)
    lock_f = _os.fdopen(lock_fd, "r+")
    fcntl.flock(lock_f, fcntl.LOCK_EX)
    try:
        recover(list_stranded())
        plan = build_plan(set())

        quarantined: list[tuple] = []
        if args.verify:
            # anything cached that cannot be digest-verified (the synthetic
            # fixture, an unpinned raw idx, a mismatch) steps ASIDE so the
            # download below replaces it with the verifiable archive — but
            # only a successful download deletes it: without egress the
            # fixture cache must survive intact
            for entry, (key, names) in zip(plan, DS._IDX_FILES.items()):
                if entry["cached"] and entry["status"] != "verified":
                    for name in names:
                        for cand in (root / name, root / (name + ".gz")):
                            if cand.exists():
                                aside = cand.with_name(cand.name + ".quarantine")
                                cand.rename(aside)
                                quarantined.append((aside, cand))

        # Snapshot AFTER quarantining: at rollback, every known-name file
        # not in this set was installed by THIS run and must go — including
        # downloads into slots that were empty to begin with (which have no
        # quarantine entry to displace).
        all_names = [n for names in DS._IDX_FILES.values()
                     for name in names for n in (name, name + ".gz")]
        pre_existing = {n for n in all_names if (root / n).exists()}

        ok = DS.maybe_download(root, dataset)
        verified = {}
        unverifiable = []
        for key, names in DS._IDX_FILES.items():
            cached = DS._find_idx(root, names)
            if cached is None:
                ok = False
                continue
            if cached.name in pins:
                got = hashlib.sha256(cached.read_bytes()).hexdigest()
                if got != pins[cached.name]:
                    ok = False
                    continue
                verified[cached.name] = got
            else:
                # a legitimate cache of uncompressed idx files (or an
                # unpinned dataset): structurally validated on install,
                # just not digest-pinnable — present counts as healthy
                unverifiable.append(cached.name)

        downloaded = sorted(n for n in all_names
                            if n not in pre_existing and (root / n).exists())
        if ok:
            for aside, _orig in quarantined:
                aside.unlink(missing_ok=True)
        else:
            # transactional rollback: drop EVERY file this run installed
            # (quarantine-displacing replacements AND downloads into
            # previously-empty slots), then put every quarantined file
            # back — the cache ends exactly as it started
            for n in downloaded:
                (root / n).unlink(missing_ok=True)
            for aside, orig in quarantined:
                orig.unlink(missing_ok=True)
                aside.rename(orig)

        # PROVENANCE.md is only rewritten when this run actually
        # established real data: it downloaded archives, or it
        # digest-verified every slot. A cache this run neither fetched nor
        # verified (unpinnable idx files, --verify not passed) keeps
        # whatever provenance it had — fetch must never relabel a fixture
        # as real.
        establishes_real = bool(downloaded) or (
            bool(pins) and len(verified) == len(DS._IDX_FILES))
        if ok and establishes_real:
            (root / "PROVENANCE.md").write_text(
                f"# Real dataset ({dataset})\n\n"
                f"Downloaded and installed by `launch fetch` at "
                f"{time.strftime('%Y-%m-%d %H:%M:%S UTC', time.gmtime())}.\n"
                + ("Archives verified against the pinned sha256 digests "
                   "(distributedmnist_tpu/data/datasets.py:_PINNED_SHA256):\n\n"
                   + "".join(f"- `{k}`: `{v}`\n" for k, v in sorted(verified.items()))
                   if verified else
                   "No digest-pinnable archives (structural idx validation "
                   "applied on install).\n")
                + ("".join(f"- `{n}`: present, structurally valid, no digest "
                           "pin applicable\n" for n in sorted(unverifiable))
                   if unverifiable else ""))
        if ok:
            print(json.dumps({"ok": True, "dataset": dataset,
                              "data_dir": str(root),
                              "downloaded": downloaded,
                              "verified": sorted(verified),
                              "unverifiable": sorted(unverifiable),
                              "provenance_updated": establishes_real}))
        else:
            print(json.dumps({"ok": False, "dataset": dataset,
                              "data_dir": str(root),
                              "hint": "no egress or mirror/digest failure; "
                                      "the cache was left as-is (fixture runs "
                                      "keep working)"}))
            sys.exit(1)
    finally:
        fcntl.flock(lock_f, fcntl.LOCK_UN)
        lock_f.close()


def _devices(_args) -> None:
    """≙ list_running_instances (tools/tf_ec2.py:371-402) — but the
    'cluster' is whatever mesh JAX sees."""
    import jax
    info = {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "devices": [{"id": d.id, "platform": d.platform,
                     "kind": getattr(d, "device_kind", "?")}
                    for d in jax.devices()],
    }
    print(json.dumps(info, indent=2))


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "campaign":
        # pre-dispatch: the campaign owns its own flags (REMAINDER
        # cannot capture leading options), and its 8-device mesh must
        # be forced before any backend init
        from ..core.mesh import simulate_devices
        simulate_devices(8)
        from .campaign import main as campaign_main
        return campaign_main(argv[1:])
    p = argparse.ArgumentParser(prog="distributedmnist_tpu.launch")
    sub = p.add_subparsers(dest="cmd", required=True)

    pt = sub.add_parser("train", help="run a training experiment")
    pt.add_argument("--config", default=None)
    pt.add_argument("overrides", nargs="*", help="dotted overrides k=v")
    pt.set_defaults(fn=_train)

    pe = sub.add_parser("eval", help="continuous evaluator")
    pe.add_argument("--train_dir", required=True)
    pe.add_argument("--eval_dir", default="/tmp/dmt_eval")
    pe.add_argument("--eval_interval_secs", type=float, default=1.0)
    pe.add_argument("--run_once", action="store_true")
    pe.add_argument("--max_evals", type=int, default=0)
    pe.add_argument("--single_device", action="store_true",
                    help="evaluate on ONE ambient device regardless of the "
                         "training mesh (DP checkpoints only; the lean "
                         "co-located mode)")
    pe.set_defaults(fn=_eval)

    pv = sub.add_parser(
        "serve", help="serving replica: hot-follow a train_dir's "
                      "published checkpoints (digest-verified, torn "
                      "publishes skipped) and serve inference over a "
                      "local socket with admission control and "
                      "zero-drop weight hot-swap")
    pv.add_argument("--train_dir", required=True,
                    help="the publish dir to follow")
    pv.add_argument("--serve-dir", default=".",
                    help="where serve.json / serve_log.jsonl / "
                         "heartbeats land (the worker's own logdir "
                         "under a cluster)")
    pv.add_argument("--host", default=None)
    pv.add_argument("--port", type=int, default=None,
                    help="0 = ephemeral (the bound port is published "
                         "in serve.json)")
    pv.add_argument("--max-batch", type=int, default=None, dest="max_batch")
    pv.add_argument("--queue-depth", type=int, default=None,
                    dest="queue_depth",
                    help="admission bound; a full queue load-sheds "
                         "with a typed reject")
    pv.add_argument("--batch-window-ms", type=float, default=None,
                    dest="batch_window_ms")
    pv.add_argument("--poll-secs", type=float, default=None,
                    dest="poll_secs", help="checkpoint-follow cadence")
    pv.add_argument("--default-deadline-ms", type=float, default=None,
                    dest="default_deadline_ms")
    pv.add_argument("--precision-tier", default=None,
                    dest="precision_tier",
                    help="fp32 | bf16 | int8 — prefer the named "
                         "quantized sidecar tier (quant.publish_tiers) "
                         "over the full-precision artifact; absent/"
                         "torn sidecars fall back to fp32, journaled")
    pv.add_argument("--compute-dtype", default=None, dest="compute_dtype",
                    help="serving-side activations/matmul dtype "
                         "override (serve.compute_dtype)")
    pv.add_argument("--decode", action="store_true",
                    help="serve continuous-batching autoregressive "
                         "decode (streaming tokens over a paged KV "
                         "cache) instead of one-shot classification; "
                         "the followed checkpoint must be a dense-FFN "
                         "causal LM")
    pv.add_argument("--decode-slots", type=int, default=None,
                    dest="decode_slots",
                    help="concurrently-generating sequences per "
                         "replica (decode.decode_slots)")
    pv.add_argument("--max-new-tokens", type=int, default=None,
                    dest="max_new_tokens",
                    help="per-request generation ceiling "
                         "(decode.max_new_tokens)")
    pv.add_argument("--max-prompt-len", type=int, default=None,
                    dest="max_prompt_len",
                    help="longest admissible prompt "
                         "(decode.max_prompt_len)")
    pv.add_argument("--swap-policy", default=None, dest="swap_policy",
                    help="pin | restart — what a weight hot-swap does "
                         "to sequences mid-generation "
                         "(decode.swap_policy)")
    pv.add_argument("--attention-kernel", default=None,
                    dest="attention_kernel",
                    help="dense | paged — decode attention path "
                         "(decode.attention_kernel); paged walks each "
                         "slot's block table in-kernel, O(actual "
                         "context) per token")
    pv.add_argument("--tp-ranks", type=int, default=None,
                    dest="tp_ranks",
                    help="boot the replica as an N-rank tensor-"
                         "parallel process group (serve.tp_ranks): "
                         "rank 0 owns the socket and the sharded "
                         "serving mesh, other ranks shard-verify "
                         "every publish; any rank dying takes the "
                         "whole group down for a unit restart")
    pv.add_argument("--tp-rank", type=int, default=None, dest="tp_rank",
                    help=argparse.SUPPRESS)  # internal: set by the
    # group supervisor when re-invoking serve per rank
    pv.set_defaults(fn=_serve)

    pl = sub.add_parser(
        "serve-load", help="closed-loop load generator over a serving "
                           "cluster (round-robin failover shim, "
                           "per-request journal, p50/p99 summary)")
    pl.add_argument("--cluster-root", default=None,
                    help="LocalProcessCluster root to discover "
                         "worker*/serve.json endpoints from")
    pl.add_argument("--endpoints", default=None,
                    help="comma-separated host:port list (overrides "
                         "--cluster-root)")
    pl.add_argument("--requests", type=int, default=200)
    pl.add_argument("--concurrency", type=int, default=2)
    pl.add_argument("--deadline-s", type=float, default=5.0,
                    dest="deadline_s")
    pl.add_argument("--max-attempts", type=int, default=6,
                    dest="max_attempts")
    pl.add_argument("--ready-timeout-s", type=float, default=120.0,
                    dest="ready_timeout_s")
    pl.add_argument("--out", default="loadgen.jsonl",
                    help="per-request journal path")
    pl.set_defaults(fn=_serve_load)

    ps = sub.add_parser("sweep", help="run a directory of experiment configs")
    ps.add_argument("--configs", required=True)
    ps.add_argument("--results", required=True)
    ps.add_argument("--only", default=None, help="comma-separated names")
    ps.set_defaults(fn=_sweep)

    pr = sub.add_parser("report", help="figures + stats from run logs")
    pr.add_argument("--train_dir", required=True)
    pr.add_argument("--eval_dir", default=None)
    pr.add_argument("--out", required=True)
    pr.add_argument("--name", default="experiment")
    pr.set_defaults(fn=_report)

    pd = sub.add_parser("devices", help="show mesh topology")
    pd.set_defaults(fn=_devices)

    pf = sub.add_parser(
        "fetch", help="download + digest-verify the real dataset archives "
                      "(one command from fixture cache to verified real "
                      "data, ≙ src/mnist_data.py:39,179)")
    pf.add_argument("--dataset", default="mnist",
                    choices=["mnist", "fashion_mnist"])
    pf.add_argument("--data-dir", default="data_cache/mnist")
    pf.add_argument("--verify", action="store_true",
                    help="re-verify cached archives against the pinned "
                         "sha256 digests; non-verifiable cached files "
                         "(e.g. the synthetic fixture) are replaced")
    pf.add_argument("--dry-run", action="store_true",
                    help="print the fetch/verify plan without touching "
                         "the network or the cache")
    pf.set_defaults(fn=_fetch)

    def _pod(args) -> None:
        from .pod import main as pod_main
        pod_main(args.rest)

    pp = sub.add_parser("pod", help="TPU pod-slice lifecycle (gcloud)",
                        add_help=False)
    pp.add_argument("rest", nargs=argparse.REMAINDER)
    pp.set_defaults(fn=_pod)

    def _cluster(args) -> None:
        from .cluster import main as cluster_main
        cluster_main(args.rest)

    pc = sub.add_parser(
        "cluster", help="backend-pluggable cluster lifecycle "
                        "(local process-cluster or gcloud TPU-VM; "
                        "fault plans, command journal, supervised "
                        "self-healing runs, seeded chaos campaigns "
                        "with invariant checking — `cluster chaos "
                        "--trials N --seed S --until-step M`)",
        add_help=False)
    pc.add_argument("rest", nargs=argparse.REMAINDER)
    pc.set_defaults(fn=_cluster)

    sub.add_parser("campaign",
                   help="run the full experiment campaign grid "
                        "(options: see `campaign --help`)")

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
