"""Resource broker: demand-driven autoscaling over one mixed roster.

One device/process budget, two tenants. A publisher trainer (plus any
extra train workers) and a set of serving replicas share a single
:class:`~.cluster.LocalProcessCluster` roster — serving slots are the
worker ids carrying a ``worker_commands`` override, train slots run
the default payload. The :class:`ResourceBroker` owns that budget and
trades slots between the tenants on live demand:

* **pressure up** — the loadgen's rolling-window snapshot (p99,
  overloaded-reject rate, decode TTFT p99) or a replica heartbeat's
  pressure fields (admission-queue occupancy, KV block-pool
  exhaustion) crosses its high-water mark: the broker drains the
  highest train worker (never the publisher), reshapes the roster
  through :meth:`~.cluster.LocalProcessCluster.reconfigure`, and
  brings a new serving replica up in the freed slot — promoted from a
  warm standby when the parked pool runs the serving payload, cold
  spawned otherwise.
* **pressure down** — every present signal is back below its
  LOW-water mark (hysteresis: the band between low and high is dead,
  so a signal hovering near the threshold cannot flap the roster):
  the newest replica drains and a train worker grows back, resuming
  from the survivors' newest checkpoint via the reshape's seeding.

Decisions are paced by a cooldown measured from the last *completed*
change, and the split never leaves the configured
``[min,max]`` bounds for either tenant (:class:`~..core.config.
BrokerConfig`). The decision core (:func:`decide`) is a pure function
of (config, signal snapshot, last-change time, now) — deterministic,
property-testable without a process tree.

The broker runs ON the supervise thread as a
:meth:`~.supervisor.ClusterSupervisor.supervise_until_step` per-tick
callback (``on_tick=broker.tick``): a roster change it performs can
never race the supervisor's per-worker trackers, and a True return
resets them under the same discipline as the supervisor's own
reconfigures.

Every decision is journaled as an ``event: "autoscale"`` record
(declared in ``obsv/schema.py``): ``begin`` carries the trigger
signal, its observed value, and the threshold it crossed — the causal
license the replay invariant (``obsv/invariants.py`` "autoscale")
demands for every roster change in a brokered run; ``complete``
closes it with the detect→capacity-live reaction time.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from pathlib import Path
from typing import Any

from ..core.config import BrokerConfig
from ..obsv.journal import tail_records
from ..servesvc.loadgen import read_latest_window

logger = logging.getLogger(__name__)

SCALE_UP = "scale_up_serving"
SCALE_DOWN = "scale_down_serving"

# signal -> (high-water cfg attr, op that means "pressure", low-water
# cfg attr, op that means "calm"). KV pressure is inverted: a LOW free
# fraction is the pressure signal.
_THRESHOLDS: tuple[tuple[str, str, str, str, str], ...] = (
    ("p99_ms", "p99_high_ms", ">=", "p99_low_ms", "<="),
    ("reject_rate", "reject_high", ">=", "reject_low", "<="),
    ("ttft_p99_ms", "ttft_high_ms", ">=", "ttft_low_ms", "<="),
    ("queue_frac", "queue_high", ">=", "queue_low", "<="),
    ("kv_free_frac", "kv_free_low", "<=", "kv_free_high", ">="),
)


def threshold_holds(value: float, op: str, threshold: float) -> bool:
    """The one comparison the journal's ``begin`` records license
    against — shared with the replay invariant so the two can never
    disagree about what "crossed" means."""
    return value >= threshold if op == ">=" else value <= threshold


def tail_heartbeat(logdir: str | Path,
                   tail_bytes: int = 1 << 15) -> dict | None:
    """The newest intact heartbeat record in a replica's
    ``train_log.jsonl`` — the per-replica pressure channel (queue
    occupancy, KV block-pool fill) the broker polls every tick. Reads
    only the file tail and scans backwards past torn lines, same
    discipline (obsv/journal.py ``tail_records``) as
    :func:`~..servesvc.loadgen.read_latest_window`."""
    for rec in tail_records(Path(logdir) / "train_log.jsonl",
                            tail_bytes=tail_bytes):
        if rec.get("event") == "heartbeat":
            return rec
    return None


def collect_signals(window: dict | None, heartbeats: list[dict],
                    train_steps_per_s: float | None = None,
                    now: float | None = None,
                    window_s: float = 10.0) -> dict[str, float]:
    """Fold the raw observations into the canonical signal snapshot
    :func:`decide` consumes. Pure.

    ``window``: the newest loadgen rolling-window record (or None) —
    contributes ``p99_ms`` / ``reject_rate`` / ``ttft_p99_ms``, but
    only while fresh (a snapshot older than two windows describes a
    load that may no longer exist). ``heartbeats``: one record per
    live serving replica — queue pressure aggregates as the MAX
    occupancy fraction (one saturated replica is a problem even if
    its peers idle), KV pressure as the MIN free fraction.
    ``train_steps_per_s`` rides along informationally (journals,
    bench detail); it is not a scaling trigger."""
    sig: dict[str, float] = {}
    if window is not None:
        t = window.get("time")
        fresh = (not isinstance(t, (int, float)) or now is None
                 or (now - t) <= max(2 * window_s, 5.0))
        if fresh:
            for name in ("p99_ms", "reject_rate", "ttft_p99_ms"):
                v = window.get(name)
                if isinstance(v, (int, float)):
                    sig[name] = float(v)
    queue_fracs: list[float] = []
    kv_fracs: list[float] = []
    for hb in heartbeats:
        if not isinstance(hb, dict):
            continue
        qd, ql = hb.get("queue_depth"), hb.get("queue_limit")
        if isinstance(qd, (int, float)) and isinstance(ql, (int, float)) \
                and ql > 0:
            queue_fracs.append(qd / ql)
        free, tot = hb.get("kv_blocks_free"), hb.get("kv_blocks_total")
        if isinstance(free, (int, float)) and isinstance(tot, (int, float)) \
                and tot > 0:
            kv_fracs.append(free / tot)
    if queue_fracs:
        sig["queue_frac"] = max(queue_fracs)
    if kv_fracs:
        sig["kv_free_frac"] = min(kv_fracs)
    if isinstance(train_steps_per_s, (int, float)):
        sig["train_steps_per_s"] = float(train_steps_per_s)
    return sig


@dataclasses.dataclass(frozen=True)
class Decision:
    """One roster change the decision core wants: what fires it
    (``trigger``/``value``/``threshold``/``op`` — exactly the license
    the journal's ``begin`` record carries) and the before/after
    tenant split it moves to."""
    decision: str
    trigger: str
    value: float
    threshold: float
    op: str
    old_serve: int
    new_serve: int
    old_train: int
    new_train: int


def decide(cfg: BrokerConfig, serve_n: int, train_n: int,
           signals: dict[str, float], last_change_t: float | None,
           now: float) -> Decision | None:
    """The pure decision core: deterministic in its arguments, no
    clock, no I/O — the property tests replay signal traces through
    this directly.

    Scale-up fires on the FIRST pressure signal (in the canonical
    :data:`_THRESHOLDS` order) at or past its high-water mark, and
    only with headroom on both sides of the trade (a serving slot
    available under ``max_serve_replicas``, a train worker to give up
    above ``min_train_workers``). Scale-down requires EVERY present
    signal calm below its low-water mark — the dead band between the
    marks is the hysteresis that keeps a hovering signal from
    flapping the roster — and a cooldown window after the last change
    suppresses everything."""
    if last_change_t is not None and (now - last_change_t) < cfg.cooldown_s:
        return None
    present = []
    for name, hi_attr, hi_op, lo_attr, lo_op in _THRESHOLDS:
        v = signals.get(name)
        if isinstance(v, (int, float)):
            present.append((name, float(v), float(getattr(cfg, hi_attr)),
                            hi_op, float(getattr(cfg, lo_attr)), lo_op))
    if not present:
        return None
    for name, v, hi, hi_op, lo, lo_op in present:
        if threshold_holds(v, hi_op, hi):
            if (serve_n >= cfg.max_serve_replicas
                    or train_n <= cfg.min_train_workers):
                return None  # pressure, but the trade has no headroom
            return Decision(SCALE_UP, name, round(v, 6), hi, hi_op,
                            serve_n, serve_n + 1, train_n, train_n - 1)
    if serve_n > cfg.min_serve_replicas and all(
            threshold_holds(v, lo_op, lo)
            for _, v, _, _, lo, lo_op in present):
        name, v, _, _, lo, lo_op = present[0]
        grow = train_n < cfg.max_train_workers
        return Decision(SCALE_DOWN, name, round(v, 6), lo, lo_op,
                        serve_n, serve_n - 1, train_n,
                        train_n + (1 if grow else 0))
    return None


class ResourceBroker:
    """Executes :func:`decide`'s roster changes through the backend's
    existing verbs, journaling every move. Construct over a running
    :class:`~.supervisor.ClusterSupervisor` and pass :meth:`tick` as
    ``supervise_until_step(..., on_tick=broker.tick)``."""

    def __init__(self, supervisor: Any, cfg: BrokerConfig | None = None,
                 serve_command: str = "",
                 loadgen_journal: str | Path | None = None,
                 warm_standbys: int = 0):
        if not serve_command:
            raise ValueError("ResourceBroker needs the serve_command a "
                             "scaled-up replica slot will run")
        self.sup = supervisor
        self.backend = supervisor.backend
        self.cfg = cfg or BrokerConfig()
        self.cfg.validate()
        self.serve_command = serve_command
        self.loadgen_journal = (Path(loadgen_journal)
                                if loadgen_journal is not None else None)
        self.warm_standbys = warm_standbys
        self.fired = 0
        self.decisions: list[dict[str, Any]] = []
        self._last_change_t: float | None = None
        self._pending: dict[str, Any] | None = None
        self._train_prog: tuple[float, int] | None = None
        self._started = False

    # -- journaling ------------------------------------------------------

    def _autoscale_event(self, action: str, **fields: Any) -> None:
        self.sup._record({"event": "autoscale", "layer": "broker",
                          "action": action, "time": time.time(), **fields})

    # -- roster/signal observation ----------------------------------------

    def _roles(self, workers: list[dict]) -> tuple[list[int], list[int]]:
        """(serving ids, train ids): a slot is SERVING iff its
        ``worker_commands`` override IS the serving payload — the
        broker itself maintains that mapping as it trades slots, so
        the roster's role split is always derivable from config +
        state, never cached. Command EQUALITY (not mere override
        presence) keeps a train worker with its own overridden payload
        (a donor trainer paced differently from the publisher) on the
        train side of the trade."""
        cmds = getattr(self.backend.cfg, "worker_commands", None) or {}
        serve = sorted(w["worker"] for w in workers
                       if cmds.get(str(w["worker"])) == self.serve_command)
        train = sorted(w["worker"] for w in workers
                       if cmds.get(str(w["worker"])) != self.serve_command)
        return serve, train

    def _train_rate(self, train_ids: list[int],
                    progress: dict[int, int] | None,
                    now: float) -> float | None:
        if not progress:
            return None
        steps = [progress.get(k, -1) for k in train_ids]
        steps = [s for s in steps if s >= 0]
        if not steps:
            return None
        s = max(steps)
        prev = self._train_prog
        self._train_prog = (now, s)
        if prev is None or now <= prev[0]:
            return None
        return max(0.0, (s - prev[1]) / (now - prev[0]))

    def read_signals(self, workers: list[dict],
                     progress: dict[int, int] | None,
                     now: float) -> dict[str, float]:
        window = (read_latest_window(self.loadgen_journal)
                  if self.loadgen_journal is not None else None)
        serve_ids, train_ids = self._roles(workers)
        by_id = {w["worker"]: w for w in workers}
        heartbeats = [hb for hb in
                      (tail_heartbeat(by_id[k]["logdir"])
                       for k in serve_ids if by_id[k].get("logdir"))
                      if hb is not None]
        rate = self._train_rate(train_ids, progress, now)
        return collect_signals(window, heartbeats, rate, now=now,
                               window_s=self.cfg.window_s)

    # -- the per-tick entry point -----------------------------------------

    def start(self) -> None:
        """One-time setup: provision the warm-standby pool when asked.
        Best-effort — the pool is an optimization, cold spawns are the
        always-correct fallback."""
        if self._started:
            return
        self._started = True
        if self.warm_standbys > 0 and hasattr(self.backend,
                                              "ensure_standbys"):
            try:
                self.backend.ensure_standbys(self.warm_standbys)
            except Exception as e:
                logger.warning("broker could not provision %d standbys "
                               "(%s: %s) — scaling will cold-spawn",
                               self.warm_standbys, type(e).__name__, e)

    def tick(self, got: dict | None = None) -> bool:
        """One supervise-loop tick: settle any in-flight change first
        (its capacity going live is what closes the journal entry and
        starts the cooldown), otherwise observe → decide → execute.
        Returns True iff the roster changed this tick."""
        self.start()
        now = time.time()
        got = got or {}
        workers = got.get("workers")
        if workers is None:
            workers = (self.backend.status() or {}).get("workers", [])
        if self._pending is not None:
            self._settle(workers, now)
            return False
        serve_ids, train_ids = self._roles(workers)
        signals = self.read_signals(workers, got.get("worker_progress"),
                                    now)
        d = decide(self.cfg, len(serve_ids), len(train_ids), signals,
                   self._last_change_t, now)
        if d is None:
            return False
        return self.execute(d, serve_ids, train_ids, now)

    # -- execution ---------------------------------------------------------

    def execute(self, d: Decision, serve_ids: list[int],
                train_ids: list[int], now: float) -> bool:
        """Perform one decided trade. Scale-up: drain the highest train
        worker (the publisher, worker 0, is protected by the decision
        core's ``min_train_workers >= 1`` bound), reshape the roster to
        drop it and grow a fresh slot (checkpoint-seeded by the
        backend), register the serving payload for that slot, and
        bring it up — warm standby if the parked pool runs the serving
        payload, cold spawn otherwise. Scale-down mirrors it: drain
        the newest replica, reshape, grow a train worker back (which
        resumes from the seeded checkpoint) while under
        ``max_train_workers``."""
        self._autoscale_event(
            "begin", decision=d.decision, trigger=d.trigger, value=d.value,
            threshold=d.threshold, op=d.op, old_serve=d.old_serve,
            new_serve=d.new_serve, old_train=d.old_train,
            new_train=d.new_train, window_s=self.cfg.window_s,
            cooldown_s=self.cfg.cooldown_s)
        self._last_change_t = now
        backend = self.backend
        try:
            if d.decision == SCALE_UP:
                victim = max(train_ids)
                survivors = sorted(set(serve_ids)
                                   | (set(train_ids) - {victim}))
                self._drain(victim)
                rec = backend.reconfigure(len(survivors) + 1,
                                          survivors=survivors)
                new_id = [k for k in rec["workers"]
                          if k not in survivors][0]
                # promotion must precede the command registration:
                # promote_standby refuses overridden slots (role-swap
                # protection), and here the role swap is exactly the
                # point — guarded by _maybe_promote's pool-payload check
                promoted = self._maybe_promote(new_id, self.serve_command)
                self._set_serve_command(new_id)
                if not promoted:
                    backend.restart_worker(new_id)
                self._pending = {"decision": d, "t0": now,
                                 "worker": new_id, "role": "serve",
                                 "dropped": victim}
            else:
                victim = max(serve_ids)
                survivors = sorted((set(serve_ids) - {victim})
                                   | set(train_ids))
                grow = d.new_train > d.old_train
                self._drain(victim)
                rec = backend.reconfigure(
                    len(survivors) + (1 if grow else 0),
                    survivors=survivors)
                self._clear_serve_command(victim)
                new_id = None
                if grow:
                    new_id = [k for k in rec["workers"]
                              if k not in survivors][0]
                    promoted = self._maybe_promote(
                        new_id,
                        getattr(backend.cfg, "train_command", ""))
                    if not promoted:
                        backend.restart_worker(new_id)
                self._pending = {"decision": d, "t0": now,
                                 "worker": new_id, "role": "train",
                                 "dropped": victim}
        except Exception as e:
            logger.exception("autoscale %s failed", d.decision)
            self._autoscale_event("error", decision=d.decision,
                                  error=f"{type(e).__name__}: {e}")
            self._pending = None
            # the reshape may have landed before the failure: report a
            # roster change so the supervisor resets its trackers
            return True
        return True

    def _drain(self, victim: int) -> None:
        """Graceful SIGTERM to the victim's process group, bounded wait
        for exit — a trainer flushes its preemption checkpoint, a
        replica finishes in-flight requests. Stragglers are killed by
        the reshape that follows."""
        backend = self.backend
        if not hasattr(backend, "stop_all"):
            return
        backend.stop_all(worker=str(victim))
        drain_s = min(float(getattr(self.sup.cfg, "reconfigure_drain_s",
                                    10.0)), 10.0)
        deadline = time.monotonic() + drain_s
        while time.monotonic() < deadline:
            st = backend.status() or {}
            sel = [w for w in st.get("workers", [])
                   if w.get("worker") == victim]
            if not sel or not sel[0].get("alive"):
                return
            time.sleep(0.2)

    def _maybe_promote(self, k: int, role_command: str) -> bool:
        """Promote a warm standby into slot ``k`` — but only when the
        parked pool runs ``role_command``, the payload this slot needs.
        A pool parked on the wrong payload (train spares for a serving
        slot, or vice versa) silently swapping the role is exactly the
        failure promote_standby's own guard exists for; this is the
        broker-side mirror of that check for the slots it deliberately
        re-roles."""
        backend = self.backend
        if not role_command or not hasattr(backend, "promote_standby"):
            return False
        resolved = getattr(backend.cfg, "resolved_standby_command", None)
        pool_cmd = resolved() if callable(resolved) else ""
        if pool_cmd != role_command:
            return False
        try:
            return bool(backend.promote_standby(k))
        except Exception as e:
            logger.warning("standby promotion for worker %s failed "
                           "(%s: %s) — cold spawning", k,
                           type(e).__name__, e)
            return False

    def _set_serve_command(self, k: int) -> None:
        cfg = self.backend.cfg
        cmds = dict(getattr(cfg, "worker_commands", None) or {})
        cmds[str(k)] = self.serve_command
        self.backend.cfg = dataclasses.replace(cfg, worker_commands=cmds)

    def _clear_serve_command(self, k: int) -> None:
        cfg = self.backend.cfg
        cmds = dict(getattr(cfg, "worker_commands", None) or {})
        if cmds.pop(str(k), None) is not None:
            self.backend.cfg = dataclasses.replace(cfg,
                                                   worker_commands=cmds)

    # -- settlement ---------------------------------------------------------

    def _serve_live_at(self, k: int, workers: list[dict],
                       t0: float) -> float | None:
        """When the new replica's capacity went LIVE: its ``serve.json``
        endpoint card landing (written at bind) or its first heartbeat,
        whichever evidence appears. The grown slot's logdir is fresh,
        so any card there postdates the decision."""
        w = next((w for w in workers if w.get("worker") == k), None)
        if w is None or not w.get("logdir"):
            return None
        card = Path(w["logdir"]) / "serve.json"
        try:
            m = card.stat().st_mtime
            if m >= t0 - 1.0:
                return m
        except OSError:
            pass
        hb = tail_heartbeat(w["logdir"])
        if (hb is not None and isinstance(hb.get("time"), (int, float))
                and hb["time"] >= t0):
            return float(hb["time"])
        return None

    def _train_live_at(self, k: int, workers: list[dict],
                       t0: float) -> float | None:
        w = next((w for w in workers if w.get("worker") == k), None)
        if w is None:
            return None
        if not w.get("logdir"):
            return time.time() if w.get("alive") else None
        log = Path(w["logdir"]) / "train_log.jsonl"
        try:
            m = log.stat().st_mtime
            return m if m >= t0 - 1.0 else None
        except OSError:
            return None

    def _settle(self, workers: list[dict], now: float) -> None:
        """Close the in-flight change: journal ``complete`` with the
        detect→capacity-live reaction time once the new capacity shows
        evidence of life (or the pure shrink's victim left the
        roster), ``error`` past the settle timeout. The cooldown
        restarts from settlement — back-to-back trades cannot overlap."""
        p = self._pending
        assert p is not None
        d: Decision = p["decision"]
        if p["role"] == "serve":
            live_at = self._serve_live_at(p["worker"], workers, p["t0"])
        elif p["worker"] is None:
            # pure shrink: the reshape already removed the victim — the
            # budget change is live as soon as we observe the roster
            live_at = now
        else:
            live_at = self._train_live_at(p["worker"], workers, p["t0"])
        if live_at is not None:
            serve_ids, train_ids = self._roles(workers)
            fields: dict[str, Any] = {
                "decision": d.decision, "trigger": d.trigger,
                "reaction_s": round(max(0.0, live_at - p["t0"]), 3),
                "serve": len(serve_ids), "train": len(train_ids),
                "dropped": p["dropped"]}
            if p["worker"] is not None:
                fields["worker"] = p["worker"]
            self._autoscale_event("complete", **fields)
            self.decisions.append({**fields, "t": now})
            self.fired += 1
            self._pending = None
            self._last_change_t = now
        elif now - p["t0"] > self.cfg.settle_timeout_s:
            self._autoscale_event(
                "error", decision=d.decision,
                error=f"settle timeout: worker {p['worker']} showed no "
                      f"life within {self.cfg.settle_timeout_s}s")
            self._pending = None

    # -- reporting ----------------------------------------------------------

    def summary(self) -> dict[str, Any]:
        """The run's autoscale summary (decision mix, reaction-time
        percentiles, flap count) from the supervisor's own event
        stream — what the chaos trial record and bench detail embed."""
        from ..obsv.journal import summarize_autoscale
        recs = [r for r in self.sup.events
                if r.get("event") == "autoscale"]
        got = summarize_autoscale(recs)
        got["fired"] = self.fired
        return got
