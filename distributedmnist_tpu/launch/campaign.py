"""The flagship experiment campaign — the deliverable the reference
exists to produce (tools/benchmark.py:265-292 drove the same grids on
EC2 and plotted the curves).

Runs the full configs/ grid on the simulated 8-device mesh:

* quorum sweep  k ∈ {1,2,4,6,7,8}-of-8   (≙ cfg/50_workers/*_aggregate_sync)
* interval sweep {3000..7000} ms          (≙ cfg/50_workers/*_interval)
* worker-time-CDF grid, 4 straggler profiles (≙ cfg/time_cdf_cfgs/*)
* extras: fashion-mnist timeout drop, CIFAR ResNet-20 (scaled for the
  1-core CPU budget — overrides recorded in the result records),
  synthetic-LM transformer
* repro_mnist99: the one-command 99% config (configs/repro/
  mnist_99.json) end-to-end, evaluator oracle live against it

with the continuous evaluator (evalsvc) live against the quorum k=8 run
— the reference's oracle (src/nn_eval.py:117-140) watching an actual
training run.

Data: the idx fixture (data/fixtures.py) is materialized first so every
mnist/fashion_mnist config exercises the REAL ingest path — idx.gz
parse → normalization → sharding — not the in-memory synthetic
fallback.

Entry points: ``python run_campaign.py`` at the repo root (forces the
8-device CPU mesh first) or ``python -m distributedmnist_tpu.launch
campaign``; ``--finalize-only`` regenerates reports from disk.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

from ..core.config import ExperimentConfig
from ..core.log import JsonlSink, get_logger
from .sweep import run_experiment, write_report

logger = get_logger("campaign")

GROUPS = {
    "quorum": [f"quorum_k{k}_of_8" for k in (1, 2, 4, 6, 7, 8)],
    "interval": [f"interval_{ms}ms" for ms in (3000, 4000, 5000, 6000, 7000)],
    "cdf": ["cdf_uniform", "cdf_lognormal_mild", "cdf_lognormal_heavy",
            "cdf_spike"],
    "extras": ["fashion_mnist_timeout", "cifar10_resnet20_sync",
               "synthetic_lm_transformer"],
    # the one-command 99% repro (configs/repro/mnist_99.json) run through
    # the same harness, with the evaluator oracle live against it — the
    # reference's headline result (99%+ MNIST, src/nn_eval.py:95-103)
    "repro_mnist99": ["mnist_99"],
    # Experiment A at the reference's TRUE topology: 50 workers,
    # replicas_to_aggregate ∈ {1,10,20,30,40,49,50}
    # (cfg/50_workers/*_aggregate_sync:10). The configs force a
    # 50-virtual-device mesh (mesh.simulate_devices; run_experiment's
    # ensure_mesh restores the ambient mesh afterwards):
    #   python run_campaign.py --groups quorum50
    "quorum50": [f"quorum50_k{k}_of_50" for k in (1, 10, 20, 30, 40, 49, 50)],
    # Experiment C at the true topology: the four worker-time CDF
    # profiles on the 50-device mesh (full-barrier mode, per-replica
    # timing all-gather at the reference's actual worker count)
    "cdf50": ["cdf50_uniform", "cdf50_lognormal_mild",
              "cdf50_lognormal_heavy", "cdf50_spike"],
    # Convergence proofs for the two disciplines the grids leave short
    # (the grids bound steps on wall-clock): one interval-mode run and
    # one 50-replica cdf-mode run trained until the live evaluator's
    # 99% oracle passes — ≙ the reference driving every discipline to
    # comparable convergence (tools/benchmark.py:265-279,
    # cfg/50_workers/*_interval):
    #   python run_campaign.py --groups long
    "long": ["interval_long", "cdf50_long"],
}

# Groups a plain `python run_campaign.py` runs. The 50-device groups
# are excluded on wall-clock grounds only (300-step runs at 50-way
# SPMD, hours on one core) — launch them separately:
#   python run_campaign.py --groups quorum50
#   python run_campaign.py --groups cdf50
DEFAULT_GROUPS = [g for g in GROUPS if g not in ("quorum50", "cdf50", "long")]

# CPU-budget scale-downs, recorded verbatim into each result record.
# (Note: the 8-replica quorum/interval configs carry the reference's
# experiment batch size 128 — cfg/50_workers/*:63. The quorum50 configs
# are the exception: they BAKE IN a 16/replica batch — global 800 vs
# the reference's 128/worker = 6400 (cfg/50_workers/*:63) — as a CPU
# scale-down of their own, in the config file rather than here. Only
# the items below are campaign-local deviations.)
OVERRIDES = {
    "cifar10_resnet20_sync": {"train.max_steps": 150, "data.batch_size": 256,
                              "train.log_every_steps": 10},
    "synthetic_lm_transformer": {"train.max_steps": 200},
    # wall-clock checkpoint cadence (≙ Supervisor save_model_secs=20,
    # src/distributed_train.py:76-77) so the live evaluator sees a
    # stream of checkpoints, not just the final one
    "quorum_k8_of_8": {"train.save_interval_secs": 15.0},
    # same, and also: at this run's CPU step rate the config's step-based
    # cadence (save_interval_steps=500 ≈ 13 min) outlives the
    # evaluator's 600 s first-checkpoint timeout — wall-clock saves keep
    # the oracle fed from the start
    "mnist_99": {"train.save_interval_secs": 60.0},
    # cdf50 keeps the cdf grid's per-replica batch (128 → global 6400
    # over 50 replicas) so the timing CDFs are comparable; the step
    # budget is what yields to the 1-core clock — 100 steps is 100
    # timing samples per replica, plenty for the percentile curves
    **{f"cdf50_{p}": {"train.max_steps": 100}
       for p in ("uniform", "lognormal_mild", "lognormal_heavy", "spike")},
    # Interval sweep: UPDATE-count-matched step budgets. The configs
    # keep the reference's fixed 300-iteration benchmark convention
    # (tools/benchmark.py:265 n_iters), but a fixed step count gives
    # slower pacings fewer applied updates (300 steps at the modeled
    # ~840 ms step = 84 updates at 3000 ms but only 36 at 7000 ms), so
    # a final-accuracy column misreads as "slower pacing is broken".
    # steps ∝ interval_ms equalizes applied updates (measured 681-746
    # across the sweep — the ~680 count long/interval_long converged
    # at), so the sweep's accuracy column compares pacings at equal,
    # convergence-sufficient update budgets.
    **{f"interval_{ms}ms": {"train.max_steps": 800 * ms // 1000}
       for ms in (3000, 4000, 5000, 6000, 7000)},
}

EVALUATED_RUN = "quorum_k8_of_8"  # kept for callers that import it
# the runs the live evaluator watches (one per group that has one)
EVALUATED_RUNS = {EVALUATED_RUN, "mnist_99", "interval_long", "cdf50_long"}


def resolve_config_path(configs_dir: Path, name: str) -> Path:
    """Grid configs sit in configs/; repro configs one level down."""
    candidates = [configs_dir / f"{name}.json",
                  configs_dir / "repro" / f"{name}.json"]
    for path in candidates:
        if path.exists():
            return path
    raise FileNotFoundError(
        f"no config named {name!r}; tried "
        + " and ".join(str(p) for p in candidates))


def run_group(group: str, names: list[str], results_dir: Path,
              configs_dir: Path, data_dir: Path, quick: bool) -> list[dict]:
    gdir = results_dir / group
    gdir.mkdir(parents=True, exist_ok=True)
    records = []
    with JsonlSink(gdir / "sweep_results.jsonl") as sink:
        for name in names:
            cfg = ExperimentConfig.from_file(
                resolve_config_path(configs_dir, name))
            ov = {"data.data_dir": str(data_dir / cfg.data.dataset),
                  "data.download": False}
            ov.update(OVERRIDES.get(name, {}))
            if quick:
                ov["train.max_steps"] = 20
            cfg = cfg.override(ov)
            # Campaign semantics are RUN, not resume —
            # run_experiment's fresh default (train.resume=False)
            # guarantees it without deleting the previous artifacts
            # up front (a pre-run wipe would destroy the committed
            # evidence of a multi-hour run if the replacement crashed
            # mid-flight). History lives in sweep_results.jsonl.
            ev = None
            if name in EVALUATED_RUNS and not quick:
                ev = start_evaluator(gdir / name)
            t0 = time.time()
            try:
                rec = run_experiment(cfg, gdir)
            finally:
                if ev is not None:
                    stop_evaluator(ev, gdir / name)
                    # redraw this run's report with the evaluator's log
                    # so precision-vs-time (the oracle curve) lands
                    from ..obsv.report import generate_report
                    generate_report(gdir / name / "train",
                                    gdir / name / "eval",
                                    gdir / name / "figures", name=name)
            rec["overrides"] = ov
            rec["group"] = group
            logger.info("[%s] %s done in %.0fs", group, name, time.time() - t0)
            sink.write(rec)
            records.append(rec)
    write_report(records, gdir)
    return records


def start_evaluator(run_dir: Path) -> subprocess.Popen:
    """Launch the continuous evaluator against a run's train dir — the
    reference's separate evaluator machine (tools/tf_ec2.py:130-146).

    Runs --single_device under ``nice -n 5``: on a shared host the
    trainer's N-device collectives abort hard (XLA's 40 s rendezvous
    termination) if another full-mesh process starves them — measured
    twice on the 1-core box before this. A one-device evaluator has no
    collectives of its own and cannot starve the trainer's (one
    runnable thread against the trainer's N at higher weight), while
    nice 19 was measured to starve the EVALUATOR into uselessness
    (~5% of the core: 25 min to merely boot against a 50-device
    trainer) — 5 is the balance. (``nice`` as a command prefix, NOT
    preexec_fn: forking this multithreaded JAX parent and running
    Python pre-exec can deadlock the child.)

    The child's env is scrubbed of the parent's forced-mesh settings
    (simulate_devices mutates XLA_FLAGS/JAX_PLATFORMS process-wide) so
    the evaluator boots the true AMBIENT backend — one real device,
    not N virtual CPU devices it would immediately discard."""
    from ..core.mesh import strip_forced_platform_env
    run_dir.mkdir(parents=True, exist_ok=True)
    eval_dir = run_dir / "eval"
    env = strip_forced_platform_env(os.environ)
    with open(run_dir / "evaluator_stdout.log", "w") as log:
        proc = subprocess.Popen(
            ["nice", "-n", "5",
             sys.executable, "-m", "distributedmnist_tpu.launch", "eval",
             "--train_dir", str(run_dir / "train"),
             "--eval_dir", str(eval_dir),
             "--eval_interval_secs", "2.0",
             "--single_device"],
            stdout=log, stderr=subprocess.STDOUT,  # child keeps its dup
            env=env)
    logger.info("evaluator pid %d watching %s", proc.pid, run_dir / "train")
    return proc


def stop_evaluator(proc: subprocess.Popen, run_dir: Path) -> None:
    # give it one last poll cycle to evaluate the final checkpoint
    time.sleep(8.0)
    proc.terminate()
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
    logger.info("evaluator stopped (rc=%s)", proc.returncode)


def prune_heavy_artifacts(results_dir: Path) -> None:
    """Drop checkpoint payloads before committing: fully reproducible
    from config + seed, and tens of MB each."""
    for p in results_dir.rglob("ckpt-*.msgpack"):
        p.unlink()
    for p in results_dir.rglob("CHECKPOINT"):
        p.unlink()


# Self-description for the summary JSON: groups whose accuracy columns
# are step-budget-bounded by design carry a pointer to the long-run
# convergence proof, so the summary cannot be misread on its own. Each
# note is keyed (group, proof-run name) and only emitted when the cited
# proof run actually exists in the same results dir.
SUMMARY_NOTES = {
    ("interval", "interval_long"): (
        "budgets are update-count-matched: steps scale with interval_ms "
        "(campaign OVERRIDES) so every pacing applies ~680-750 updates "
        "— the count long/interval_long converged at — and the accuracy "
        "column compares pacings at equal, convergence-sufficient "
        "update budgets rather than penalizing slow pacings for a "
        "fixed step count."),
    ("cdf50", "cdf50_long"): (
        "accuracies are a 100-step-budget artifact: this grid measures "
        "barrier timing, not convergence. Convergence proof: "
        "long/cdf50_long (full-barrier at n=50, 400 updates, "
        "test_accuracy 1.0)."),
}


def finalize(results_dir: Path) -> None:
    """Regenerate every group's report.md/figures from its
    sweep_results.jsonl with the CURRENT analysis code, rebuild the
    top-level summary from what's on disk, and prune checkpoint
    payloads — idempotent, safe to run after partial/rerun campaigns."""
    summary = {}
    for gdir in sorted(p for p in results_dir.iterdir() if p.is_dir()):
        f = gdir / "sweep_results.jsonl"
        if not f.exists():
            continue
        records = [json.loads(l) for l in f.read_text().splitlines()
                   if l.strip()]
        # a rerun APPENDS to the group's jsonl (the full history stays
        # on disk); reports and the summary reflect each experiment's
        # LATEST record only
        records = list({r.get("name"): r for r in records}.values())
        write_report(records, gdir)
        summary[gdir.name] = [{k: r.get(k) for k in
                               ("name", "test_accuracy", "examples_per_sec",
                                "updates_applied")} for r in records]
        logger.info("finalized %s (%d experiments)", gdir.name, len(records))
    long_names = {r.get("name") for r in summary.get("long", ())}
    notes = {g: note for (g, proof), note in SUMMARY_NOTES.items()
             if g in summary and proof in long_names}
    (results_dir / "campaign_summary.json").write_text(
        json.dumps({"groups": summary, "notes": notes}, indent=2))
    prune_heavy_artifacts(results_dir)


def main(argv=None, root: Path | None = None) -> int:
    root = root or Path.cwd()
    ap = argparse.ArgumentParser(prog="campaign")
    ap.add_argument("--results", default=str(root / "results"))
    ap.add_argument("--configs", default=str(root / "configs"))
    ap.add_argument("--data-cache", default=str(root / "data_cache"))
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--groups", default=",".join(DEFAULT_GROUPS))
    ap.add_argument("--finalize-only", action="store_true")
    args = ap.parse_args(argv)
    groups = args.groups.split(",")
    unknown = [g for g in groups if g not in GROUPS]
    if unknown:
        ap.error(f"unknown groups {unknown}; choose from {sorted(GROUPS)}")
    results_dir = Path(args.results)
    results_dir.mkdir(parents=True, exist_ok=True)
    if args.finalize_only:
        finalize(results_dir)
        return 0

    from ..data.fixtures import (materialize_cifar10_fixture,
                                 materialize_idx_fixture)
    data_dir = Path(args.data_cache)
    for ds in ("mnist", "fashion_mnist"):
        materialize_idx_fixture(data_dir / ds, ds)
    materialize_cifar10_fixture(data_dir / "cifar10")
    logger.info("idx + cifar10 fixtures ready under %s", data_dir)

    t0 = time.time()
    for group in groups:
        run_group(group, GROUPS[group], results_dir, Path(args.configs),
                  data_dir, args.quick)
    # Rebuild the summary from EVERYTHING on disk (not just the groups
    # this invocation ran) — a partial run, e.g. --groups repro_mnist99,
    # must merge into, not clobber, the committed campaign summary.
    finalize(results_dir)
    logger.info("campaign complete in %.0fs", time.time() - t0)
    return 0
