"""Command execution engine beneath the cluster backends.

≙ the reference's ad-hoc ``run_ssh_commands_parallel`` + retry loops
(tools/tf_ec2.py:536-569 fan-out, :237-271 launch-and-wait): every
shell interaction there was a bare ``subprocess``/paramiko call with
hand-rolled sleeps. Here ONE executor owns the subprocess boundary for
the whole launch layer and gives every command:

* a per-command **timeout** (a hung ``gcloud ssh`` must not hang the
  driver),
* bounded **retry with exponential backoff + jitter** on transient
  failures (nonzero rc / timeout — the reference re-ran whole launches
  by hand when a spot request or SSH flaked),
* a structured **JSONL command journal** (argv, rc, duration_ms,
  attempt, stdout/stderr tails) so a run leaves auditable evidence of
  exactly what executed — the artifact `obsv.journal` summarizes,
* a **fault-injection seam** (:class:`FaultPlan`) so the failure
  handling above is *testable* with real subprocesses: fail the first
  n attempts of a verb, delay a command class, kill a worker mid-run
  (the backup-workers regime of arXiv:1604.00981, applied to the
  control plane).

Dry-run records argv without executing — the same audit seam
``launch/pod.py`` has always had, now shared by every backend.
"""

from __future__ import annotations

import dataclasses
import json
import random
import shlex
import subprocess
import time
from pathlib import Path
from typing import Sequence

from ..core.log import JsonlSink, get_logger, text_tail

logger = get_logger("exec")


class ExecError(RuntimeError):
    """A command could not be executed or exhausted its attempt budget."""


class BinaryNotFoundError(ExecError):
    """argv[0] is not on PATH — permanent, never retried. A distinct
    type so callers can tell a missing CLI from a command whose stderr
    merely contains the words "not found" (e.g. a gcloud NOT_FOUND
    resource error)."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with jitter.

    Delay before retry ``k`` (1-based count of failures so far) is
    ``min(max_backoff_s, backoff_s * multiplier**(k-1))`` scaled by a
    uniform jitter in ``[1-jitter_frac, 1+jitter_frac]`` — jitter so N
    workers retrying the same flaked control-plane verb do not
    re-stampede it in lockstep.
    """

    max_attempts: int = 3
    backoff_s: float = 0.25
    multiplier: float = 2.0
    max_backoff_s: float = 8.0
    jitter_frac: float = 0.25
    seed: int | None = None  # deterministic jitter for tests

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")

    def delay_s(self, failures: int, rng: random.Random) -> float:
        base = min(self.max_backoff_s,
                   self.backoff_s * self.multiplier ** (failures - 1))
        if self.jitter_frac <= 0:
            return base
        return base * (1.0 + self.jitter_frac * (2.0 * rng.random() - 1.0))


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Declarative fault injection, wired through the executor and the
    local backend (≙ the failure regime of arXiv:1604.00981 — dead and
    slow workers — applied to the execution layer).

    ``fail_first``         {verb: n}   — synthesize a failure for the
                                         first n attempts of ``verb``
                                         (tests retry/backoff recovery)
    ``delay_ms``           {verb: ms}  — sleep before every execution
                                         of ``verb`` (straggler class)
    ``kill_worker_at_step`` {k: s}     — LocalProcessCluster kills
                                         worker ``k`` once a poll
                                         observes ITS OWN log at step
                                         >= ``s`` (mid-run worker loss;
                                         per-worker logs skew by whole
                                         boot times, so triggers key on
                                         the target worker)
    ``hang_worker_at_step`` {k: s}     — SIGSTOP worker ``k`` once its
                                         log reaches step >= ``s``: the
                                         pid stays alive but the run
                                         stalls (the hung-worker half
                                         of the failure regime —
                                         liveness probes alone cannot
                                         see it)
    ``corrupt_latest_checkpoint_at_step`` {k: s} — once worker ``k``'s
                                         log reaches step >= ``s``,
                                         truncate the latest checkpoint
                                         artifact in its logdir (a torn
                                         write at the worst moment: a
                                         restarted worker must fall
                                         back to the previous loadable
                                         step)
    ``stall_worker_for_ms_at_step`` {k: [s, ms]} — SIGSTOP worker ``k``
                                         at its step >= ``s`` and
                                         SIGCONT it ``ms`` later: a
                                         TRANSIENT straggler that
                                         recovers on its own, unlike
                                         the permanent hang — the
                                         restart-vs-wait race against a
                                         supervisor's stall timeout is
                                         only testable with this one
    ``resize_world_at_step`` [s, m]    — once the SUPERVISED run
                                         observes step >= ``s``, the
                                         supervisor reconfigures the
                                         cluster to ``m`` workers (the
                                         elastic shrink/grow fault —
                                         cluster-level, executed by
                                         ``ClusterSupervisor``, not the
                                         backend's poll hook)
    ``net_faults`` {k: [script, …]}    — transport faults against the
                                         serving replica on worker
                                         ``k``, executed by the chaos
                                         proxy (``launch/netchaos.py``)
                                         interposed on its endpoint:
                                         each script is a dict with a
                                         ``kind`` in {latency,
                                         bandwidth, reset, blackhole,
                                         partition} plus kind-specific
                                         knobs (see ``ChaosProxy``)
    ``disk_faults`` {k: [script, …]}   — storage faults inside worker
                                         ``k``'s own durable-write path
                                         (``train/storage.py``, armed
                                         via the ``DMT_DISK_FAULTS``
                                         env the backend threads into
                                         the worker): each script is a
                                         dict with a ``kind`` in
                                         {enospc_after_bytes, eio,
                                         slow_io_ms,
                                         torn_write_at_byte,
                                         crash_rename} plus
                                         kind-specific knobs (see
                                         ``DiskFaultInjector``);
                                         firings land in the worker's
                                         ``storage_faults.jsonl``

    Every action fires at most once per worker per run.
    """

    fail_first: dict[str, int] = dataclasses.field(default_factory=dict)
    delay_ms: dict[str, float] = dataclasses.field(default_factory=dict)
    kill_worker_at_step: dict[int, int] = dataclasses.field(
        default_factory=dict)
    hang_worker_at_step: dict[int, int] = dataclasses.field(
        default_factory=dict)
    corrupt_latest_checkpoint_at_step: dict[int, int] = dataclasses.field(
        default_factory=dict)
    # {worker: (trigger_step, stall_duration_ms)}
    stall_worker_for_ms_at_step: dict[int, tuple[int, float]] = \
        dataclasses.field(default_factory=dict)
    # (trigger_step, new_world) — None = no resize fault armed
    resize_world_at_step: tuple[int, int] | None = None
    # {worker: [net fault scripts]} — consumed by netchaos.ChaosProxy
    net_faults: dict[int, list[dict]] = dataclasses.field(
        default_factory=dict)
    # {worker: [disk fault scripts]} — armed inside the worker process
    # by train/storage.py (the backend serializes each worker's list
    # into its DMT_DISK_FAULTS environment)
    disk_faults: dict[int, list[dict]] = dataclasses.field(
        default_factory=dict)

    _WORKER_KEYED = ("kill_worker_at_step", "hang_worker_at_step",
                     "corrupt_latest_checkpoint_at_step")

    @classmethod
    def from_file(cls, path: str | Path) -> "FaultPlan":
        return cls.from_json_dict(json.loads(Path(path).read_text()))

    @classmethod
    def from_json_dict(cls, d: dict) -> "FaultPlan":
        d = dict(d)
        unknown = set(d) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise ExecError(f"unknown fault plan keys: {sorted(unknown)}")
        # JSON object keys are strings; worker indices are ints
        for key in cls._WORKER_KEYED:
            if key in d:
                d[key] = {int(k): int(v) for k, v in d[key].items()}
        if "stall_worker_for_ms_at_step" in d:
            d["stall_worker_for_ms_at_step"] = {
                int(k): (int(v[0]), float(v[1]))
                for k, v in d["stall_worker_for_ms_at_step"].items()}
        if d.get("resize_world_at_step") is not None:
            v = d["resize_world_at_step"]
            d["resize_world_at_step"] = (int(v[0]), int(v[1]))
        if "net_faults" in d:
            d["net_faults"] = {int(k): [dict(s) for s in v]
                               for k, v in d["net_faults"].items()}
        if "disk_faults" in d:
            d["disk_faults"] = {int(k): [dict(s) for s in v]
                                for k, v in d["disk_faults"].items()}
        return cls(**d)

    def to_json_dict(self) -> dict:
        """The file-format view (string keys, lists for tuples) — what
        ``from_file`` reads back; empty actions omitted. The chaos
        engine emits shrunk reproducers through this."""
        out: dict = {}
        for f in dataclasses.fields(self):
            val = getattr(self, f.name)
            if not val:
                continue
            if isinstance(val, dict):
                out[f.name] = {str(k): (list(v) if isinstance(v, tuple)
                                        else v) for k, v in val.items()}
            else:
                out[f.name] = list(val) if isinstance(val, tuple) else val
        return out

    def should_fail(self, verb: str, attempt: int) -> bool:
        return attempt <= self.fail_first.get(verb, 0)

    def command_delay_s(self, verb: str) -> float:
        return self.delay_ms.get(verb, 0.0) / 1e3


@dataclasses.dataclass
class ExecResult:
    """Outcome of one :meth:`CommandExecutor.run` call (final attempt)."""

    argv: list[str]
    returncode: int | None       # None ⇔ the attempt timed out
    duration_ms: float
    attempts: int
    stdout: str | None
    stderr: str | None
    timed_out: bool = False
    injected: bool = False       # failure synthesized by the FaultPlan

    @property
    def ok(self) -> bool:
        return self.returncode == 0 and not self.timed_out


class CommandExecutor:
    """Runs argv lists with timeout / retry / journal / fault seams.

    One instance per cluster action sequence; every attempt of every
    command appends one JSONL record to ``journal`` (a path or an open
    :class:`JsonlSink`), so the artifact alone reconstructs what ran.
    """

    def __init__(self, journal: str | Path | JsonlSink | None = None,
                 retry: RetryPolicy | None = None,
                 timeout_s: float | None = None,
                 fault_plan: FaultPlan | None = None,
                 dry_run: bool = False,
                 sleep=time.sleep):
        self.retry = retry or RetryPolicy()
        self.timeout_s = timeout_s
        self.fault_plan = fault_plan or FaultPlan()
        self.dry_run = dry_run
        self.recorded: list[list[str]] = []
        self._sleep = sleep
        self._rng = random.Random(self.retry.seed)
        self._own_journal = not isinstance(journal, JsonlSink)
        self._journal: JsonlSink | None = (
            journal if isinstance(journal, JsonlSink)
            else JsonlSink(journal) if journal is not None else None)

    @property
    def journal_path(self) -> Path | None:
        return self._journal.path if self._journal else None

    def close(self) -> None:
        if self._journal is not None and self._own_journal:
            self._journal.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def journal(self, record: dict) -> None:
        """Append a non-command record (spawn, fault, lifecycle marker)
        to the same journal the commands land in."""
        if self._journal is not None:
            self._journal.write(record)

    _log = journal

    # ------------------------------------------------------------------

    def run(self, argv: Sequence[str], *, verb: str | None = None,
            check: bool = True, capture: bool = True,
            timeout_s: float | None = None,
            max_attempts: int | None = None,
            cwd: str | Path | None = None,
            env: dict[str, str] | None = None) -> ExecResult | None:
        """Execute ``argv``; retry transient failures within the budget.

        ``verb`` names the command class for the journal and the fault
        plan (defaults to ``argv[0]``). Transient = nonzero rc or
        timeout; a missing binary is permanent and raises immediately.
        Returns the final :class:`ExecResult`, or None under dry-run
        (argv recorded + journaled). ``check=True`` raises
        :class:`ExecError` when the final attempt still failed.
        """
        argv = [str(a) for a in argv]
        verb = verb or argv[0]
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        budget = max_attempts or self.retry.max_attempts
        self.recorded.append(argv)
        if self.dry_run:
            logger.info("DRY-RUN: %s", shlex.join(argv))
            self._log({"event": "command", "verb": verb, "argv": argv,
                       "dry_run": True})
            return None

        last: ExecResult | None = None
        for attempt in range(1, budget + 1):
            delay_s = self.fault_plan.command_delay_s(verb)
            if delay_s > 0:
                self._sleep(delay_s)
            t0 = time.perf_counter()
            if self.fault_plan.should_fail(verb, attempt):
                res = ExecResult(argv=argv, returncode=1,
                                 duration_ms=0.0, attempts=attempt,
                                 stdout="", injected=True,
                                 stderr=f"fault-injected failure "
                                        f"(verb={verb!r} attempt={attempt})")
            else:
                try:
                    cp = subprocess.run(argv, text=True,
                                        capture_output=capture,
                                        timeout=timeout_s,
                                        cwd=cwd, env=env)
                    res = ExecResult(
                        argv=argv, returncode=cp.returncode,
                        duration_ms=(time.perf_counter() - t0) * 1e3,
                        attempts=attempt, stdout=cp.stdout,
                        stderr=cp.stderr)
                except subprocess.TimeoutExpired as e:
                    res = ExecResult(
                        argv=argv, returncode=None,
                        duration_ms=(time.perf_counter() - t0) * 1e3,
                        attempts=attempt, timed_out=True,
                        stdout=e.stdout if isinstance(e.stdout, str) else None,
                        stderr=e.stderr if isinstance(e.stderr, str) else None)
                except FileNotFoundError as e:
                    self._log({"event": "command", "verb": verb,
                               "argv": argv, "rc": None, "attempt": attempt,
                               "error": "binary not found"})
                    raise BinaryNotFoundError(
                        f"{argv[0]!r} not found on PATH") from e
            will_retry = (not res.ok) and attempt < budget
            self._log({"event": "command", "verb": verb, "argv": argv,
                       "rc": res.returncode,
                       "duration_ms": round(res.duration_ms, 3),
                       "attempt": attempt, "check": check,
                       "timed_out": res.timed_out,
                       "injected": res.injected,
                       "injected_delay_ms": delay_s * 1e3 or None,
                       "stdout_tail": text_tail(res.stdout),
                       "stderr_tail": text_tail(res.stderr),
                       "will_retry": will_retry})
            if res.ok:
                return res
            last = res
            if will_retry:
                backoff = self.retry.delay_s(attempt, self._rng)
                logger.warning(
                    "command failed (verb=%s rc=%s timed_out=%s) — "
                    "attempt %d/%d, retrying in %.3fs", verb,
                    res.returncode, res.timed_out, attempt, budget, backoff)
                self._sleep(backoff)
        assert last is not None
        if check:
            why = "timed out" if last.timed_out else f"rc={last.returncode}"
            raise ExecError(
                f"command failed after {last.attempts} attempt(s) "
                f"({why}): {shlex.join(argv)}"
                + (f"\nstderr tail: {text_tail(last.stderr, 500)}"
                   if last.stderr else ""))
        return last
