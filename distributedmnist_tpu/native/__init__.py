"""Native (C++) runtime components.

The reference framework's native substrate is the TF 1.x C++ runtime
it imports (SURVEY §2.3); this package is ours. The library is built
on demand from :file:`dml_native.cc` with the system ``g++`` (no
pybind11 in this image — the ABI is plain C, consumed via ctypes) and
cached in ``_build/``; rebuilt automatically when the source is newer
than the cached object.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

_SRC = Path(__file__).resolve().parent / "dml_native.cc"
_BUILD_DIR = Path(__file__).resolve().parent / "_build"
_LIB_PATH = _BUILD_DIR / "libdml_native.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


class NativeBuildError(RuntimeError):
    """g++ compile of the native library failed."""


def _build() -> None:
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    tmp = _BUILD_DIR / f".libdml_native.{os.getpid()}.tmp.so"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", str(_SRC),
           "-o", str(tmp), "-lz", "-pthread"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise NativeBuildError(f"failed to run g++: {e}") from e
    if proc.returncode != 0:
        tmp.unlink(missing_ok=True)
        raise NativeBuildError(
            f"g++ failed ({proc.returncode}):\n{proc.stderr[-2000:]}")
    os.replace(tmp, _LIB_PATH)  # atomic: concurrent builders both win


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.dml_free.argtypes = [c.c_void_p]
    lib.dml_free.restype = None

    lib.dml_read_idx.argtypes = [
        c.c_char_p, c.POINTER(c.POINTER(c.c_uint8)),
        c.POINTER(c.c_int32), c.POINTER(c.c_int64)]
    lib.dml_read_idx.restype = c.c_int

    lib.dml_loader_create.argtypes = [
        c.c_void_p, c.c_void_p, c.c_int64, c.c_int64, c.c_int64, c.c_int64,
        c.c_uint64, c.c_int32]
    lib.dml_loader_create.restype = c.c_void_p

    lib.dml_loader_next.argtypes = [
        c.c_void_p, c.c_void_p, c.c_void_p,
        c.POINTER(c.c_int64), c.POINTER(c.c_int64)]
    lib.dml_loader_next.restype = c.c_int

    lib.dml_loader_restore.argtypes = [c.c_void_p, c.c_int64, c.c_int64]
    lib.dml_loader_restore.restype = None

    lib.dml_loader_destroy.argtypes = [c.c_void_p]
    lib.dml_loader_destroy.restype = None
    return lib


def load_library() -> ctypes.CDLL:
    """Build (if stale/missing) and load the native library.

    Raises NativeBuildError when the toolchain is unavailable; callers
    degrade to the pure-python path (data.pipeline.make_train_iterator).
    """
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not _LIB_PATH.exists()
                or _LIB_PATH.stat().st_mtime < _SRC.stat().st_mtime):
            _build()
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
        except OSError as e:
            raise NativeBuildError(f"could not load {_LIB_PATH}: {e}") from e
        _lib = _bind(lib)
        return _lib
