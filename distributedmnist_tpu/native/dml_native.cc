// Native data-pipeline runtime for distributedmnist_tpu.
//
// The reference delegates its data/queue machinery to the TF 1.x C++
// runtime: FIFOQueue kernels feed the token barrier and input pipeline
// (reference: sync_replicas_optimizer_modified.py:199-206; the Python
// DataSet.next_batch at src/mnist_data.py:102-130 is the only
// first-party data code). This library is the framework's own native
// substrate for that capability: idx(.gz) decoding, a seeded
// per-epoch Fisher-Yates shuffle, and a background producer thread
// feeding a bounded batch queue (the FIFOQueue equivalent) so host
// batch assembly overlaps device execution.
//
// Exposed as a plain C ABI consumed via ctypes
// (distributedmnist_tpu/data/native_loader.py). ctypes releases the
// GIL for foreign calls, so the blocking dml_loader_next overlaps
// Python-side work.

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <new>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

// splitmix64: tiny, well-mixed, deterministic across platforms.
uint64_t splitmix64(uint64_t* s) {
  uint64_t z = (*s += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

int read_exact(gzFile f, void* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    unsigned chunk = static_cast<unsigned>(
        std::min<size_t>(n - got, 1u << 30));
    int r = gzread(f, static_cast<char*>(buf) + got, chunk);
    if (r <= 0) return -1;
    got += static_cast<size_t>(r);
  }
  return 0;
}

struct Batch {
  std::vector<uint8_t> images, labels;
  int64_t epoch = 0, pos_after = 0;
};

// The prefetching loader. Rows are opaque byte strips, so any
// (dtype, shape) pair works: float32 image tensors and int32 token
// sequences alike. Producer-side state (epoch/pos/order) is owned by
// the worker thread; restore() joins the thread before touching it.
struct Loader {
  const uint8_t* images = nullptr;  // borrowed; Python keeps them alive
  const uint8_t* labels = nullptr;
  int64_t n = 0, img_row = 0, lab_row = 0, batch = 0;
  uint64_t seed = 0;
  size_t depth = 2;

  std::vector<int64_t> order;
  int64_t epoch = 0, pos = 0;

  std::mutex mu;
  std::condition_variable cv_space, cv_data;
  std::deque<Batch> q;
  bool stopping = false;
  std::thread worker;

  // Deterministic permutation for (seed, epoch) — the reference
  // reshuffles per epoch with a *time* seed (src/mnist_data.py:55,
  // 80-84,113-125); here the stream is replayable.
  void shuffle_for(int64_t ep) {
    order.resize(static_cast<size_t>(n));
    for (int64_t i = 0; i < n; ++i) order[static_cast<size_t>(i)] = i;
    uint64_t s = seed ^ (0x9e3779b97f4a7c15ull * static_cast<uint64_t>(ep + 1));
    splitmix64(&s);  // decorrelate nearby (seed, epoch) pairs
    for (int64_t i = n - 1; i > 0; --i) {
      uint64_t j = splitmix64(&s) % static_cast<uint64_t>(i + 1);
      std::swap(order[static_cast<size_t>(i)], order[static_cast<size_t>(j)]);
    }
  }

  void produce() {
    for (;;) {
      if (pos + batch > n) {  // drop ragged tail, reshuffle
        epoch += 1;
        shuffle_for(epoch);
        pos = 0;
      }
      Batch b;
      b.images.resize(static_cast<size_t>(img_row * batch));
      b.labels.resize(static_cast<size_t>(lab_row * batch));
      for (int64_t i = 0; i < batch; ++i) {
        int64_t src = order[static_cast<size_t>(pos + i)];
        std::memcpy(b.images.data() + i * img_row, images + src * img_row,
                    static_cast<size_t>(img_row));
        std::memcpy(b.labels.data() + i * lab_row, labels + src * lab_row,
                    static_cast<size_t>(lab_row));
      }
      pos += batch;
      b.epoch = epoch;
      b.pos_after = pos;
      std::unique_lock<std::mutex> lk(mu);
      cv_space.wait(lk, [&] { return stopping || q.size() < depth; });
      if (stopping) return;
      q.push_back(std::move(b));
      cv_data.notify_one();
    }
  }

  void start() {
    stopping = false;
    worker = std::thread([this] { produce(); });
  }

  void stop() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv_space.notify_all();
    cv_data.notify_all();
    if (worker.joinable()) worker.join();
    q.clear();
  }
};

}  // namespace

extern "C" {

void dml_free(void* p) { std::free(p); }

// idx(.gz) reader (the MNIST/Fashion-MNIST container; zlib's gzopen
// transparently handles both compressed and raw files). ubyte payloads
// only (type code 0x08 — what the format's datasets use). Returns 0 on
// success; *out_data is malloc'd and must be released via dml_free.
int dml_read_idx(const char* path, uint8_t** out_data, int32_t* out_ndim,
                 int64_t* out_dims /* capacity >= 4 */) {
  gzFile f = gzopen(path, "rb");
  if (!f) return -1;
  uint8_t magic[4];
  if (read_exact(f, magic, 4) != 0 || magic[0] != 0 || magic[1] != 0 ||
      magic[2] != 0x08) {
    gzclose(f);
    return -2;
  }
  int nd = magic[3];
  if (nd < 1 || nd > 4) {
    gzclose(f);
    return -3;
  }
  int64_t total = 1;
  for (int i = 0; i < nd; ++i) {
    uint8_t b[4];
    if (read_exact(f, b, 4) != 0) {
      gzclose(f);
      return -4;
    }
    int64_t d = (static_cast<int64_t>(b[0]) << 24) |
                (static_cast<int64_t>(b[1]) << 16) |
                (static_cast<int64_t>(b[2]) << 8) | b[3];
    if (d <= 0) {
      gzclose(f);
      return -5;
    }
    out_dims[i] = d;
    total *= d;
  }
  uint8_t* data = static_cast<uint8_t*>(std::malloc(static_cast<size_t>(total)));
  if (!data) {
    gzclose(f);
    return -6;
  }
  if (read_exact(f, data, static_cast<size_t>(total)) != 0) {
    std::free(data);
    gzclose(f);
    return -7;
  }
  gzclose(f);
  *out_data = data;
  *out_ndim = nd;
  return 0;
}

void* dml_loader_create(const void* images, const void* labels,
                        int64_t num_examples, int64_t image_row_bytes,
                        int64_t label_row_bytes, int64_t batch_size,
                        uint64_t seed, int32_t depth) {
  if (!images || !labels || num_examples <= 0 || batch_size <= 0 ||
      batch_size > num_examples || image_row_bytes <= 0 ||
      label_row_bytes <= 0 || depth < 1)
    return nullptr;
  Loader* L = new (std::nothrow) Loader();
  if (!L) return nullptr;
  L->images = static_cast<const uint8_t*>(images);
  L->labels = static_cast<const uint8_t*>(labels);
  L->n = num_examples;
  L->img_row = image_row_bytes;
  L->lab_row = label_row_bytes;
  L->batch = batch_size;
  L->seed = seed;
  L->depth = static_cast<size_t>(depth);
  L->shuffle_for(0);
  L->start();
  return L;
}

// Blocking pop of the next prefetched batch into caller buffers
// (batch_size * row_bytes each). out_epoch/out_pos report the stream
// position *after* this batch — the checkpointable cursor.
int dml_loader_next(void* loader, void* out_images, void* out_labels,
                    int64_t* out_epoch, int64_t* out_pos) {
  Loader* L = static_cast<Loader*>(loader);
  if (!L || !out_images || !out_labels) return -2;
  Batch b;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_data.wait(lk, [&] { return L->stopping || !L->q.empty(); });
    if (L->stopping) return -1;
    b = std::move(L->q.front());
    L->q.pop_front();
  }
  L->cv_space.notify_one();
  std::memcpy(out_images, b.images.data(), b.images.size());
  std::memcpy(out_labels, b.labels.data(), b.labels.size());
  if (out_epoch) *out_epoch = b.epoch;
  if (out_pos) *out_pos = b.pos_after;
  return 0;
}

// Reposition the stream to (epoch, pos) — exact resume of the
// deterministic shuffle stream (the reference cannot resume its data
// stream at all; its shuffle is time-seeded).
void dml_loader_restore(void* loader, int64_t epoch, int64_t pos) {
  Loader* L = static_cast<Loader*>(loader);
  if (!L) return;
  L->stop();
  L->epoch = epoch < 0 ? 0 : epoch;
  L->shuffle_for(L->epoch);
  L->pos = pos < 0 ? 0 : (pos > L->n ? L->n : pos);
  L->start();
}

void dml_loader_destroy(void* loader) {
  Loader* L = static_cast<Loader*>(loader);
  if (!L) return;
  L->stop();
  delete L;
}

}  // extern "C"
