"""CLI: ``python -m distributedmnist_tpu.evalsvc --train_dir ... [overrides]``

≙ the evaluator binary (src/mnist_eval.py) the EC2 launcher starts on
its evaluator node (tools/tf_ec2.py:130-146).
"""

import argparse

from ..core.config import EvalConfig
from .evaluator import Evaluator


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="continuous checkpoint evaluator")
    p.add_argument("--train_dir", required=True)
    p.add_argument("--eval_dir", default="/tmp/dmt_eval")
    p.add_argument("--eval_interval_secs", type=float, default=1.0)
    p.add_argument("--eval_batch_size", type=int, default=0)
    p.add_argument("--run_once", action="store_true")
    p.add_argument("--max_evals", type=int, default=0)
    p.add_argument("--single_device", action="store_true",
                   help="evaluate on ONE ambient device regardless of the "
                        "training mesh (DP checkpoints only) — the lean "
                        "co-located mode: no collectives to starve while "
                        "sharing a host with the trainer")
    args = p.parse_args(argv)

    ecfg = EvalConfig(eval_interval_secs=args.eval_interval_secs,
                      eval_dir=args.eval_dir,
                      eval_batch_size=args.eval_batch_size,
                      run_once=args.run_once, max_evals=args.max_evals)
    Evaluator(args.train_dir, ecfg, single_device=args.single_device).run()


if __name__ == "__main__":
    main()
