from .evaluator import Evaluator

__all__ = ["Evaluator"]
