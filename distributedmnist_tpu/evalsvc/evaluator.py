"""Continuous checkpoint evaluator.

≙ the reference's dedicated evaluator process (src/mnist_eval.py,
src/nn_eval.py): poll the trainer's checkpoint directory, restore the
newest checkpoint, skip if the step hasn't advanced
(src/nn_eval.py:84-88), measure full-test-set accuracy+loss, emit the
regex-parseable line (src/nn_eval.py:102-103) plus structured JSONL.

Differences from the reference:
* The model/config is read from the checkpoint's own saved config — no
  risk of evaluator/trainer graph skew (the reference rebuilds the
  graph from whatever flags the evaluator was launched with).
* Eval batches are static-shaped and weight-padded instead of building
  a graph at batch = full-test-set size (src/nn_eval.py:121-122).
* The checkpoint pointer read is atomic (no torn reads off NFS).
"""

from __future__ import annotations

import time
from pathlib import Path

import jax

from ..core.config import (EvalConfig, ExperimentConfig, MeshConfig,
                           effective_model_config)
from ..core.log import JsonlSink, eval_line, get_logger
from ..core.mesh import Topology, make_topology
from ..data.datasets import Datasets, load_datasets
from ..models.registry import get_model
from ..parallel.api import (build_eval_step, init_train_state,
                            state_partition_specs)
from ..train import checkpoint as ckpt
from ..train.evaluation import run_full_eval

logger = get_logger("eval")


class Evaluator:
    """Polls ``train_dir`` and evaluates each new checkpoint once."""

    def __init__(self, train_dir: str | Path, eval_cfg: EvalConfig | None = None,
                 cfg: ExperimentConfig | None = None,
                 topo: Topology | None = None,
                 datasets: Datasets | None = None,
                 single_device: bool = False):
        self.train_dir = Path(train_dir)
        self.eval_cfg = eval_cfg or EvalConfig()
        if cfg is None:
            cfg = self._config_from_checkpoint()
        self.cfg = cfg
        if topo is not None:
            self.topo = topo
        elif single_device:
            # Lean mesh for co-located evaluation: ONE ambient device,
            # regardless of the training mesh (incl. simulate_devices
            # configs — no forced N-device backend, no collectives, no
            # rendezvous to starve while sharing a host with the
            # trainer; the campaign's live oracle runs this way).
            # DP checkpoints restore shape-identically (replicated);
            # TP/SP/EP checkpoints restore too — their global arrays
            # equal the unsharded init layout, and the per-host sharded
            # format reassembles full arrays on read
            # (train/checkpoint.py). Only pipeline layouts genuinely
            # differ (layer-stacked/chunk-interleaved blocks vs the
            # flat list) — refuse those; the default full-mesh
            # evaluator handles them.
            m = cfg.mesh
            if m.pipeline_parallelism > 1:
                raise ValueError(
                    "single_device evaluation cannot restore "
                    "pipeline-stacked parameter layouts; run the "
                    "evaluator without --single_device (it builds the "
                    "training mesh)")
            if (cfg.model.num_experts > 0 and cfg.model.moe_num_groups == 0
                    and (m.expert_parallelism > 1 or m.seq_parallelism > 1)):
                raise ValueError(
                    "single_device evaluation of an expert-/seq-sharded "
                    "MoE run needs an explicit model.moe_num_groups: with "
                    "the mesh-derived auto grouping the 1-device routing "
                    "(groups/capacity) differs from the training mesh and "
                    "metrics would silently diverge; set moe_num_groups "
                    "or run the evaluator without --single_device")
            self.topo = make_topology(MeshConfig(num_replicas=1),
                                      devices=jax.devices()[:1])
        else:
            self.topo = make_topology(cfg.mesh)
        self.model = get_model(effective_model_config(cfg))
        self.datasets = datasets if datasets is not None else load_datasets(
            cfg.data, cfg.model.image_size, cfg.model.num_channels,
            cfg.model.num_classes, cfg.model.seq_len, cfg.model.vocab_size)
        self.eval_fn = build_eval_step(self.model, cfg, self.topo)
        self.template = init_train_state(self.model, cfg, self.topo)
        # the shared hot-follow loop (train/checkpoint.py): atomic
        # pointer read, step-advanced check, skip-and-retry on a torn /
        # corrupt / GC-raced artifact — the same follower the serving
        # tier (servesvc) runs on
        self.follower = ckpt.CheckpointFollower(self.train_dir)
        self._sink: JsonlSink | None = None
        self._tb = None

    @property
    def last_step_evaluated(self) -> int:
        return self.follower.last_step

    def _config_from_checkpoint(self) -> ExperimentConfig:
        """Wait for the first checkpoint, then adopt its saved config
        (the shared checkpoint-layer bootstrap the serving tier uses
        too — reads only the JSON ``extra`` payload, no state
        template, so any model/optimizer shape works)."""
        return ckpt.wait_for_run_config(self.train_dir)

    # ------------------------------------------------------------------

    def evaluate_checkpoint(self, step: int | None = None) -> dict | None:
        """Evaluate one checkpoint (≙ do_eval, src/nn_eval.py:49-115).
        Skips (returns None) when the artifact is unreadable — the
        standalone-call convenience; the service loop gets the same
        policy from the shared follower."""
        try:
            return self._read_and_eval(step)
        except (OSError, ValueError, KeyError) as e:
            # The trainer's checkpoint GC can unlink this step between
            # our latest_checkpoint_step poll and the read (or a shared
            # fs serves a torn file). Skip; the next poll sees a newer one.
            logger.warning("checkpoint step=%s unreadable (%s); skipping",
                           step, e)
            return None

    def _read_and_eval(self, step: int | None) -> dict | None:
        """Restore + evaluate, RAISING on an unreadable artifact —
        the ``read`` the follower wraps with skip-and-retry
        (CheckpointCorruptError subclasses ValueError, so a failed
        digest flows into the same skip path as a torn msgpack)."""
        restored = ckpt.restore_checkpoint(self.train_dir, self.template,
                                           step)
        if restored is None:
            return None
        state, _, at_step = restored
        specs = state_partition_specs(self.model, self.cfg, self.topo)
        params = self.topo.device_put_state(state.params, specs.params)
        out = run_full_eval(
            self.eval_fn, params, self.topo,
            self.datasets.test, self.eval_cfg.eval_batch_size,
            # honor the run's staging knobs — the same off-switch the
            # Trainer's eval respects
            prefetch_depth=self.cfg.data.effective_device_prefetch_depth())
        result = {
            "event": "eval", "step": at_step, "time": time.time(),
            "num_examples": out["num_examples"],
            "precision_at_1": out["accuracy"],
            "loss": out["loss"],
            "seconds": out["seconds"],
        }
        # the reference's exact parseable line (src/nn_eval.py:102-103)
        print(eval_line(result["num_examples"], result["precision_at_1"],
                        result["loss"], result["seconds"]), flush=True)
        if self._sink:
            self._sink.write(result)
        if self._tb is not None:
            # ≙ the evaluator's TB scalars (src/nn_eval.py:107-110)
            self._tb.add_scalars({"Validation Accuracy": out["accuracy"],
                                  "Validation Loss": out["loss"]},
                                 step=at_step)
            self._tb.flush()
        return result

    def poll_once(self) -> dict | None:
        """One follow tick: evaluate the newest checkpoint iff its step
        advanced past the last one evaluated; a torn/corrupt/unlinked
        artifact is skipped (retried next tick), never fatal."""
        if self.follower.newest_step() is None:
            logger.info("no checkpoint yet in %s", self.train_dir)
            return None
        return self.follower.poll(lambda step: self._read_and_eval(step))

    def run(self) -> list[dict]:
        """Poll loop (≙ evaluate(), src/nn_eval.py:117-140) — the
        shared follower (train/checkpoint.py CheckpointFollower) owns
        the pointer-read / step-advanced / skip-and-retry discipline."""
        ecfg = self.eval_cfg
        eval_dir = Path(ecfg.eval_dir)
        eval_dir.mkdir(parents=True, exist_ok=True)
        self._sink = JsonlSink(eval_dir / "eval_log.jsonl")
        from ..obsv.tb import SummaryWriter
        self._tb = SummaryWriter(eval_dir / "tb")
        results: list[dict] = []
        try:
            while True:
                out = self.poll_once()
                if out is not None:
                    results.append(out)
                if ecfg.run_once and results:
                    break
                if ecfg.max_evals and len(results) >= ecfg.max_evals:
                    break
                time.sleep(ecfg.eval_interval_secs)
        finally:
            self._sink.close()
            self._sink = None
        return results
