"""Continuous checkpoint evaluator.

≙ the reference's dedicated evaluator process (src/mnist_eval.py,
src/nn_eval.py): poll the trainer's checkpoint directory, restore the
newest checkpoint, skip if the step hasn't advanced
(src/nn_eval.py:84-88), measure full-test-set accuracy+loss, emit the
regex-parseable line (src/nn_eval.py:102-103) plus structured JSONL.

Differences from the reference:
* The model/config is read from the checkpoint's own saved config — no
  risk of evaluator/trainer graph skew (the reference rebuilds the
  graph from whatever flags the evaluator was launched with).
* Eval batches are static-shaped and weight-padded instead of building
  a graph at batch = full-test-set size (src/nn_eval.py:121-122).
* The checkpoint pointer read is atomic (no torn reads off NFS).
"""

from __future__ import annotations

import time
from pathlib import Path

import jax

from ..core.config import EvalConfig, ExperimentConfig, MeshConfig
from ..core.log import JsonlSink, eval_line, get_logger
from ..core.mesh import Topology, make_topology
from ..data.datasets import Datasets, load_datasets
from ..models.registry import get_model
from ..parallel.api import (build_eval_step, init_train_state,
                            state_partition_specs)
from ..train import checkpoint as ckpt
from ..train.evaluation import run_full_eval

logger = get_logger("eval")


class Evaluator:
    """Polls ``train_dir`` and evaluates each new checkpoint once."""

    def __init__(self, train_dir: str | Path, eval_cfg: EvalConfig | None = None,
                 cfg: ExperimentConfig | None = None,
                 topo: Topology | None = None,
                 datasets: Datasets | None = None,
                 single_device: bool = False):
        self.train_dir = Path(train_dir)
        self.eval_cfg = eval_cfg or EvalConfig()
        if cfg is None:
            cfg = self._config_from_checkpoint()
        self.cfg = cfg
        if topo is not None:
            self.topo = topo
        elif single_device:
            # Lean mesh for co-located evaluation: ONE ambient device,
            # regardless of the training mesh (incl. simulate_devices
            # configs — no forced N-device backend, no collectives, no
            # rendezvous to starve while sharing a host with the
            # trainer; the campaign's live oracle runs this way).
            # DP checkpoints restore shape-identically (replicated);
            # TP/SP/EP checkpoints restore too — their global arrays
            # equal the unsharded init layout, and the per-host sharded
            # format reassembles full arrays on read
            # (train/checkpoint.py). Only pipeline layouts genuinely
            # differ (layer-stacked/chunk-interleaved blocks vs the
            # flat list) — refuse those; the default full-mesh
            # evaluator handles them.
            m = cfg.mesh
            if m.pipeline_parallelism > 1:
                raise ValueError(
                    "single_device evaluation cannot restore "
                    "pipeline-stacked parameter layouts; run the "
                    "evaluator without --single_device (it builds the "
                    "training mesh)")
            if (cfg.model.num_experts > 0 and cfg.model.moe_num_groups == 0
                    and (m.expert_parallelism > 1 or m.seq_parallelism > 1)):
                raise ValueError(
                    "single_device evaluation of an expert-/seq-sharded "
                    "MoE run needs an explicit model.moe_num_groups: with "
                    "the mesh-derived auto grouping the 1-device routing "
                    "(groups/capacity) differs from the training mesh and "
                    "metrics would silently diverge; set moe_num_groups "
                    "or run the evaluator without --single_device")
            self.topo = make_topology(MeshConfig(num_replicas=1),
                                      devices=jax.devices()[:1])
        else:
            self.topo = make_topology(cfg.mesh)
        self.model = get_model(cfg.model)
        self.datasets = datasets if datasets is not None else load_datasets(
            cfg.data, cfg.model.image_size, cfg.model.num_channels,
            cfg.model.num_classes, cfg.model.seq_len, cfg.model.vocab_size)
        self.eval_fn = build_eval_step(self.model, cfg, self.topo)
        self.template = init_train_state(self.model, cfg, self.topo)
        self.last_step_evaluated = -1
        self._sink: JsonlSink | None = None
        self._tb = None

    def _config_from_checkpoint(self) -> ExperimentConfig:
        """Wait for the first checkpoint, then adopt its saved config.

        Reads only the checkpoint's JSON ``extra`` payload — no state
        template needed, so this works for any model/optimizer shape
        (a resnet20/momentum/interval run, not just the default CNN)."""
        deadline = time.time() + 600.0
        while time.time() < deadline:
            try:
                out = ckpt.read_checkpoint_extra(self.train_dir)
            except (OSError, ValueError, KeyError) as e:
                # mid-replace read on a shared fs / torn file — this is
                # a long-running service, retry on the next poll
                logger.warning("checkpoint read failed (%s); retrying", e)
                out = None
            if out is not None:
                extra, _ = out
                if "config" in extra:
                    return ExperimentConfig.from_dict(extra["config"])
                logger.warning("checkpoint has no saved config; using defaults")
                return ExperimentConfig()
            time.sleep(1.0)
        raise TimeoutError(f"no checkpoint appeared in {self.train_dir} within 600s")

    # ------------------------------------------------------------------

    def evaluate_checkpoint(self, step: int | None = None) -> dict | None:
        """Evaluate one checkpoint (≙ do_eval, src/nn_eval.py:49-115)."""
        try:
            restored = ckpt.restore_checkpoint(self.train_dir, self.template,
                                               step)
        except (OSError, ValueError, KeyError) as e:
            # The trainer's checkpoint GC can unlink this step between
            # our latest_checkpoint_step poll and the read (or a shared
            # fs serves a torn file). Skip; the next poll sees a newer one.
            logger.warning("checkpoint step=%s unreadable (%s); skipping",
                           step, e)
            return None
        if restored is None:
            return None
        state, _, at_step = restored
        specs = state_partition_specs(self.model, self.cfg, self.topo)
        params = self.topo.device_put_state(state.params, specs.params)
        out = run_full_eval(
            self.eval_fn, params, self.topo,
            self.datasets.test, self.eval_cfg.eval_batch_size,
            # honor the run's staging knobs — the same off-switch the
            # Trainer's eval respects
            prefetch_depth=self.cfg.data.effective_device_prefetch_depth())
        result = {
            "event": "eval", "step": at_step, "time": time.time(),
            "num_examples": out["num_examples"],
            "precision_at_1": out["accuracy"],
            "loss": out["loss"],
            "seconds": out["seconds"],
        }
        # the reference's exact parseable line (src/nn_eval.py:102-103)
        print(eval_line(result["num_examples"], result["precision_at_1"],
                        result["loss"], result["seconds"]), flush=True)
        if self._sink:
            self._sink.write(result)
        if self._tb is not None:
            # ≙ the evaluator's TB scalars (src/nn_eval.py:107-110)
            self._tb.add_scalars({"Validation Accuracy": out["accuracy"],
                                  "Validation Loss": out["loss"]},
                                 step=at_step)
            self._tb.flush()
        return result

    def run(self) -> list[dict]:
        """Poll loop (≙ evaluate(), src/nn_eval.py:117-140)."""
        ecfg = self.eval_cfg
        eval_dir = Path(ecfg.eval_dir)
        eval_dir.mkdir(parents=True, exist_ok=True)
        self._sink = JsonlSink(eval_dir / "eval_log.jsonl")
        from ..obsv.tb import SummaryWriter
        self._tb = SummaryWriter(eval_dir / "tb")
        results: list[dict] = []
        try:
            while True:
                step = ckpt.latest_checkpoint_step(self.train_dir)
                if step is not None and step != self.last_step_evaluated:
                    out = self.evaluate_checkpoint(step)
                    if out is not None:
                        self.last_step_evaluated = step
                        results.append(out)
                elif step is None:
                    logger.info("no checkpoint yet in %s", self.train_dir)
                if ecfg.run_once and results:
                    break
                if ecfg.max_evals and len(results) >= ecfg.max_evals:
                    break
                time.sleep(ecfg.eval_interval_secs)
        finally:
            self._sink.close()
            self._sink = None
        return results
