"""Contribution-mask policies — the reference's aggregation disciplines
re-expressed for lockstep SPMD.

Each policy answers one question per replica per step: *does this
replica's gradient enter this step's masked-mean psum?* This single
abstraction covers what the reference spreads across
``SyncReplicasOptimizer`` quorum accumulation
(src/distributed_train.py:184-188), the ``TimeoutReplicasOptimizer``'s
two take-grad modes (sync_replicas_optimizer_modified.py:363-378), the
disabled RPC straggler-kill (src/timeout_manager.py:38-46), and the
chief's wall-clock interval timer
(sync_replicas_optimizer_modified.py:208-215).

Quorum semantics in lockstep SPMD (SURVEY §7 "hard parts"): "first k
gradients win" is a race in the reference; replicas here arrive
together. We reproduce the *statistical* behavior the reference's
experiments sweep: each replica gets a per-step time — measured on real
hardware and/or drawn from a synthetic straggler model (the reference
induced stragglers by buying slow EC2 instance types,
cfg/time_cdf_cfgs/*) — and the k fastest contribute. Selection is
exactly k via lexicographic (time, replica_id) ranking.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core import prng
from ..core.config import SyncConfig


def sample_step_time_ms(cfg: SyncConfig, root_key: jax.Array,
                        step: jax.Array, replica: jax.Array,
                        measured_ms: jax.Array) -> jax.Array:
    """Model this replica's step time.

    ``measured_ms`` is a host-injected base (real measured step time; 0
    when unused). The synthetic straggler profile adds on top:

    * "lognormal": heavy-tailed per-step compute time — matches the
      shape of the per-worker CDFs the reference's Experiment C
      measures (tools/benchmark.py:226-263).
    * "spike": occasional large stalls (preemption-like).
    * "none": a deterministic tiny per-replica jitter so that time
      ranking still breaks ties uniquely.
    """
    key = prng.replica_key(root_key, "straggler", step, replica)
    base = jnp.asarray(measured_ms, jnp.float32)
    if cfg.straggler_profile == "lognormal":
        z = jax.random.normal(key)
        t = cfg.straggler_mean_ms * jnp.exp(cfg.straggler_sigma * z
                                            - 0.5 * cfg.straggler_sigma**2)
        return base + t
    if cfg.straggler_profile == "spike":
        spike = jax.random.bernoulli(key, cfg.straggler_spike_prob)
        t = cfg.straggler_mean_ms * jnp.where(spike, cfg.straggler_spike_scale, 1.0)
        return base + t
    if cfg.straggler_profile == "none":
        # sub-microsecond jitter: invisible in stats, unique for ranking
        return base + jax.random.uniform(key, (), jnp.float32, 0.0, 1e-3)
    raise ValueError(f"unknown straggler_profile {cfg.straggler_profile!r}")


def rank_by_time(time_ms: jax.Array, axis_name: str) -> jax.Array:
    """This replica's rank (0 = fastest) under lexicographic
    (time, replica_id) order — deterministic and an exact permutation."""
    n = lax.axis_size(axis_name)
    times = lax.all_gather(time_ms, axis_name)  # [n]
    ids = jnp.arange(n)
    me = lax.axis_index(axis_name)
    my_t = time_ms
    earlier = (times < my_t) | ((times == my_t) & (ids < me))
    return jnp.sum(earlier.astype(jnp.int32))


def quorum_flag(time_ms: jax.Array, k: int | jax.Array, axis_name: str) -> jax.Array:
    """k-of-n backup-worker mask: 1 for the k fastest replicas
    (≙ replicas_to_aggregate=k; the n−k slowest are the "backups" whose
    work is discarded, arXiv:1604.00981 semantics).

    ``k`` may be a traced scalar (the adaptive discipline controller
    swaps it at runtime without recompiling); integer-valued floats are
    rounded, never truncated."""
    k_i = jnp.round(jnp.asarray(k, jnp.float32)).astype(jnp.int32)
    return (rank_by_time(time_ms, axis_name) < k_i).astype(jnp.float32)


def timeout_flag(time_ms: jax.Array, timeout_ms: float | jax.Array) -> jax.Array:
    """Deadline straggler drop: replicas slower than the deadline are
    masked out instead of killed (≙ src/timeout_manager.py:38-46).
    ``timeout_ms`` may be a traced scalar (runtime-adaptive deadline)."""
    return (time_ms <= jnp.asarray(timeout_ms, jnp.float32)).astype(jnp.float32)


def resolve_aggregate_k(cfg: SyncConfig, num_replicas: int) -> int:
    """-1 → all replicas (reference default, src/distributed_train.py:118-121)."""
    k = cfg.num_replicas_to_aggregate
    if k == -1:
        return num_replicas
    if not (1 <= k <= num_replicas):
        raise ValueError(f"num_replicas_to_aggregate={k} outside [1, {num_replicas}]")
    return k
