"""The SPMD train step — replaces reference layers L3 (modified sync
optimizer) and L4 (Twisted RPC mesh) with one compiled program.

Where the reference pushes gradients into PS-hosted accumulators,
blocks on per-worker token queues, and lets a chief thread apply the
update (sync_replicas_optimizer_modified.py:237-429), here every
replica computes its gradient, a masked-mean ``lax.psum`` over the ICI
mesh aggregates exactly the contributions the active policy allows,
and every replica applies the identical update to its replicated
parameters. Barriers, tokens, staleness checks and the chief role all
disappear into collective semantics.

The step is built once per (model, config, topology) and jitted with
donated state; everything inside is static-shaped and control flow is
`lax.cond`, so XLA compiles a single fused program per mode.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core import mesh as mesh_lib
from ..core import prng
from ..core.config import ExperimentConfig
from ..core.mesh import Topology
from ..models.registry import Model
from ..ops.drop_connect import drop_connect_grads
from ..ops.masked_psum import masked_mean_psum
from . import policies

# LR schedule: updates_applied -> lr (see train.lr_schedule; kept as a
# plain callable type here to avoid a parallel<->train import cycle).
Schedule = Callable[[jax.Array], jax.Array]


class TrainState(struct.PyTreeNode):
    """Replicated training state (a pure pytree).

    ``updates_applied`` is the reference's global_step — it counts
    *applied updates* (PS applies, src/distributed_train.py:140), while
    ``step`` counts loop iterations; the two differ in interval mode.
    """

    params: Any
    momentum: Any            # momentum buffers or None
    step: jax.Array          # int32, loop iterations
    updates_applied: jax.Array  # int32, ≙ global_step
    root_key: jax.Array
    # interval mode only (None otherwise):
    window_acc: Any          # accumulated sum of per-step masked means
    window_rounds: jax.Array  # float32 rounds accumulated in this window
    wall_ms: jax.Array       # modeled wall clock
    next_apply_ms: jax.Array


def state_partition_specs(model: Model, cfg: ExperimentConfig,
                          topo: Topology) -> TrainState:
    """A TrainState-shaped pytree of PartitionSpecs: P() (replicated)
    everywhere, except param-shaped subtrees which take the model's
    tensor-parallel specs when the mesh's model axis is >1."""
    from jax.sharding import PartitionSpec as P_

    n_model = topo.mesh.shape[topo.model_axis]
    n_stage = topo.mesh.shape[topo.stage_axis]
    n_expert = topo.mesh.shape[topo.expert_axis]
    if n_model > 1 and getattr(model, "tp_param_specs", None) is None:
        raise ValueError(f"mesh has model_parallelism={n_model} but model "
                         f"{model.name!r} has no tensor-parallel parameter "
                         "specs")
    if n_expert > 1 and (getattr(model, "tp_param_specs", None) is None
                         or not getattr(model, "has_aux", False)):
        raise ValueError(f"mesh has expert_parallelism={n_expert} but model "
                         f"{model.name!r} has no experts to shard")
    if n_stage > 1 and getattr(model, "pp_param_specs", None) is None:
        raise ValueError(f"mesh has pipeline_parallelism={n_stage} but model "
                         f"{model.name!r} has no pipeline parameter specs")
    if n_stage > 1:
        pspec: Any = model.pp_param_specs(
            topo.stage_axis, topo.model_axis if n_model > 1 else None,
            topo.expert_axis if n_expert > 1 else None)
    elif n_model > 1 or n_expert > 1:
        pspec = model.tp_param_specs(
            topo.model_axis if n_model > 1 else None,
            topo.expert_axis if n_expert > 1 else None)
    else:
        pspec = P_()
    has_momentum = cfg.optim.momentum > 0.0
    interval = cfg.sync.mode == "interval"
    return TrainState(
        params=pspec,
        momentum=pspec if has_momentum else None,
        step=P_(), updates_applied=P_(), root_key=P_(),
        window_acc=pspec if interval else None,
        window_rounds=P_(), wall_ms=P_(), next_apply_ms=P_())


def init_train_state(model: Model, cfg: ExperimentConfig,
                     topo: Topology | None = None) -> TrainState:
    params = model.init(jax.random.PRNGKey(cfg.model.init_seed))
    if (topo is not None and topo.mesh.shape[topo.stage_axis] > 1):
        if getattr(model, "pp_transform", None) is None:
            raise ValueError(f"mesh has pipeline stages but model "
                             f"{model.name!r} has no pp_transform")
        if cfg.mesh.pipeline_schedule == "1f1b":
            if getattr(model, "pp_transform_chunked", None) is None:
                raise ValueError(
                    f"pipeline_schedule='1f1b' but model {model.name!r} "
                    "has no pp_transform_chunked")
            # chunk-interleaved layer order: device d's contiguous
            # stage shard holds global chunks {d, S+d, ...}
            params = model.pp_transform_chunked(
                params, topo.mesh.shape[topo.stage_axis],
                cfg.mesh.pipeline_chunks)
        else:
            params = model.pp_transform(params)  # layer-stacked layout
    momentum = (jax.tree.map(jnp.zeros_like, params)
                if cfg.optim.momentum > 0.0 else None)
    interval = cfg.sync.mode == "interval"
    return TrainState(
        params=params,
        momentum=momentum,
        step=jnp.zeros((), jnp.int32),
        updates_applied=jnp.zeros((), jnp.int32),
        root_key=prng.root_key(cfg.train.seed),
        window_acc=jax.tree.map(jnp.zeros_like, params) if interval else None,
        window_rounds=jnp.zeros((), jnp.float32),
        wall_ms=jnp.zeros((), jnp.float32),
        next_apply_ms=jnp.asarray(cfg.sync.interval_ms, jnp.float32),
    )


def _sgd(params: Any, grads: Any, momentum_bufs: Any, lr: jax.Array,
         momentum: float) -> tuple[Any, Any]:
    """Plain SGD (≙ tf.train.GradientDescentOptimizer,
    src/distributed_train.py:176), with optional heavyball momentum."""
    if momentum_bufs is None:
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, None
    new_bufs = jax.tree.map(lambda b, g: momentum * b + g, momentum_bufs, grads)
    new_params = jax.tree.map(lambda p, b: p - lr * b, params, new_bufs)
    return new_params, new_bufs


def _gather_replicated(x: jax.Array, axis: str, n: int) -> jax.Array:
    """All-gather a per-replica scalar into a REPLICATED [n] vector.

    Expressed as a one-hot psum instead of ``lax.all_gather`` because
    psum's output is statically known to be replicated over ``axis`` —
    so it can leave shard_map under an out_spec of P() and every host
    of a multi-host run holds the full vector (an all_gather result
    stays marked device-varying and would need a sharded out_spec,
    which non-addressable processes cannot materialize)."""
    me = lax.axis_index(axis)
    onehot = (jnp.arange(n) == me).astype(x.dtype)
    return lax.psum(onehot * x, axis)


def build_train_step(model: Model, cfg: ExperimentConfig, topo: Topology,
                     schedule: Schedule) -> Callable[[TrainState, dict], tuple[TrainState, dict]]:
    """Compile the per-step SPMD training function.

    Returns ``step_fn(state, batch, measured_ms=None) -> (state, metrics)``
    where ``batch = {"image": [B, ...], "label": [B]}`` is globally
    batched and sharded over the replica axis, and state/metrics are
    replicated. ``measured_ms`` is an optional per-replica [n] vector of
    real measured step times (ms), sharded over the replica axis: each
    host feeds the entries for its own replicas (Topology.
    device_put_measured), so quorum/timeout/interval policies select on
    genuine per-replica speed — ≙ the reference's measured per-worker
    CDF semantics (src/timeout_manager.py:48-61) without the RPC mesh.
    Defaults to zeros (pure synthetic-profile timing).
    """
    axis = topo.replica_axis
    n = topo.num_replicas
    sync = cfg.sync
    mode = sync.mode
    if mode not in ("sync", "quorum", "timeout", "interval", "cdf"):
        raise ValueError(f"unknown sync mode {mode!r}")
    k = policies.resolve_aggregate_k(sync, n)
    momentum = cfg.optim.momentum

    # Sequence parallelism: when the mesh spends devices on the seq
    # axis, the model must provide a sequence-sharded apply (the
    # transformer does, via ring/all-to-all attention). Each shard then
    # computes a PARTIAL loss/gradient over its token slice; psum over
    # the seq axis reassembles the exact full-sequence gradient before
    # the replica-axis aggregation disciplines see it.
    #
    # Tensor parallelism: when the mesh's model axis is >1, params are
    # placed per the model's TP partition specs; each rank holds its
    # head/MLP column shard, activations stay replicated over the axis
    # (psums inside apply), and each rank's param gradients are its own
    # shard's — no model-axis reduction of gradients is needed.
    seq_ax = topo.seq_axis
    n_seq = topo.mesh.shape[seq_ax]
    model_ax = topo.model_axis
    n_model = topo.mesh.shape[model_ax]
    # Pipeline parallelism: layers sharded over the stage axis, batch
    # microbatched through the activation pipeline (ops/pipeline.py).
    # Stage-sharded param grads stay local; replicated leaves (embed,
    # norms) get their stage-psum from the AD transpose of replication.
    stage_ax = topo.stage_axis
    n_stage = topo.mesh.shape[stage_ax]
    # Expert parallelism: experts sharded over the expert axis; composes
    # with TP (model axis splits heads + every expert's hidden dim).
    expert_ax = topo.expert_axis
    n_expert = topo.mesh.shape[expert_ax]
    if ((n_seq > 1 or n_model > 1 or n_expert > 1) and n_stage == 1
            and getattr(model, "sharded_apply_factory", None) is None):
        raise ValueError(
            f"mesh has seq_parallelism={n_seq} / model_parallelism="
            f"{n_model} / expert_parallelism={n_expert} but model "
            f"{model.name!r} supports none of them "
            "(no sharded_apply_factory)")
    pp_schedule = cfg.mesh.pipeline_schedule
    if pp_schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline_schedule {pp_schedule!r}")
    pp_1f1b_grads_fn = None
    if n_stage > 1:
        if getattr(model, "pp_apply_factory", None) is None:
            raise ValueError(f"mesh has pipeline_parallelism={n_stage} but "
                             f"model {model.name!r} has no pipeline apply")
        if pp_schedule == "1f1b":
            # fused interleaved schedule (ops/pipeline.py): explicit
            # forward/backward chunk-works in one scan — built below
            # instead of value_and_grad. TP/SP/EP collectives inside
            # the chunk bodies execute inside the engine's
            # stage-varying switch branches; that is safe because they
            # reduce over NON-stage axes whose participant groups share
            # a stage coordinate and hence a branch (ops/pipeline.py).
            if getattr(model, "pp_1f1b_grads_factory", None) is None:
                raise ValueError(f"model {model.name!r} has no 1f1b "
                                 "pipeline support")
            pp_1f1b_grads_fn = model.pp_1f1b_grads_factory(
                stage_ax, cfg.mesh.pipeline_microbatches,
                cfg.mesh.pipeline_chunks,
                model_ax if n_model > 1 else None,
                seq_ax if n_seq > 1 else None,
                expert_ax if n_expert > 1 else None)
            pp_apply = None
        else:
            # PP outermost; TP (model axis) inside each stage; SP (seq
            # axis) through the stage blocks' sharded attention; EP
            # (expert axis) through the blocks' grouped MoE dispatch —
            # every device runs the same tick schedule so attention and
            # expert collectives stay lockstep inside the pipeline scan
            pp_apply = model.pp_apply_factory(
                stage_ax, cfg.mesh.pipeline_microbatches,
                model_ax if n_model > 1 else None,
                seq_ax if n_seq > 1 else None,
                expert_ax if n_expert > 1 else None)
    else:
        pp_apply = None
    sharded_apply = (model.sharded_apply_factory(
        seq_ax if n_seq > 1 else None, model_ax if n_model > 1 else None,
        expert_ax if n_expert > 1 else None)
        if ((n_seq > 1 or n_model > 1 or n_expert > 1)
            and pp_apply is None and pp_1f1b_grads_fn is None)
        else None)
    # The SP/PP loss paths do not thread a dropout key; refuse loudly
    # instead of silently training a dropout model without dropout.
    if ((sharded_apply is not None or pp_apply is not None
            or pp_1f1b_grads_fn is not None)
            and getattr(model, "uses_dropout", False)):
        raise ValueError(
            f"model {model.name!r} uses dropout, but the sharded "
            "(SP/TP/PP) loss paths do not thread a dropout key; set "
            "model.dropout_rate=0 or run it data-parallel only")
    # raw per-shard grads are needed w.r.t. the axes the masks/explicit
    # psums manage; the model axis stays as-is (sharded params are
    # already device-varying there)
    grad_axes = (axis, seq_ax) if n_seq > 1 else (axis,)
    state_specs = state_partition_specs(model, cfg, topo)

    has_aux = getattr(model, "has_aux", False)
    aux_w = getattr(model, "aux_weight", 0.0)

    def local_loss(params, batch, dropout_key):
        if has_aux:
            logits, aux = model.apply(params, batch["image"], train=True,
                                      dropout_key=dropout_key,
                                      return_aux=True)
            return model.loss(logits, batch["label"]) + aux_w * aux, logits
        logits = model.apply(params, batch["image"], train=True,
                             dropout_key=dropout_key)
        return model.loss(logits, batch["label"]), logits

    def local_loss_pp(params, batch, dropout_key):
        del dropout_key
        if has_aux:  # MoE: per-group aux, tick-accumulated (apply_pp)
            logits, aux = pp_apply(params, batch["image"], return_aux=True)
            return model.loss(logits, batch["label"]) + aux_w * aux, logits
        logits = pp_apply(params, batch["image"])  # stage-replicated
        return model.loss(logits, batch["label"]), logits

    def make_sp_loss(apply_fn, with_aux):
        """Per-(replica, seq-shard) partial next-token loss over any
        seq-sharded apply (the DP×SP×TP path, or the pipeline apply for
        PP×SP).

        Targets are inputs shifted left by one GLOBAL position, so the
        target of a shard's last token lives on the next shard — one
        ppermute fetches each neighbor's first column. The global last
        position has no target (weight 0), matching the dense
        ``transformer.loss_fn`` exactly: partial sums are normalized by
        the global valid-token count so psum(partials) == dense loss.
        """
        def sp_loss(params, batch, dropout_key):
            del dropout_key
            tokens = batch["image"]
            labels = batch["label"]
            b, s_loc = tokens.shape
            me_s = lax.axis_index(seq_ax)
            positions = me_s * s_loc + jnp.arange(s_loc)
            if with_aux:  # MoE: EP-only, SP×EP, or PP×SP×EP
                logits, aux = apply_fn(params, tokens, positions,
                                       return_aux=True)
            else:
                logits = apply_fn(params, tokens, positions)  # [b, s_loc, V]
                aux = 0.0

            # shard j receives shard (j+1)'s first target column
            perm = [((j + 1) % n_seq, j) for j in range(n_seq)]
            nxt = lax.ppermute(labels[:, :1], seq_ax, perm)
            tgt = jnp.concatenate([labels[:, 1:], nxt], axis=1).astype(jnp.int32)

            from ..models.transformer import sp_partial_token_loss
            s_global = s_loc * n_seq
            # total = this replica's global token count; the shared
            # kernel keeps this path and the 1F1B seed head identical
            loss_part, acc_part = sp_partial_token_loss(
                logits, tgt, positions, s_global, b * (s_global - 1))
            # aux is already the full-token value on every seq shard
            # (moe_ffn pmeans its stats over the stats_axes), so the
            # caller's psum over the seq axis would count it n_seq
            # times — pre-divide so the psum reassembles exactly one.
            return loss_part + aux_w * aux / n_seq, acc_part
        return sp_loss

    local_loss_sp = (make_sp_loss(sharded_apply, has_aux)
                     if sharded_apply is not None else
                     make_sp_loss(pp_apply, has_aux)
                     if (pp_apply is not None and n_seq > 1) else None)

    def shard_fn(state: TrainState, batch: dict,
                 measured_ms: jax.Array) -> tuple[TrainState, dict]:
        me = lax.axis_index(axis)
        step = state.step
        my_measured_ms = measured_ms[0]  # this replica's [1]-shard

        # --- local forward+backward (one pass: the reference's second
        # forward per step, src/distributed_train.py:332-335, is a
        # documented quirk we do not replicate) -----------------------
        #
        # Params are replicated over the mesh; differentiating w.r.t. a
        # *replicated* value inside shard_map makes AD insert the
        # cross-axis psum itself (transpose of the broadcast). We need
        # the raw per-shard gradient — masks must apply BEFORE the
        # replica aggregation, and the seq-axis psum must be explicit —
        # so cast params to varying over every grad axis first.
        dkey = prng.replica_key(state.root_key, "dropout", step, me)
        local_params = jax.tree.map(
            lambda x: lax.pcast(x, grad_axes, to="varying"), state.params)
        if pp_1f1b_grads_fn is not None:
            # fused 1F1B: the engine computes loss, accuracy and grads
            # in one interleaved scan — no outer value_and_grad. Under
            # SP the engine returns per-seq-shard partials; psum
            # reassembles the exact dense values (same as the SP
            # branch below).
            loss, train_acc, grads = pp_1f1b_grads_fn(
                local_params, batch["image"], batch["label"])
            if n_seq > 1:
                loss = lax.psum(loss, seq_ax)
                train_acc = lax.psum(train_acc, seq_ax)
                grads = jax.tree.map(lambda g: lax.psum(g, seq_ax), grads)
        elif local_loss_sp is not None:  # DP×SP×TP, or PP×SP
            (loss_p, acc_p), grads = jax.value_and_grad(
                local_loss_sp, has_aux=True)(local_params, batch, dkey)
            # reassemble the full-sequence gradient / metrics
            loss = lax.psum(loss_p, seq_ax)
            train_acc = lax.psum(acc_p, seq_ax)
            grads = jax.tree.map(lambda g: lax.psum(g, seq_ax), grads)
        elif pp_apply is not None:
            (loss, logits), grads = jax.value_and_grad(
                local_loss_pp, has_aux=True)(local_params, batch, dkey)
            train_acc = model.accuracy(logits, batch["label"])
        else:
            (loss, logits), grads = jax.value_and_grad(
                local_loss, has_aux=True)(local_params, batch, dkey)
            train_acc = model.accuracy(logits, batch["label"])

        # --- per-worker drop-connect before aggregation
        # (src/distributed_train.py:194-196) --------------------------
        if sync.drop_connect:
            dckey = prng.replica_key(state.root_key, "drop_connect", step, me)
            grads = drop_connect_grads(grads, dckey, sync.drop_connect_probability)

        # --- step-time model & contribution mask ---------------------
        t_ms = policies.sample_step_time_ms(sync, state.root_key, step, me,
                                            my_measured_ms)
        if mode in ("sync", "cdf"):
            flag = jnp.ones((), jnp.float32)
        elif mode == "quorum":
            flag = policies.quorum_flag(t_ms, k, axis)
        elif mode == "timeout":
            flag = policies.timeout_flag(t_ms, sync.timeout_ms)
        else:  # interval: stale if slower than a whole window
            flag = policies.timeout_flag(t_ms, sync.interval_ms)

        mean_grads, num_contrib = masked_mean_psum(grads, flag, axis)

        # --- apply discipline ----------------------------------------
        if mode == "interval":
            new_state, applied = _interval_apply(state, mean_grads, t_ms)
        else:
            lr = schedule(state.updates_applied)
            applied = (num_contrib > 0).astype(jnp.int32)
            # If every replica was masked out (possible under timeout),
            # the mean is zero and the update must be a true no-op.
            if state.momentum is None:
                # plain SGD: lr·0 is exact, so scaling the scalar lr by
                # the applied flag IS the no-op — no full-size
                # per-parameter select pass (a measured throughput tax
                # on small steps, bench_mode_overhead)
                new_params, new_bufs = _sgd(
                    state.params, mean_grads, None,
                    lr * applied.astype(jnp.float32), momentum)
            else:
                new_params, new_bufs = _sgd(state.params, mean_grads,
                                            state.momentum, lr, momentum)
                # momentum buffers decay even on zero gradients, so a
                # true no-op needs the select
                new_params = jax.tree.map(
                    lambda new, old: jnp.where(applied > 0, new, old),
                    new_params, state.params)
                new_bufs = jax.tree.map(
                    lambda new, old: jnp.where(applied > 0, new, old),
                    new_bufs, state.momentum)
            new_state = state.replace(
                params=new_params, momentum=new_bufs,
                updates_applied=state.updates_applied + applied)

        new_state = new_state.replace(step=step + 1)

        # --- metrics: everything comes out REPLICATED (scalars via
        # pmean/psum, per-replica series via all_gather) so every host
        # holds the full [n] timing vector — a multi-host process can
        # materialize its own copy without touching non-addressable
        # shards (≙ the CDF timing gossip, src/timeout_manager.py:48-61,
        # with no RPC mesh at all) ------------------------------------
        metrics = {
            "loss": lax.pmean(loss, axis),
            "train_acc": lax.pmean(train_acc, axis),
            "lr": schedule(state.updates_applied),
            "num_contributors": num_contrib,
            "updates_applied": new_state.updates_applied,
            "step_times_ms": _gather_replicated(t_ms, axis, n),  # [n]
            "flags": _gather_replicated(flag, axis, n),          # [n]
            "applied": applied,
        }
        return new_state, metrics

    def _interval_apply(state: TrainState, mean_grads: Any,
                        t_ms: jax.Array) -> tuple[TrainState, jax.Array]:
        """Wall-clock-windowed aggregation (≙ the chief's recurring
        Timer running take_grad(1)-average-of-arrived,
        sync_replicas_optimizer_modified.py:208-215,371-373,392-393).

        A wall-clock-async update is not expressible inside one SPMD
        program (SURVEY §7), so the window is re-expressed over the
        lockstep loop: each step's masked mean joins a window
        accumulator; the modeled wall clock advances by the mean
        replica pace; when it crosses the window boundary the
        accumulated average is applied and the window resets.
        """
        acc = jax.tree.map(lambda a, g: a + g, state.window_acc, mean_grads)
        rounds = state.window_rounds + 1.0
        wall = state.wall_ms + lax.pmean(t_ms, axis)
        fire = wall >= state.next_apply_ms

        lr = schedule(state.updates_applied)
        window_mean = jax.tree.map(lambda a: a / rounds, acc)
        applied_params, applied_bufs = _sgd(state.params, window_mean,
                                            state.momentum, lr, momentum)

        def pick(new, old):
            return jax.tree.map(lambda a, b: jnp.where(fire, a, b), new, old)

        new_params = pick(applied_params, state.params)
        new_bufs = (None if state.momentum is None
                    else pick(applied_bufs, state.momentum))
        zeros = jax.tree.map(jnp.zeros_like, acc)
        new_acc = pick(zeros, acc)
        new_rounds = jnp.where(fire, 0.0, rounds)
        # Reschedule relative to *now*, as the reference timer does by
        # re-arming after each run (skipped windows are not replayed).
        next_apply = jnp.where(fire, wall + sync.interval_ms, state.next_apply_ms)
        applied = fire.astype(jnp.int32)
        return state.replace(
            params=new_params, momentum=new_bufs, window_acc=new_acc,
            window_rounds=new_rounds, wall_ms=wall, next_apply_ms=next_apply,
            updates_applied=state.updates_applied + applied), applied

    mesh = topo.mesh
    metrics_specs = {
        "loss": P(), "train_acc": P(), "lr": P(), "num_contributors": P(),
        "updates_applied": P(), "step_times_ms": P(), "flags": P(),
        "applied": P(),
    }
    batch_spec = P(axis, seq_ax) if n_seq > 1 else P(axis)
    sharded = mesh_lib.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(state_specs, batch_spec, P(axis)),
        out_specs=(state_specs, metrics_specs))
    jitted = jax.jit(sharded, donate_argnums=0)

    zeros_ms: list[jax.Array] = []  # lazily built + cached default

    def step_fn(state: TrainState, batch: dict,
                measured_ms: jax.Array | None = None):
        if measured_ms is None:
            if not zeros_ms:
                zeros_ms.append(topo.zeros_measured())
            measured_ms = zeros_ms[0]
        return jitted(state, batch, measured_ms)

    return step_fn


def build_eval_step(model: Model, cfg: ExperimentConfig, topo: Topology):
    """Sharded inference step: weighted accuracy/loss so padded
    examples (batch not divisible by replica count) don't bias metrics.

    ``batch = {"image", "label", "weight"}``; returns summed
    (correct, weighted_loss, weight) — caller divides.
    """
    axis = topo.replica_axis
    model_ax = topo.model_axis
    n_model = topo.mesh.shape[model_ax]
    n_stage = topo.mesh.shape[topo.stage_axis]
    n_expert = topo.mesh.shape[topo.expert_axis]
    if n_stage > 1:
        # pipeline-parallel params: stacked layout. Eval pipelines at
        # the largest microbatch count that divides the per-replica
        # eval rows (capped by the training cadence) — M=1 would run
        # the stages fully serialized, an S× eval slowdown measured in
        # the tens of minutes on deep CPU-mesh evals.
        if getattr(model, "pp_apply_factory", None) is None:
            raise ValueError(f"mesh has pipeline_parallelism={n_stage} but "
                             f"model {model.name!r} has no pipeline apply")
        tp_ax = model_ax if n_model > 1 else None
        ep_ax = topo.expert_axis if n_expert > 1 else None
        pspec: Any = model.pp_param_specs(topo.stage_axis, tp_ax, ep_ax)
        if (cfg.mesh.pipeline_schedule == "1f1b"
                and getattr(model, "pp_1f1b_apply_factory", None) is None):
            # mirror the train-path guard: fail with a clear error at
            # build time instead of an opaque trace-time NoneType call
            raise ValueError(f"model {model.name!r} has no 1f1b "
                             "pipeline support")
        cap = max(1, cfg.mesh.pipeline_microbatches)

        def run(params, images):
            # per-replica rows are static at trace time (eval batches
            # are padded to a fixed shape); pipeline at the largest
            # microbatch count ≤ the training cadence that divides
            # them. MoE included: token groups nest inside sequence
            # rows (ops/moe.py), so routing capacity and metrics are
            # identical for every microbatch split — the round-4 M=1
            # force is gone (tests pin M-invariance).
            b = images.shape[0]
            m_eval = max(m for m in range(1, cap + 1) if b % m == 0)
            if cfg.mesh.pipeline_schedule == "1f1b":
                apply_fn = model.pp_1f1b_apply_factory(
                    topo.stage_axis, m_eval, cfg.mesh.pipeline_chunks,
                    tp_ax, ep_ax)
            else:
                apply_fn = model.pp_apply_factory(topo.stage_axis, m_eval,
                                                  tp_ax, None, ep_ax)
            return apply_fn(params, images)
    elif n_model > 1 or n_expert > 1:
        # tensor-/expert-parallel params: sharded apply (full sequence
        # per device — eval batches are not seq-sharded), sharded in_spec
        if (getattr(model, "tp_param_specs", None) is None
                or getattr(model, "sharded_apply_factory", None) is None):
            raise ValueError(f"mesh has model_parallelism={n_model} / "
                             f"expert_parallelism={n_expert} but model "
                             f"{model.name!r} is not tensor-/expert-parallel "
                             "capable")
        tp_ax = model_ax if n_model > 1 else None
        ep_ax = topo.expert_axis if n_expert > 1 else None
        pspec: Any = model.tp_param_specs(tp_ax, ep_ax)
        tp_apply = model.sharded_apply_factory(None, tp_ax, ep_ax)

        def run(params, images):
            return tp_apply(params, images, None)
    else:
        pspec = P()

        def run(params, images):
            return model.apply(params, images, train=False)

    def shard_fn(params, batch):
        logits = run(params, batch["image"])
        correct, loss_sum, weight = model.eval_metrics(
            logits, batch["label"], batch["weight"])
        return (lax.psum(correct, axis), lax.psum(loss_sum, axis),
                lax.psum(weight, axis))

    sharded = mesh_lib.shard_map(
        shard_fn, mesh=topo.mesh,
        in_specs=(pspec, P(axis)),
        out_specs=(P(), P(), P()))
    return jax.jit(sharded)
