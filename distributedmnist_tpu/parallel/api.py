"""The SPMD train step — replaces reference layers L3 (modified sync
optimizer) and L4 (Twisted RPC mesh) with one compiled program.

Where the reference pushes gradients into PS-hosted accumulators,
blocks on per-worker token queues, and lets a chief thread apply the
update (sync_replicas_optimizer_modified.py:237-429), here every
replica computes its gradient, a masked-mean ``lax.psum`` over the ICI
mesh aggregates exactly the contributions the active policy allows,
and every replica applies the identical update to its replicated
parameters. Barriers, tokens, staleness checks and the chief role all
disappear into collective semantics.

The step is built once per (model, config, topology) and jitted with
donated state; everything inside is static-shaped and control flow is
`lax.cond`, so XLA compiles a single fused program per mode.
"""

from __future__ import annotations

import dataclasses
import functools
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core import mesh as mesh_lib
from ..core import prng
from ..core.config import ExperimentConfig
from ..core.log import get_logger
from ..core.mesh import Topology
from ..models.registry import Model, replicated_partition_rules
from ..ops.drop_connect import drop_connect_grads
from ..ops.masked_psum import contribution_scale, masked_mean_psum
from . import policies
from .partition_rules import (RuleAxes, Zero1Plan, comm_bucket_assignment,
                              match_partition_rules, make_zero1_plan,
                              zero1_init_state, zero1_pack,
                              zero1_state_specs, zero1_unpack)

logger = get_logger("parallel")

# LR schedule: updates_applied -> lr (see train.lr_schedule; kept as a
# plain callable type here to avoid a parallel<->train import cycle).
Schedule = Callable[[jax.Array], jax.Array]

# Runtime discipline vector layout: the aggregation-discipline
# parameters ride into the compiled step as ONE replicated [3] float32
# input (spec P()), so the adaptive controller (train/discipline.py)
# changes discipline by swapping a 12-byte buffer — never by
# recompiling. Indexed symbolically everywhere; order is part of the
# AOT signature, so reordering would invalidate precompiled caches.
DISC_K = 0            # quorum size (integer-valued float; rounded in use)
DISC_TIMEOUT_MS = 1   # timeout-mode deadline
DISC_INTERVAL_MS = 2  # interval-mode window / staleness bound


def make_discipline_vector(k: float, timeout_ms: float,
                           interval_ms: float) -> jax.Array:
    """Pack runtime discipline params as the traced [3] step input."""
    return jnp.asarray([float(k), float(timeout_ms), float(interval_ms)],
                       jnp.float32)


class TrainState(struct.PyTreeNode):
    """Replicated training state (a pure pytree).

    ``updates_applied`` is the reference's global_step — it counts
    *applied updates* (PS applies, src/distributed_train.py:140), while
    ``step`` counts loop iterations; the two differ in interval mode.

    ``momentum`` holds the optimizer's moment slots in the registry's
    layout (train/optim.py): None (stateless sgd), a params-shaped
    tree (momentum/LARS — byte-identical to the historical layout), or
    ``{"m": tree, "v": tree}`` (LAMB). Under
    ``precision.master_weights``, ``params`` ARE the float32 masters;
    the train step derives the low-precision forward view per step, so
    no second param tree ever enters the state or its checkpoints.
    """

    params: Any
    momentum: Any            # optimizer moment slots or None
    step: jax.Array          # int32, loop iterations
    updates_applied: jax.Array  # int32, ≙ global_step
    root_key: jax.Array
    # interval mode only (None otherwise):
    window_acc: Any          # accumulated sum of per-step masked means
    window_rounds: jax.Array  # float32 rounds accumulated in this window
    wall_ms: jax.Array       # modeled wall clock
    next_apply_ms: jax.Array


def _build_params(model: Model, cfg: ExperimentConfig,
                  topo: Topology | None) -> Any:
    """Init params in the layout the mesh trains (pp-transformed when
    the stage axis is active) — shared by :func:`init_train_state` and
    the abstract-shape path the spec engine maps rules over."""
    params = model.init(jax.random.PRNGKey(cfg.model.init_seed))
    if (topo is not None and topo.mesh.shape[topo.stage_axis] > 1):
        if getattr(model, "pp_transform", None) is None:
            raise ValueError(f"mesh has pipeline stages but model "
                             f"{model.name!r} has no pp_transform")
        if cfg.mesh.pipeline_schedule == "1f1b":
            if getattr(model, "pp_transform_chunked", None) is None:
                raise ValueError(
                    f"pipeline_schedule='1f1b' but model {model.name!r} "
                    "has no pp_transform_chunked")
            # chunk-interleaved layer order: device d's contiguous
            # stage shard holds global chunks {d, S+d, ...}
            params = model.pp_transform_chunked(
                params, topo.mesh.shape[topo.stage_axis],
                cfg.mesh.pipeline_chunks)
        else:
            params = model.pp_transform(params)  # layer-stacked layout
    return params


@functools.lru_cache(maxsize=128)
def _abstract_train_params_cached(model: Model, cfg: ExperimentConfig,
                                  topo: Topology | None) -> Any:
    return jax.eval_shape(lambda: _build_params(model, cfg, topo))


def abstract_train_params(model: Model, cfg: ExperimentConfig,
                          topo: Topology | None) -> Any:
    """Shape/dtype skeleton of the trained param tree (no FLOPs, no
    device buffers) — what the rule engine needs to name leaves.

    Memoized on the (model, cfg, topo) triple: one Trainer build calls
    through here several times (state specs, train step, eval step,
    ZeRO-1 plan) with the same frozen objects, and re-tracing init each
    time is pure waste (~0.1 s per trace). Falls back to a direct trace
    for unhashable inputs."""
    try:
        return _abstract_train_params_cached(model, cfg, topo)
    except TypeError:
        return jax.eval_shape(lambda: _build_params(model, cfg, topo))


def params_partition_specs(model: Model, cfg: ExperimentConfig,
                           topo: Topology, params: Any = None) -> Any:
    """The per-leaf PartitionSpec tree for the trained params, derived
    by mapping the model's declarative rule table
    (``models/registry.py``) over the real param tree with the active
    mesh axes bound — ``parallel/partition_rules.py``. Replaces the
    hand-built spec trees ``state_partition_specs`` used to assemble
    per layout; the models' spec builders remain as the parity oracle
    (tests/test_partition_rules.py)."""
    n_model = topo.mesh.shape[topo.model_axis]
    n_stage = topo.mesh.shape[topo.stage_axis]
    n_expert = topo.mesh.shape[topo.expert_axis]
    if n_model > 1 and getattr(model, "tp_param_specs", None) is None:
        raise ValueError(f"mesh has model_parallelism={n_model} but model "
                         f"{model.name!r} has no tensor-parallel parameter "
                         "specs")
    if n_expert > 1 and (getattr(model, "tp_param_specs", None) is None
                         or not getattr(model, "has_aux", False)):
        raise ValueError(f"mesh has expert_parallelism={n_expert} but model "
                         f"{model.name!r} has no experts to shard")
    if n_stage > 1 and getattr(model, "pp_param_specs", None) is None:
        raise ValueError(f"mesh has pipeline_parallelism={n_stage} but model "
                         f"{model.name!r} has no pipeline parameter specs")
    axes = RuleAxes(
        model=topo.model_axis if n_model > 1 else None,
        expert=topo.expert_axis if n_expert > 1 else None,
        stage=topo.stage_axis if n_stage > 1 else None)
    if model.partition_rules is None and (axes.model or axes.expert
                                          or axes.stage):
        # the replicated fallback table is only safe when nothing needs
        # sharding — silently replicating a TP/PP/EP model's weights
        # would double-count its model-axis psums, the exact failure
        # the rule engine's unmatched-leaf error exists to prevent
        raise ValueError(
            f"model {model.name!r} declares sharded-parallelism support "
            "but no partition_rules table (models/registry.py) — cannot "
            f"derive placements for active axes {axes}")
    rules = (model.partition_rules or replicated_partition_rules)(axes)
    if params is None:
        params = abstract_train_params(model, cfg, topo)
    return match_partition_rules(rules, params)


def zero1_plan_for(model: Model, cfg: ExperimentConfig, topo: Topology,
                   params: Any = None) -> Zero1Plan | None:
    """The ZeRO-1 shard plan when ``parallel.shard_weight_update`` is
    both enabled and applicable, else None. Inapplicable: a replica
    axis of 1 (nothing is redundant), or interval mode (the windowed
    accumulator averages the FULL mean across steps; sharding it too is
    possible but not worth the extra state surface — documented
    fallback, see README Performance)."""
    par = cfg.parallel
    par.validate()  # typed ConfigError at build time, not mid-step
    if not par.shard_weight_update:
        return None
    if topo.num_replicas <= 1 or cfg.sync.mode == "interval":
        return None
    if params is None:
        params = abstract_train_params(model, cfg, topo)
    pspecs = params_partition_specs(model, cfg, topo, params=params)
    return make_zero1_plan(params, pspecs, topo.replica_axis,
                           topo.num_replicas,
                           min_leaf_size=par.shard_min_leaf_size,
                           comm_buckets=par.comm_buckets,
                           params_sharded=par.resident_sharded)


def resolved_param_dtype(cfg: ExperimentConfig):
    """The dtype ``TrainState.params`` is STORED in: float32 masters
    when ``precision.master_weights`` (the low-precision view is
    derived per step), else ``precision.param_dtype`` itself. Typed
    validation, matching the optim section's convention: a bad dtype
    string is a ConfigError naming the key, not a numpy TypeError from
    deep inside state init."""
    from ..core.config import ConfigError
    try:
        dt = jnp.dtype(cfg.precision.param_dtype)
    except TypeError as e:
        raise ConfigError(
            f"precision.param_dtype={cfg.precision.param_dtype!r} is not "
            f"a recognized dtype ({e}); use e.g. 'float32' or 'bfloat16'"
        ) from e
    if not jnp.issubdtype(dt, jnp.floating):
        raise ConfigError(
            f"precision.param_dtype={cfg.precision.param_dtype!r} is not a "
            "floating dtype")
    return jnp.float32 if cfg.precision.master_weights else dt


def state_partition_specs(model: Model, cfg: ExperimentConfig,
                          topo: Topology) -> TrainState:
    """A TrainState-shaped pytree of PartitionSpecs: P() (replicated)
    scalars, per-leaf engine-derived specs for param-shaped subtrees
    (tensor/pipeline/expert placements per the model's rule table), and
    — under ``parallel.shard_weight_update`` — optimizer moment slots
    split over the replica axis per the ZeRO-1 plan (every slot of a
    multi-slot optimizer shards the same way). Under
    ``parallel.resident_sharded`` the PARAMS take the same
    replica-split flat placement as the slots — the plan is the single
    source of truth for both layouts."""
    from jax.sharding import PartitionSpec as P_
    from ..train import optim as optim_lib

    abstract = abstract_train_params(model, cfg, topo)
    pspec = params_partition_specs(model, cfg, topo, params=abstract)
    opt = optim_lib.make_optimizer(cfg.optim)
    interval = cfg.sync.mode == "interval"
    plan = zero1_plan_for(model, cfg, topo, params=abstract)
    slot_spec = (zero1_state_specs(plan, pspec) if plan is not None
                 else pspec)
    mspec = optim_lib.init_slots(opt, lambda: slot_spec)
    param_spec = (slot_spec if plan is not None and plan.params_sharded
                  else pspec)
    return TrainState(
        params=param_spec,
        momentum=mspec,
        step=P_(), updates_applied=P_(), root_key=P_(),
        window_acc=pspec if interval else None,
        window_rounds=P_(), wall_ms=P_(), next_apply_ms=P_())


def init_train_state(model: Model, cfg: ExperimentConfig,
                     topo: Topology | None = None) -> TrainState:
    from ..train import optim as optim_lib

    params = _build_params(model, cfg, topo)
    store_dt = resolved_param_dtype(cfg)
    if store_dt != jnp.float32:
        # true low-precision training (no master copy): params are cast
        # once here and updated in this dtype from now on
        params = jax.tree.map(
            lambda p: (p.astype(store_dt)
                       if jnp.issubdtype(p.dtype, jnp.floating) else p),
            params)
    plan = (zero1_plan_for(model, cfg, topo, params=params)
            if topo is not None else None)
    opt = optim_lib.make_optimizer(cfg.optim)

    def one_slot_tree():
        if plan is not None:
            return zero1_init_state(params, plan,
                                    dtype_fn=optim_lib.slot_dtype)
        return jax.tree.map(
            lambda p: jnp.zeros(p.shape, optim_lib.slot_dtype(p.dtype)),
            params)

    momentum = optim_lib.init_slots(opt, one_slot_tree)
    interval = cfg.sync.mode == "interval"
    if plan is not None and plan.params_sharded:
        # resident-sharded layout: params live flattened-padded like
        # the slots (host-side pack at init; the engine's padding is
        # zeros by contract so the pack is exact)
        params = zero1_pack(params, plan)
    return TrainState(
        params=params,
        momentum=momentum,
        step=jnp.zeros((), jnp.int32),
        updates_applied=jnp.zeros((), jnp.int32),
        root_key=prng.root_key(cfg.train.seed),
        # fp32 always: the window accumulates float32 masked means even
        # when params store low-precision (precision.param_dtype)
        window_acc=(jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
            if interval else None),
        window_rounds=jnp.zeros((), jnp.float32),
        wall_ms=jnp.zeros((), jnp.float32),
        next_apply_ms=jnp.asarray(cfg.sync.interval_ms, jnp.float32),
    )


# ---------------------------------------------------------------------------
# canonical checkpoint layout (ZeRO-1 pack/unpack) + mesh portability
# ---------------------------------------------------------------------------

def world_signature(topo: Topology) -> dict:
    """The world a checkpoint is saved under — JSON-clean, stamped into
    every artifact's ``extra["world"]`` (train/loop.py ``_save``) so a
    restore can tell "same world, graft directly" from "resized world,
    reshard" and name both sides in errors
    (train/checkpoint.py ``WorldSizeMismatchError``). Only axes > 1
    enter the mesh record, so a pure-DP world compares equal however
    many size-1 axes the mesh spells out."""
    return {"num_replicas": int(topo.num_replicas),
            "process_count": int(jax.process_count()),
            "mesh": {ax: int(topo.mesh.shape[ax])
                     for ax in topo.mesh.axis_names
                     if int(topo.mesh.shape[ax]) > 1}}


def restore_for_topology(model: Model, cfg: ExperimentConfig,
                         topo: Topology, train_dir, template_state: TrainState,
                         step: int | None = None,
                         on_event: Callable[[dict], None] | None = None,
                         ) -> tuple[TrainState, dict, int] | None:
    """Mesh-portable restore (ROADMAP item 2, TF-Replicator's
    resource-shape-agnostic replicas): load an artifact saved under ANY
    world size and reshard it for the CURRENT mesh.

    Why this works without migration code:

    * **Params / sharded (tp/pp) state** — checkpoints store logical
      global arrays (the per-host sharded layout reassembles them from
      every saver process's shard file regardless of the reader's
      process count); the caller re-splits them per the NEW spec trees
      by placing the result with ``Topology.device_put_state`` over
      ``state_partition_specs`` — the rule engine derives those from
      the current mesh, not the saver's.
    * **ZeRO-1 optimizer state** — the canonical-layout contract
      unpacks momentum to logical shapes on save, so restore re-derives
      the :class:`~..parallel.partition_rules.Zero1Plan` (padding,
      chunk ownership) from the NEW replica count and repacks; an
      artifact that kept the flat layout (cross-process sharded saves)
      carries a foreign ``pad`` and is re-padded exactly
      (``zero1_pack`` truncates zero padding, never data).
    * **Data cursor** — ``extra["data_iter"]`` carries the lockstep
      ``batches`` coordinate plus the saver's world; the new world's
      ``BatchIterator.restore`` reassigns it so no sample range is
      dropped or double-visited (data/pipeline.py).

    A world change is reported through ``on_event`` as
    ``action: "cross_world_restore"`` naming both worlds — the
    journaled evidence the chaos cross-world resume invariant pairs
    with the supervisor's ``event: "reconfigure"`` license.

    **Cross-optimizer guard**: an artifact whose saved config carries a
    different optimizer-STATE kind (none/momentum/lars/lamb —
    train/optim.opt_state_kind) than this run raises the typed
    :class:`~..train.checkpoint.OptimizerStateMismatchError` BEFORE any
    graft is attempted. LARS and momentum state share a tree shape, so
    a structural check alone would silently reinterpret one as the
    other; and a shape mismatch (momentum tree into LAMB's
    ``{"m","v"}`` slots) would surface as an opaque flax structure
    error. Neither is a fallback-past-it condition — a kind mismatch
    affects every step of the run equally."""
    from ..train import checkpoint as ckpt
    from ..train import optim as optim_lib
    try:
        extra_got = ckpt.read_checkpoint_extra(train_dir, step)
    except (OSError, ValueError, KeyError):
        # unreadable/torn LATEST artifact: the restore call below owns
        # corrupt-checkpoint fallback (older steps of the same run
        # carry the same optimizer config, so the guard loses nothing)
        extra_got = None
    if extra_got is not None:
        saved_extra, probe_step = extra_got
        saved_optim = ((saved_extra or {}).get("config") or {}).get("optim")
        saved_kind = optim_lib.saved_opt_state_kind(saved_optim)
        want_kind = optim_lib.opt_state_kind(cfg.optim)
        if saved_kind is not None and saved_kind != want_kind:
            raise ckpt.OptimizerStateMismatchError(
                f"checkpoint step={probe_step} in {train_dir} holds "
                f"{saved_kind!r} optimizer state (saved optim config "
                f"{saved_optim!r}) but this run's optim.name="
                f"{cfg.optim.name!r} needs {want_kind!r} state; refusing "
                "to graft mismatched opt-state trees — restore under the "
                "saving optimizer, or start the new optimizer fresh "
                "(train.resume=false / a fresh train_dir)",
                saved_kind=saved_kind, requested_kind=want_kind)
    restored = ckpt.restore_checkpoint(train_dir, template_state,
                                       step=step, on_event=on_event)
    if restored is None:
        return None
    state, extra, got_step = restored
    # precision portability: params are stored in the saving run's
    # storage dtype (fp32 masters, or a low-precision no-master layout);
    # normalize to THIS config's storage dtype so a precision-knob
    # change never leaves a stale-dtype tree in the live state
    store_dt = resolved_param_dtype(cfg)

    def _to_storage_dtype(p):
        dt = getattr(p, "dtype", None)
        if dt is None or not jnp.issubdtype(jnp.dtype(dt), jnp.floating):
            return p
        return p if jnp.dtype(dt) == store_dt else p.astype(store_dt)

    state = state.replace(params=jax.tree.map(_to_storage_dtype,
                                              state.params))
    # the plan (padding, chunk ownership) comes from the CURRENT
    # replica count — never the saver's n
    plan = zero1_plan_for(model, cfg, topo)
    state = pack_restored_state(state, plan)
    saved_world = (extra or {}).get("world")
    current = world_signature(topo)
    if isinstance(saved_world, dict) and saved_world != current:
        logger.info("cross-world restore: checkpoint step=%d saved under "
                    "world %s resharded onto %s", got_step, saved_world,
                    current)
        if on_event is not None:
            on_event({"layer": "checkpoint",
                      "action": "cross_world_restore", "step": got_step,
                      "saved_world": saved_world, "new_world": current})
    return state, extra, got_step


def canonical_save_state(state: TrainState,
                         plan: Zero1Plan | None) -> TrainState:
    """The state as checkpoints store it: optimizer buffers in their
    LOGICAL shapes regardless of the in-memory ZeRO-1 layout, so the
    artifact (and its canonical path digest, train/checkpoint.py) is
    byte-stable across ``parallel.shard_weight_update`` settings and a
    sharded run's checkpoint restores onto a replicated config (and
    vice versa) with no migration. Multi-slot optimizer state (LAMB's
    first/second moments) unpacks per slot, same contract; under
    ``parallel.resident_sharded`` the params unpack too — artifacts
    carry logical params whatever layout the live state keeps them in,
    so the path digest is identical across comm_buckets /
    resident_sharded / shard_weight_update. Host-side; a no-op without
    a plan."""
    from ..train import optim as optim_lib
    if plan is None:
        return state
    if state.momentum is not None:
        state = state.replace(momentum=optim_lib.map_slots(
            lambda tree: zero1_unpack(tree, plan), state.momentum))
    if plan.params_sharded:
        state = state.replace(params=zero1_unpack(state.params, plan))
    return state


def pack_restored_state(state: TrainState,
                        plan: Zero1Plan | None) -> TrainState:
    """Inverse of :func:`canonical_save_state` on the restore path:
    fold canonically-saved (logical-shape) optimizer slots — and, when
    the plan keeps params resident-sharded, the params — back into the
    flattened-padded replica-shard layout the live state uses.
    Exact — padding is zeros, truncation only ever removes padding."""
    from ..train import optim as optim_lib
    if plan is None:
        return state
    if state.momentum is not None:
        state = state.replace(momentum=optim_lib.map_slots(
            lambda tree: zero1_pack(tree, plan), state.momentum))
    if plan.params_sharded:
        state = state.replace(params=zero1_pack(state.params, plan))
    return state


def _spec_norm_axes(spec) -> tuple[str, ...]:
    """The mesh axes a PartitionSpec pins any dim to — what a partial
    leaf's sum-of-squares must psum over so the trust-ratio math sees
    the FULL logical leaf's norms (TP/stage/expert placements hold
    shards inside shard_map)."""
    axes: list[str] = []
    for entry in tuple(spec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.extend(a for a in entry if a is not None)
        else:
            axes.append(entry)
    return tuple(axes)


def _apply_tree_update(opt, params: Any, grads: Any, opt_state: Any,
                       lr: jax.Array, t: jax.Array,
                       param_specs: Any) -> tuple[Any, Any]:
    """The replicated-discipline weight update: map the optimizer's
    pure per-leaf rule (train/optim.py) over full logical leaves.
    ``norm_reduce`` completes partial sums over whatever non-replica
    axes a leaf is sharded on (its PartitionSpec); fully-replicated
    leaves reduce with the identity. NO masking guard here — callers
    own the all-masked no-op semantics (lr·applied for stateless sgd,
    a select for stateful optimizers whose moments would decay)."""
    from ..train import optim as optim_lib

    p_leaves, treedef = jax.tree.flatten(params)
    g_leaves = treedef.flatten_up_to(grads)
    spec_leaves = treedef.flatten_up_to(param_specs)
    in_slot_trees = optim_lib.slot_trees(opt, opt_state)
    slot_leaves = [treedef.flatten_up_to(tr) for tr in in_slot_trees]

    new_p: list = []
    new_slots: list[list] = [[] for _ in in_slot_trees]
    for i, (p, g, spec) in enumerate(zip(p_leaves, g_leaves, spec_leaves)):
        axes = _spec_norm_axes(spec)
        nr = ((lambda x, a=axes: lax.psum(x, a)) if axes
              else (lambda x: x))
        slots = tuple(sl[i] for sl in slot_leaves)
        np_, ns = opt.update_leaf(p, g, slots, lr, t, nr,
                                  adapt=len(getattr(p, "shape", ())) > 1)
        new_p.append(np_)
        for j, s in enumerate(ns):
            new_slots[j].append(s)
    return (jax.tree.unflatten(treedef, new_p),
            optim_lib.from_slot_trees(
                opt, [jax.tree.unflatten(treedef, sl) for sl in new_slots]))


def _pad_flat(x: jax.Array, lp) -> jax.Array:
    """Flatten a logical leaf and zero-pad it to the plan's ``pad``
    length (the even-split layout; padding math lives in the engine,
    partition_rules.LeafShardPlan)."""
    flat = x.reshape(-1)
    if lp.pad == lp.size:
        return flat
    return jnp.concatenate(
        [flat, jnp.zeros((lp.pad - lp.size,), flat.dtype)])


def _zero1_update(params: Any, grads: Any, opt_state: Any,
                  flag: jax.Array, lr: jax.Array, t: jax.Array,
                  axis: str, plan: Zero1Plan, opt, param_specs: Any
                  ) -> tuple[Any, Any, jax.Array, jax.Array]:
    """The ZeRO-1 weight-update discipline (arXiv:2004.13336), inside
    shard_map: per sharded leaf, the masked gradients are
    REDUCE-SCATTERED over the replica axis (each replica receives the
    summed 1/n slice — the full mean gradient is never materialized),
    the optimizer's moment slots and param slice are updated locally
    via the same pure per-leaf rule the replicated path uses
    (train/optim.py — trust-ratio norms complete over the replica axis,
    exact because ZeRO padding is zeros), and the fresh param slices
    are allgathered back to the replicated layout the forward pass
    consumes. Fallback leaves (tensor-parallel placements, leaves below
    the shard floor) take the classic replicated psum + full update,
    with their norms completed over whatever axes their spec shards.

    Masking semantics match the replicated path exactly: gradients are
    pre-scaled by ``flag / max(psum(flag), 1)`` so the scattered sum IS
    the masked mean, and an all-masked step is a true no-op (stateless
    SGD scales lr by the applied flag; stateful optimizers — whose
    moments would decay — are select-guarded).

    **Bucketed overlap** (``plan.comm_buckets > 1``, arXiv:1810.11112):
    the sharded leaves' collectives are regrouped into layer-ordered
    buckets (``partition_rules.comm_bucket_assignment``) — per bucket,
    each leaf's padded gradient reshapes to ``[n, chunk]``, the rows
    concatenate into one ``[n, C_b]`` matrix, and ONE ``psum_scatter``
    hands this replica its concatenated chunk row; the allgather leg
    reassembles per bucket the same way. A bucket's scatter depends
    only on its own leaves' gradients, so the compiler can issue it
    while earlier layers' backward still runs, and the per-collective
    launch cost amortizes over the bucket. The per-ELEMENT cross-
    replica sums are untouched by the regrouping — same addends, same
    collective op, same dtype — so losses/params stay bitwise equal to
    the monolithic path (pinned in tests/test_zero1.py).

    **Resident-sharded params** (``plan.params_sharded``): the param
    leaf arriving here IS this replica's ``[chunk]`` slice (the state
    keeps the flat layout between steps), so the update skips both the
    pre-update ``dynamic_slice`` and the post-update allgather — the
    NEXT forward's just-in-time bucket gather replaces it
    (:func:`_gather_resident_params`).

    Returns ``(new_params, new_opt_state, num_contributors, applied)``.
    """
    from ..train import optim as optim_lib

    scale, num = contribution_scale(flag, axis)
    applied = (num > 0).astype(jnp.int32)
    me = lax.axis_index(axis)

    p_leaves, treedef = jax.tree.flatten(params)
    g_leaves = treedef.flatten_up_to(grads)
    lp_leaves = treedef.flatten_up_to(plan.leaf_plans)
    spec_leaves = treedef.flatten_up_to(param_specs)
    in_slot_trees = optim_lib.slot_trees(opt, opt_state)
    slot_leaves = [treedef.flatten_up_to(tr) for tr in in_slot_trees]
    stateless = opt.num_slots == 0
    # stateless sgd: lr·0 is exact, so scaling lr by the applied flag
    # IS the all-masked no-op (same trick as the replicated path)
    lr_eff = lr * applied.astype(jnp.float32) if stateless else lr
    resident = plan.params_sharded
    bucketed = plan.comm_buckets > 1
    buckets = (comm_bucket_assignment(plan) if bucketed or resident
               else [])

    def guard(new, old):
        return new if stateless else jnp.where(applied > 0, new, old)

    gm_leaves = [g * scale.astype(g.dtype) for g in g_leaves]

    # bucketed reduce-scatter: one collective per layer-ordered bucket,
    # issued as soon as that bucket's gradients exist in the dataflow
    gsh_by_leaf: dict[int, jax.Array] = {}
    if bucketed:
        for bucket in buckets:
            rows = [_pad_flat(gm_leaves[i], lp_leaves[i])
                    .reshape(plan.n, lp_leaves[i].chunk) for i in bucket]
            scat = lax.psum_scatter(jnp.concatenate(rows, axis=1), axis,
                                    scatter_dimension=0, tiled=True)[0]
            off = 0
            for i in bucket:
                c = lp_leaves[i].chunk
                gsh_by_leaf[i] = scat[off:off + c]
                off += c

    new_p: list = []
    new_slots: list[list] = [[] for _ in in_slot_trees]
    upd_chunks: dict[int, jax.Array] = {}  # bucketed gather leg inputs
    for i, (p, gm, lp, spec) in enumerate(
            zip(p_leaves, gm_leaves, lp_leaves, spec_leaves)):
        slots = tuple(sl[i] for sl in slot_leaves)
        adapt = len(lp.shape) > 1
        if lp.sharded:
            if bucketed:
                gsh = gsh_by_leaf[i]
            else:
                # monolithic discipline: reduce-scatter per leaf —
                # [pad] masked grads → this replica's summed [chunk]
                # slice (already the mean via the pre-scale)
                gsh = lax.psum_scatter(_pad_flat(gm, lp), axis,
                                       scatter_dimension=0, tiled=True)
            psh = (p if resident
                   else lax.dynamic_slice(_pad_flat(p, lp),
                                          (me * lp.chunk,), (lp.chunk,)))
            nps, nslots = opt.update_leaf(
                psh, gsh, slots, lr_eff, t,
                lambda x: lax.psum(x, axis), adapt)
            # select on the chunk — 1/n of the replicated guard cost
            nps = guard(nps, psh)
            nslots = tuple(guard(ns, s) for ns, s in zip(nslots, slots))
            if resident:
                new_p.append(nps)  # stays a chunk; next forward gathers
            elif bucketed:
                upd_chunks[i] = nps
                new_p.append(None)  # filled by the bucket gather below
            else:
                full = mesh_lib.gather_chunks_replicated(
                    nps, axis, lp.pad, me * lp.chunk)
                new_p.append(full[:lp.size].reshape(lp.shape))
        else:
            mean = lax.psum(gm, axis)
            axes = _spec_norm_axes(spec)
            nr = ((lambda x, a=axes: lax.psum(x, a)) if axes
                  else (lambda x: x))
            npv, nslots = opt.update_leaf(p, mean, slots, lr_eff, t,
                                          nr, adapt)
            new_p.append(guard(npv, p))
            nslots = tuple(guard(ns, s) for ns, s in zip(nslots, slots))
        for j, s in enumerate(nslots):
            new_slots[j].append(s)
    if bucketed and not resident:
        # allgather leg, per bucket: one collective reassembles every
        # leaf of the bucket; column slices of the replicated [n, C_b]
        # recover each leaf's [n, chunk] view, whose row-major flatten
        # IS its padded layout
        for bucket in buckets:
            cat = jnp.concatenate([upd_chunks[i] for i in bucket])
            full = mesh_lib.gather_bucket_replicated(cat, axis, plan.n)
            off = 0
            for i in bucket:
                lp = lp_leaves[i]
                flat = full[:, off:off + lp.chunk].reshape(-1)
                new_p[i] = flat[:lp.size].reshape(lp.shape)
                off += lp.chunk
    params_out = jax.tree.unflatten(treedef, new_p)
    state_out = optim_lib.from_slot_trees(
        opt, [jax.tree.unflatten(treedef, sl) for sl in new_slots])
    return params_out, state_out, num, applied


def _gather_resident_params(params: Any, plan: Zero1Plan,
                            axis: str) -> Any:
    """The just-in-time weight gather of the resident-sharded layout
    (``parallel.resident_sharded``): reassemble full LOGICAL param
    leaves from the per-replica flat chunks the state carries, one
    collective per layer-ordered comm bucket — the next forward's
    gather replacing the classic post-update allgather
    (arXiv:2004.13336 §5). Runs inside shard_map on the chunk view;
    fallback (unsharded) leaves pass through untouched."""
    leaves, treedef = jax.tree.flatten(params)
    lp_leaves = treedef.flatten_up_to(plan.leaf_plans)
    out = list(leaves)
    for bucket in comm_bucket_assignment(plan):
        cat = jnp.concatenate([leaves[i] for i in bucket])
        full = mesh_lib.gather_bucket_replicated(cat, axis, plan.n)
        off = 0
        for i in bucket:
            lp = lp_leaves[i]
            flat = full[:, off:off + lp.chunk].reshape(-1)
            out[i] = flat[:lp.size].reshape(lp.shape)
            off += lp.chunk
    return jax.tree.unflatten(treedef, out)


# jitted gather per (plan, mesh) — a fresh jax.jit wrapper per call
# would miss the jit cache and recompile the gather on every
# evaluate(). Keyed by id(plan) with the plan itself stored for the
# identity check (its dict-structured leaf_plans make it unhashable);
# the stored reference pins the plan, so ids can't be recycled under a
# live entry — hence the size cap, which bounds what the cache keeps
# alive across many short-lived Trainers.
_logical_params_fns: dict[int, tuple] = {}


def logical_params(state_params: Any, plan: Zero1Plan | None,
                   topo: Topology) -> Any:
    """A REPLICATED logical-layout view of possibly resident-sharded
    live params — what in-process consumers that want the classic
    layout (Trainer.evaluate feeding build_eval_step) call. A
    passthrough without a resident plan; otherwise a jitted
    truncate-and-reshape with replicated out_shardings (cached per
    plan, so repeated evals pay a gather, not a recompile), working on
    multi-host meshes too (checkpoint consumers never need this —
    artifacts already store the canonical logical layout)."""
    if plan is None or not plan.params_sharded:
        return state_params
    from jax.sharding import NamedSharding
    cached = _logical_params_fns.get(id(plan))
    if cached is None or cached[0] is not plan or cached[1] is not topo.mesh:

        def unpack(tree):
            return jax.tree.map(
                lambda x, lp: (x[:lp.size].reshape(lp.shape) if lp.sharded
                               else x),
                tree, plan.leaf_plans)

        if len(_logical_params_fns) >= 32:
            _logical_params_fns.clear()
        cached = (plan, topo.mesh,
                  jax.jit(unpack,
                          out_shardings=NamedSharding(topo.mesh, P())))
        _logical_params_fns[id(plan)] = cached
    return cached[2](state_params)


def _gather_replicated(x: jax.Array, axis: str, n: int) -> jax.Array:
    """All-gather a per-replica scalar into a REPLICATED [n] vector.

    Expressed as a one-hot psum instead of ``lax.all_gather`` because
    psum's output is statically known to be replicated over ``axis`` —
    so it can leave shard_map under an out_spec of P() and every host
    of a multi-host run holds the full vector (an all_gather result
    stays marked device-varying and would need a sharded out_spec,
    which non-addressable processes cannot materialize)."""
    me = lax.axis_index(axis)
    onehot = (jnp.arange(n) == me).astype(x.dtype)
    return lax.psum(onehot * x, axis)


def measure_bucket_comm_ms(topo: Topology, plan: Zero1Plan,
                           repeats: int = 3) -> list[float]:
    """Calibrate each comm bucket's scatter+gather wall ms in
    isolation (median of ``repeats`` timed runs of a tiny jitted
    program per bucket) — the per-bucket comm gauge the timing report
    surfaces when overlap is on (obsv/timing.py). Inside the fused
    train step the per-bucket comm time is not separately observable;
    this measures the same collectives on zeros of the same shapes.
    One small compile per bucket — call from precompile, not per
    step."""
    import statistics
    import time as _time
    axis = topo.replica_axis
    n = plan.n
    lps = jax.tree.leaves(plan.leaf_plans,
                          is_leaf=lambda x: hasattr(x, "sharded"))
    out: list[float] = []
    for bucket in comm_bucket_assignment(plan):
        c_b = sum(lps[i].chunk for i in bucket)

        def probe(x):
            s = lax.psum_scatter(x, axis, scatter_dimension=0,
                                 tiled=True)[0]
            g = mesh_lib.gather_bucket_replicated(s, axis, n)
            return g.sum()

        fn = jax.jit(mesh_lib.shard_map(probe, mesh=topo.mesh,
                                        in_specs=P(), out_specs=P()))
        x = jnp.zeros((n, c_b), jnp.float32)
        float(fn(x))  # compile + warm
        times = []
        for _ in range(max(1, repeats)):
            t0 = _time.perf_counter()
            float(fn(x))
            times.append((_time.perf_counter() - t0) * 1e3)
        out.append(statistics.median(times))
    return out


def build_train_step(model: Model, cfg: ExperimentConfig, topo: Topology,
                     schedule: Schedule) -> Callable[[TrainState, dict], tuple[TrainState, dict]]:
    """Compile the per-step SPMD training function.

    Returns ``step_fn(state, batch, measured_ms=None, discipline=None)
    -> (state, metrics)`` where ``batch = {"image": [B, ...], "label":
    [B]}`` is globally batched and sharded over the replica axis, and
    state/metrics are replicated. ``measured_ms`` is an optional
    per-replica [n] vector of real measured step times (ms), sharded
    over the replica axis: each host feeds the entries for its own
    replicas (Topology.device_put_measured), so quorum/timeout/interval
    policies select on genuine per-replica speed — ≙ the reference's
    measured per-worker CDF semantics (src/timeout_manager.py:48-61)
    without the RPC mesh. Defaults to zeros (pure synthetic-profile
    timing).

    ``discipline`` is an optional replicated [3] float32 vector
    ``(k, timeout_ms, interval_ms)`` (make_discipline_vector) carrying
    the aggregation-discipline parameters as *traced* inputs: the
    adaptive straggler controller (train/discipline.py) swaps this
    scalar buffer at runtime and the same compiled executable keeps
    running — a discipline change costs a device_put, not a recompile.
    Defaults to the static values from ``cfg.sync``.
    """
    axis = topo.replica_axis
    n = topo.num_replicas
    sync = cfg.sync
    sync.validate(num_replicas=n)
    mode = sync.mode
    if mode not in ("sync", "quorum", "timeout", "interval", "cdf"):
        raise ValueError(f"unknown sync mode {mode!r}")
    k = policies.resolve_aggregate_k(sync, n)
    from ..train import optim as optim_lib
    opt = optim_lib.make_optimizer(cfg.optim)  # validates the section
    # Gradient accumulation (train.grad_accum_steps): the step receives
    # accum host batches concatenated along dim 0 and scans them as
    # microbatches, accumulating gradients in float32 before ONE
    # optimizer application — effective batch = data.batch_size × accum.
    accum = max(1, int(cfg.train.grad_accum_steps))
    # Mixed precision (cfg.precision): with master weights the state
    # params are float32 and the forward pass sees a derived
    # param_dtype view; differentiating w.r.t. the view is exact — the
    # cast's transpose casts cotangents back, and grads are accumulated
    # in float32 regardless.
    param_dtype = jnp.dtype(cfg.precision.param_dtype)
    fwd_cast = (cfg.precision.master_weights
                and param_dtype != jnp.float32)

    def fwd_view(params):
        if not fwd_cast:
            return params
        return jax.tree.map(
            lambda p: (p.astype(param_dtype)
                       if jnp.issubdtype(p.dtype, jnp.floating) else p),
            params)

    # Sequence parallelism: when the mesh spends devices on the seq
    # axis, the model must provide a sequence-sharded apply (the
    # transformer does, via ring/all-to-all attention). Each shard then
    # computes a PARTIAL loss/gradient over its token slice; psum over
    # the seq axis reassembles the exact full-sequence gradient before
    # the replica-axis aggregation disciplines see it.
    #
    # Tensor parallelism: when the mesh's model axis is >1, params are
    # placed per the model's TP partition specs; each rank holds its
    # head/MLP column shard, activations stay replicated over the axis
    # (psums inside apply), and each rank's param gradients are its own
    # shard's — no model-axis reduction of gradients is needed.
    seq_ax = topo.seq_axis
    n_seq = topo.mesh.shape[seq_ax]
    model_ax = topo.model_axis
    n_model = topo.mesh.shape[model_ax]
    # Pipeline parallelism: layers sharded over the stage axis, batch
    # microbatched through the activation pipeline (ops/pipeline.py).
    # Stage-sharded param grads stay local; replicated leaves (embed,
    # norms) get their stage-psum from the AD transpose of replication.
    stage_ax = topo.stage_axis
    n_stage = topo.mesh.shape[stage_ax]
    # Expert parallelism: experts sharded over the expert axis; composes
    # with TP (model axis splits heads + every expert's hidden dim).
    expert_ax = topo.expert_axis
    n_expert = topo.mesh.shape[expert_ax]
    if ((n_seq > 1 or n_model > 1 or n_expert > 1) and n_stage == 1
            and getattr(model, "sharded_apply_factory", None) is None):
        raise ValueError(
            f"mesh has seq_parallelism={n_seq} / model_parallelism="
            f"{n_model} / expert_parallelism={n_expert} but model "
            f"{model.name!r} supports none of them "
            "(no sharded_apply_factory)")
    pp_schedule = cfg.mesh.pipeline_schedule
    if pp_schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline_schedule {pp_schedule!r}")
    pp_1f1b_grads_fn = None
    if n_stage > 1:
        if getattr(model, "pp_apply_factory", None) is None:
            raise ValueError(f"mesh has pipeline_parallelism={n_stage} but "
                             f"model {model.name!r} has no pipeline apply")
        if pp_schedule == "1f1b":
            # fused interleaved schedule (ops/pipeline.py): explicit
            # forward/backward chunk-works in one scan — built below
            # instead of value_and_grad. TP/SP/EP collectives inside
            # the chunk bodies execute inside the engine's
            # stage-varying switch branches; that is safe because they
            # reduce over NON-stage axes whose participant groups share
            # a stage coordinate and hence a branch (ops/pipeline.py).
            if getattr(model, "pp_1f1b_grads_factory", None) is None:
                raise ValueError(f"model {model.name!r} has no 1f1b "
                                 "pipeline support")
            pp_1f1b_grads_fn = model.pp_1f1b_grads_factory(
                stage_ax, cfg.mesh.pipeline_microbatches,
                cfg.mesh.pipeline_chunks,
                model_ax if n_model > 1 else None,
                seq_ax if n_seq > 1 else None,
                expert_ax if n_expert > 1 else None)
            pp_apply = None
        else:
            # PP outermost; TP (model axis) inside each stage; SP (seq
            # axis) through the stage blocks' sharded attention; EP
            # (expert axis) through the blocks' grouped MoE dispatch —
            # every device runs the same tick schedule so attention and
            # expert collectives stay lockstep inside the pipeline scan
            pp_apply = model.pp_apply_factory(
                stage_ax, cfg.mesh.pipeline_microbatches,
                model_ax if n_model > 1 else None,
                seq_ax if n_seq > 1 else None,
                expert_ax if n_expert > 1 else None)
    else:
        pp_apply = None
    sharded_apply = (model.sharded_apply_factory(
        seq_ax if n_seq > 1 else None, model_ax if n_model > 1 else None,
        expert_ax if n_expert > 1 else None)
        if ((n_seq > 1 or n_model > 1 or n_expert > 1)
            and pp_apply is None and pp_1f1b_grads_fn is None)
        else None)
    # The SP/PP loss paths do not thread a dropout key; refuse loudly
    # instead of silently training a dropout model without dropout.
    if ((sharded_apply is not None or pp_apply is not None
            or pp_1f1b_grads_fn is not None)
            and getattr(model, "uses_dropout", False)):
        raise ValueError(
            f"model {model.name!r} uses dropout, but the sharded "
            "(SP/TP/PP) loss paths do not thread a dropout key; set "
            "model.dropout_rate=0 or run it data-parallel only")
    # raw per-shard grads are needed w.r.t. the axes the masks/explicit
    # psums manage; the model axis stays as-is (sharded params are
    # already device-varying there)
    grad_axes = (axis, seq_ax) if n_seq > 1 else (axis,)
    state_specs = state_partition_specs(model, cfg, topo)
    # per-leaf LOGICAL param placements — what the trust-ratio norm
    # reductions complete partial sums over for non-replica-sharded
    # leaves (NOT state_specs.params, which under resident_sharded
    # carries the flat replica-split layout instead)
    pspec_tree = params_partition_specs(model, cfg, topo)
    # ZeRO-1 (parallel.shard_weight_update): reduce-scatter grads,
    # update only this replica's param/momentum slice, allgather fresh
    # params — per the engine's shard plan, which state_partition_specs
    # and init_train_state derived the state layout from.
    z_plan = zero1_plan_for(model, cfg, topo)
    if cfg.parallel.shard_weight_update and z_plan is None:
        logger.warning(
            "parallel.shard_weight_update=true is a no-op here (%s); "
            "running the replicated update",
            "replica axis is 1" if n <= 1 else
            f"sync.mode={mode!r} keeps the full windowed accumulator")

    has_aux = getattr(model, "has_aux", False)
    aux_w = getattr(model, "aux_weight", 0.0)

    def local_loss(params, batch, dropout_key):
        if has_aux:
            logits, aux = model.apply(params, batch["image"], train=True,
                                      dropout_key=dropout_key,
                                      return_aux=True)
            return model.loss(logits, batch["label"]) + aux_w * aux, logits
        logits = model.apply(params, batch["image"], train=True,
                             dropout_key=dropout_key)
        return model.loss(logits, batch["label"]), logits

    def local_loss_pp(params, batch, dropout_key):
        del dropout_key
        if has_aux:  # MoE: per-group aux, tick-accumulated (apply_pp)
            logits, aux = pp_apply(params, batch["image"], return_aux=True)
            return model.loss(logits, batch["label"]) + aux_w * aux, logits
        logits = pp_apply(params, batch["image"])  # stage-replicated
        return model.loss(logits, batch["label"]), logits

    def make_sp_loss(apply_fn, with_aux):
        """Per-(replica, seq-shard) partial next-token loss over any
        seq-sharded apply (the DP×SP×TP path, or the pipeline apply for
        PP×SP).

        Targets are inputs shifted left by one GLOBAL position, so the
        target of a shard's last token lives on the next shard — one
        ppermute fetches each neighbor's first column. The global last
        position has no target (weight 0), matching the dense
        ``transformer.loss_fn`` exactly: partial sums are normalized by
        the global valid-token count so psum(partials) == dense loss.
        """
        def sp_loss(params, batch, dropout_key):
            del dropout_key
            tokens = batch["image"]
            labels = batch["label"]
            b, s_loc = tokens.shape
            me_s = lax.axis_index(seq_ax)
            positions = me_s * s_loc + jnp.arange(s_loc)
            if with_aux:  # MoE: EP-only, SP×EP, or PP×SP×EP
                logits, aux = apply_fn(params, tokens, positions,
                                       return_aux=True)
            else:
                logits = apply_fn(params, tokens, positions)  # [b, s_loc, V]
                aux = 0.0

            # shard j receives shard (j+1)'s first target column
            perm = [((j + 1) % n_seq, j) for j in range(n_seq)]
            nxt = lax.ppermute(labels[:, :1], seq_ax, perm)
            tgt = jnp.concatenate([labels[:, 1:], nxt], axis=1).astype(jnp.int32)

            from ..models.transformer import sp_partial_token_loss
            s_global = s_loc * n_seq
            # total = this replica's global token count; the shared
            # kernel keeps this path and the 1F1B seed head identical
            loss_part, acc_part = sp_partial_token_loss(
                logits, tgt, positions, s_global, b * (s_global - 1))
            # aux is already the full-token value on every seq shard
            # (moe_ffn pmeans its stats over the stats_axes), so the
            # caller's psum over the seq axis would count it n_seq
            # times — pre-divide so the psum reassembles exactly one.
            return loss_part + aux_w * aux / n_seq, acc_part
        return sp_loss

    local_loss_sp = (make_sp_loss(sharded_apply, has_aux)
                     if sharded_apply is not None else
                     make_sp_loss(pp_apply, has_aux)
                     if (pp_apply is not None and n_seq > 1) else None)

    def shard_fn(state: TrainState, batch: dict, measured_ms: jax.Array,
                 discipline: jax.Array) -> tuple[TrainState, dict]:
        me = lax.axis_index(axis)
        step = state.step
        my_measured_ms = measured_ms[0]  # this replica's [1]-shard
        # runtime discipline params (replicated [3]): traced, so the
        # adaptive controller swaps them without a recompile
        disc_k = discipline[DISC_K]
        disc_timeout_ms = discipline[DISC_TIMEOUT_MS]
        disc_interval_ms = discipline[DISC_INTERVAL_MS]

        # --- local forward+backward (one pass: the reference's second
        # forward per step, src/distributed_train.py:332-335, is a
        # documented quirk we do not replicate) -----------------------
        #
        # Params are replicated over the mesh; differentiating w.r.t. a
        # *replicated* value inside shard_map makes AD insert the
        # cross-axis psum itself (transpose of the broadcast). We need
        # the raw per-shard gradient — masks must apply BEFORE the
        # replica aggregation, and the seq-axis psum must be explicit —
        # so cast params to varying over every grad axis first.
        # Resident-sharded layout: the state carries per-replica flat
        # chunks; the just-in-time bucket gather reassembles the full
        # logical weights HERE — in the next step's forward — instead
        # of the update's trailing allgather (arXiv:2004.13336 §5).
        fwd_source = (state.params
                      if z_plan is None or not z_plan.params_sharded
                      else _gather_resident_params(state.params, z_plan,
                                                   axis))
        local_params = jax.tree.map(
            lambda x: lax.pcast(x, grad_axes, to="varying"), fwd_source)
        # master weights: the forward sees the derived param_dtype view
        fwd_params = fwd_view(local_params)

        def compute_grads(mb_batch, dkey):
            """(loss, train_acc, grads) for ONE microbatch — the
            per-parallelism branch chain, shared by the single-shot and
            the accumulation paths."""
            if pp_1f1b_grads_fn is not None:
                # fused 1F1B: the engine computes loss, accuracy and
                # grads in one interleaved scan — no outer
                # value_and_grad. Under SP the engine returns
                # per-seq-shard partials; psum reassembles the exact
                # dense values (same as the SP branch below).
                loss, train_acc, grads = pp_1f1b_grads_fn(
                    fwd_params, mb_batch["image"], mb_batch["label"])
                if n_seq > 1:
                    loss = lax.psum(loss, seq_ax)
                    train_acc = lax.psum(train_acc, seq_ax)
                    grads = jax.tree.map(lambda g: lax.psum(g, seq_ax),
                                         grads)
            elif local_loss_sp is not None:  # DP×SP×TP, or PP×SP
                (loss_p, acc_p), grads = jax.value_and_grad(
                    local_loss_sp, has_aux=True)(fwd_params, mb_batch, dkey)
                # reassemble the full-sequence gradient / metrics
                loss = lax.psum(loss_p, seq_ax)
                train_acc = lax.psum(acc_p, seq_ax)
                grads = jax.tree.map(lambda g: lax.psum(g, seq_ax), grads)
            elif pp_apply is not None:
                (loss, logits), grads = jax.value_and_grad(
                    local_loss_pp, has_aux=True)(fwd_params, mb_batch, dkey)
                train_acc = model.accuracy(logits, mb_batch["label"])
            else:
                (loss, logits), grads = jax.value_and_grad(
                    local_loss, has_aux=True)(fwd_params, mb_batch, dkey)
                train_acc = model.accuracy(logits, mb_batch["label"])
            return loss, train_acc, grads

        if accum == 1:
            dkey = prng.replica_key(state.root_key, "dropout", step, me)
            loss, train_acc, grads = compute_grads(batch, dkey)
            grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        else:
            # microbatch scan: fp32 accumulation, one optimizer apply.
            # The local rows are any accum-way partition of this
            # replica's slice of the effective batch — every sample
            # carries weight 1/(accum·b_local) locally and 1/n across
            # replicas, so the accumulated mean is exactly the
            # effective-batch mean regardless of the grouping.
            mb_batch = jax.tree.map(
                lambda x: x.reshape((accum, x.shape[0] // accum)
                                    + x.shape[1:]), batch)
            g_zero = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), fwd_params)

            def mb_body(carry, xs):
                g_acc, l_acc, a_acc = carry
                one_batch, idx = xs
                dkey = prng.replica_key(state.root_key, "dropout",
                                        step * accum + idx, me)
                l, a, g = compute_grads(one_batch, dkey)
                g_acc = jax.tree.map(
                    lambda s, gi: s + gi.astype(jnp.float32), g_acc, g)
                return (g_acc, l_acc + l, a_acc + a), None

            (g_sum, l_sum, a_sum), _ = lax.scan(
                mb_body, (g_zero, jnp.zeros((), jnp.float32),
                          jnp.zeros((), jnp.float32)),
                (mb_batch, jnp.arange(accum)))
            grads = jax.tree.map(lambda g: g / accum, g_sum)
            loss = l_sum / accum
            train_acc = a_sum / accum

        # --- per-worker drop-connect before aggregation
        # (src/distributed_train.py:194-196) --------------------------
        if sync.drop_connect:
            dckey = prng.replica_key(state.root_key, "drop_connect", step, me)
            grads = drop_connect_grads(grads, dckey, sync.drop_connect_probability)

        # --- step-time model & contribution mask ---------------------
        t_ms = policies.sample_step_time_ms(sync, state.root_key, step, me,
                                            my_measured_ms)
        if mode in ("sync", "cdf"):
            flag = jnp.ones((), jnp.float32)
        elif mode == "quorum":
            flag = policies.quorum_flag(t_ms, disc_k, axis)
        elif mode == "timeout":
            flag = policies.timeout_flag(t_ms, disc_timeout_ms)
        else:  # interval: stale if slower than a whole window
            flag = policies.timeout_flag(t_ms, disc_interval_ms)

        # --- apply discipline ----------------------------------------
        t_next = state.updates_applied.astype(jnp.float32) + 1.0
        if mode == "interval":
            mean_grads, num_contrib = masked_mean_psum(grads, flag, axis)
            new_state, applied = _interval_apply(state, mean_grads, t_ms,
                                                 disc_interval_ms)
        elif z_plan is not None:
            # ZeRO-1: no full mean gradient is ever built — the
            # reduce-scatter inside _zero1_update hands each replica
            # its slice of it directly
            lr = schedule(state.updates_applied)
            new_params, new_opt, num_contrib, applied = _zero1_update(
                state.params, grads, state.momentum, flag, lr, t_next,
                axis, z_plan, opt, pspec_tree)
            new_state = state.replace(
                params=new_params, momentum=new_opt,
                updates_applied=state.updates_applied + applied)
        else:
            mean_grads, num_contrib = masked_mean_psum(grads, flag, axis)
            lr = schedule(state.updates_applied)
            applied = (num_contrib > 0).astype(jnp.int32)
            # If every replica was masked out (possible under timeout),
            # the mean is zero and the update must be a true no-op.
            if opt.num_slots == 0:
                # stateless sgd: lr·0 is exact, so scaling the scalar
                # lr by the applied flag IS the no-op — no full-size
                # per-parameter select pass (a measured throughput tax
                # on small steps, bench_mode_overhead)
                new_params, new_opt = _apply_tree_update(
                    opt, state.params, mean_grads, None,
                    lr * applied.astype(jnp.float32), t_next, pspec_tree)
            else:
                new_params, new_opt = _apply_tree_update(
                    opt, state.params, mean_grads, state.momentum, lr,
                    t_next, pspec_tree)
                # moment slots decay even on zero gradients, so a true
                # no-op needs the select
                new_params = jax.tree.map(
                    lambda new, old: jnp.where(applied > 0, new, old),
                    new_params, state.params)
                new_opt = jax.tree.map(
                    lambda new, old: jnp.where(applied > 0, new, old),
                    new_opt, state.momentum)
            new_state = state.replace(
                params=new_params, momentum=new_opt,
                updates_applied=state.updates_applied + applied)

        new_state = new_state.replace(step=step + 1)

        # --- metrics: everything comes out REPLICATED (scalars via
        # pmean/psum, per-replica series via all_gather) so every host
        # holds the full [n] timing vector — a multi-host process can
        # materialize its own copy without touching non-addressable
        # shards (≙ the CDF timing gossip, src/timeout_manager.py:48-61,
        # with no RPC mesh at all) ------------------------------------
        metrics = {
            "loss": lax.pmean(loss, axis),
            "train_acc": lax.pmean(train_acc, axis),
            "lr": schedule(state.updates_applied),
            "num_contributors": num_contrib,
            "updates_applied": new_state.updates_applied,
            "step_times_ms": _gather_replicated(t_ms, axis, n),  # [n]
            "flags": _gather_replicated(flag, axis, n),          # [n]
            "applied": applied,
        }
        return new_state, metrics

    def _interval_apply(state: TrainState, mean_grads: Any,
                        t_ms: jax.Array,
                        interval_ms: jax.Array) -> tuple[TrainState, jax.Array]:
        """Wall-clock-windowed aggregation (≙ the chief's recurring
        Timer running take_grad(1)-average-of-arrived,
        sync_replicas_optimizer_modified.py:208-215,371-373,392-393).

        A wall-clock-async update is not expressible inside one SPMD
        program (SURVEY §7), so the window is re-expressed over the
        lockstep loop: each step's masked mean joins a window
        accumulator; the modeled wall clock advances by the mean
        replica pace; when it crosses the window boundary the
        accumulated average is applied and the window resets.
        """
        acc = jax.tree.map(lambda a, g: a + g, state.window_acc, mean_grads)
        rounds = state.window_rounds + 1.0
        wall = state.wall_ms + lax.pmean(t_ms, axis)
        fire = wall >= state.next_apply_ms

        lr = schedule(state.updates_applied)
        window_mean = jax.tree.map(lambda a: a / rounds, acc)
        applied_params, applied_bufs = _apply_tree_update(
            opt, state.params, window_mean, state.momentum, lr,
            state.updates_applied.astype(jnp.float32) + 1.0, pspec_tree)

        def pick(new, old):
            return jax.tree.map(lambda a, b: jnp.where(fire, a, b), new, old)

        new_params = pick(applied_params, state.params)
        new_bufs = (None if state.momentum is None
                    else pick(applied_bufs, state.momentum))
        zeros = jax.tree.map(jnp.zeros_like, acc)
        new_acc = pick(zeros, acc)
        new_rounds = jnp.where(fire, 0.0, rounds)
        # Reschedule relative to *now*, as the reference timer does by
        # re-arming after each run (skipped windows are not replayed).
        next_apply = jnp.where(fire, wall + interval_ms, state.next_apply_ms)
        applied = fire.astype(jnp.int32)
        return state.replace(
            params=new_params, momentum=new_bufs, window_acc=new_acc,
            window_rounds=new_rounds, wall_ms=wall, next_apply_ms=next_apply,
            updates_applied=state.updates_applied + applied), applied

    mesh = topo.mesh
    metrics_specs = {
        "loss": P(), "train_acc": P(), "lr": P(), "num_contributors": P(),
        "updates_applied": P(), "step_times_ms": P(), "flags": P(),
        "applied": P(),
    }
    batch_spec = P(axis, seq_ax) if n_seq > 1 else P(axis)
    sharded = mesh_lib.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(state_specs, batch_spec, P(axis), P()),
        out_specs=(state_specs, metrics_specs))
    jitted = jax.jit(sharded, donate_argnums=0)

    zeros_ms: list[jax.Array] = []  # lazily built + cached default
    disc_default: list[jax.Array] = []  # static-cfg discipline vector
    # AOT fast path (parallel/aot.py): precompile() fills this with the
    # ahead-of-time compiled executable + the argument signature it was
    # lowered for; step_fn then dispatches matching concrete calls
    # through it — the first training step after a precompile (or a
    # warm-standby promotion) never waits on jit's compile path.
    aot_box: dict[str, Any] = {}

    def _default_measured() -> jax.Array:
        if not zeros_ms:
            zeros_ms.append(topo.zeros_measured())
        return zeros_ms[0]

    def _default_discipline() -> jax.Array:
        if not disc_default:
            disc_default.append(make_discipline_vector(
                k, sync.timeout_ms, sync.interval_ms))
        return disc_default[0]

    def _args_sig(args):
        leaves, treedef = jax.tree.flatten(args)
        return (treedef,
                tuple((getattr(x, "shape", ()), getattr(x, "dtype", None))
                      for x in leaves))

    def step_fn(state: TrainState, batch: dict,
                measured_ms: jax.Array | None = None,
                discipline: jax.Array | None = None):
        if measured_ms is None:
            measured_ms = _default_measured()
        if discipline is None:
            discipline = _default_discipline()
        exe = aot_box.get("exe")
        if exe is not None:
            # one flatten covers both guards: tracers ANYWHERE in the
            # args (a caller jitting over step_fn — e.g. bench's scanned
            # chunks, or a jit closing over state but tracing the batch)
            # must take the traceable jit path, and a different
            # signature (a test swapping batch shapes) simply compiles
            # through jit as before. Compared leafwise with early exit —
            # no per-step sig allocation on this hot path.
            leaves, treedef = jax.tree.flatten(
                (state, batch, measured_ms, discipline))
            sig_td, sig_leaves = aot_box["sig"]
            if (treedef == sig_td and len(leaves) == len(sig_leaves)
                    and not any(isinstance(x, jax.core.Tracer)
                                for x in leaves)
                    and all(getattr(x, "shape", ()) == s
                            and getattr(x, "dtype", None) == d
                            for x, (s, d) in zip(leaves, sig_leaves))):
                return exe(state, batch, measured_ms, discipline)
        return jitted(state, batch, measured_ms, discipline)

    def precompile(state: TrainState, batch: dict,
                   measured_ms: jax.Array | None = None,
                   discipline: jax.Array | None = None,
                   cache_dir=None, cache_key: str | None = None,
                   trust_cross_process: bool = False) -> dict[str, Any]:
        """AOT-compile the step for these exact avals (no execution, no
        donation — lowering only reads shapes) and arm the fast path.
        With a cache_dir+key, the executable round-trips the disk cache
        where the platform supports it AND the jax release is outside
        the cross-process corruption quarantine (parallel/aot.py)."""
        from . import aot as aot_lib
        if measured_ms is None:
            measured_ms = _default_measured()
        if discipline is None:
            discipline = _default_discipline()
        compiled, info = aot_lib.aot_compile(
            jitted, (state, batch, measured_ms, discipline),
            cache_dir=cache_dir, key=cache_key,
            trust_cross_process=trust_cross_process)
        aot_box["exe"] = compiled
        aot_box["sig"] = _args_sig((state, batch, measured_ms, discipline))
        return info

    step_fn.precompile = precompile
    step_fn.jitted = jitted
    step_fn.default_discipline = _default_discipline
    return step_fn


def build_eval_step(model: Model, cfg: ExperimentConfig, topo: Topology):
    """Sharded inference step: weighted accuracy/loss so padded
    examples (batch not divisible by replica count) don't bias metrics.

    ``batch = {"image", "label", "weight"}``; returns summed
    (correct, weighted_loss, weight) — caller divides.
    """
    axis = topo.replica_axis
    model_ax = topo.model_axis
    n_model = topo.mesh.shape[model_ax]
    n_stage = topo.mesh.shape[topo.stage_axis]
    n_expert = topo.mesh.shape[topo.expert_axis]
    if n_stage > 1:
        # pipeline-parallel params: stacked layout. Eval pipelines at
        # the largest microbatch count that divides the per-replica
        # eval rows (capped by the training cadence) — M=1 would run
        # the stages fully serialized, an S× eval slowdown measured in
        # the tens of minutes on deep CPU-mesh evals.
        if getattr(model, "pp_apply_factory", None) is None:
            raise ValueError(f"mesh has pipeline_parallelism={n_stage} but "
                             f"model {model.name!r} has no pipeline apply")
        tp_ax = model_ax if n_model > 1 else None
        ep_ax = topo.expert_axis if n_expert > 1 else None
        pspec: Any = params_partition_specs(model, cfg, topo)
        if (cfg.mesh.pipeline_schedule == "1f1b"
                and getattr(model, "pp_1f1b_apply_factory", None) is None):
            # mirror the train-path guard: fail with a clear error at
            # build time instead of an opaque trace-time NoneType call
            raise ValueError(f"model {model.name!r} has no 1f1b "
                             "pipeline support")
        cap = max(1, cfg.mesh.pipeline_microbatches)

        def run(params, images):
            # per-replica rows are static at trace time (eval batches
            # are padded to a fixed shape); pipeline at the largest
            # microbatch count ≤ the training cadence that divides
            # them. MoE included: token groups nest inside sequence
            # rows (ops/moe.py), so routing capacity and metrics are
            # identical for every microbatch split — the round-4 M=1
            # force is gone (tests pin M-invariance).
            b = images.shape[0]
            m_eval = max(m for m in range(1, cap + 1) if b % m == 0)
            if cfg.mesh.pipeline_schedule == "1f1b":
                apply_fn = model.pp_1f1b_apply_factory(
                    topo.stage_axis, m_eval, cfg.mesh.pipeline_chunks,
                    tp_ax, ep_ax)
            else:
                apply_fn = model.pp_apply_factory(topo.stage_axis, m_eval,
                                                  tp_ax, None, ep_ax)
            return apply_fn(params, images)
    elif n_model > 1 or n_expert > 1:
        # tensor-/expert-parallel params: sharded apply (full sequence
        # per device — eval batches are not seq-sharded), sharded in_spec
        if (getattr(model, "tp_param_specs", None) is None
                or getattr(model, "sharded_apply_factory", None) is None):
            raise ValueError(f"mesh has model_parallelism={n_model} / "
                             f"expert_parallelism={n_expert} but model "
                             f"{model.name!r} is not tensor-/expert-parallel "
                             "capable")
        tp_ax = model_ax if n_model > 1 else None
        ep_ax = topo.expert_axis if n_expert > 1 else None
        pspec: Any = params_partition_specs(model, cfg, topo)
        tp_apply = model.sharded_apply_factory(None, tp_ax, ep_ax)

        def run(params, images):
            return tp_apply(params, images, None)
    else:
        # engine-derived per-leaf tree (all P() on a pure-DP mesh) —
        # same derivation as the train step, one source of truth
        pspec = params_partition_specs(model, cfg, topo)

        def run(params, images):
            return model.apply(params, images, train=False)

    def shard_fn(params, batch):
        logits = run(params, batch["image"])
        correct, loss_sum, weight = model.eval_metrics(
            logits, batch["label"], batch["weight"])
        return (lax.psum(correct, axis), lax.psum(loss_sum, axis),
                lax.psum(weight, axis))

    sharded = mesh_lib.shard_map(
        shard_fn, mesh=topo.mesh,
        in_specs=(pspec, P(axis)),
        out_specs=(P(), P(), P()))
    return jax.jit(sharded)


def build_weight_update_step(model: Model, cfg: ExperimentConfig,
                             topo: Topology, schedule: Schedule):
    """Jitted ``(state, grads) -> state`` applying ONLY the gradient
    aggregation + weight update — no forward/backward — under the
    configured discipline (replicated, or ZeRO-1 when
    ``parallel.shard_weight_update`` applies).

    This isolates the exact region the ZeRO-1 paper optimizes so the
    ``weight_update_sharding`` bench case (bench.py) can time it and
    meter its per-chip optimizer-state bytes without the model compute
    drowning the signal. ``grads`` is a params-shaped pytree placed per
    ``params_partition_specs`` (replicated on a pure-DP mesh); its
    values only feed the update, so a bench may pass any tree of the
    right shapes.
    """
    axis = topo.replica_axis
    from ..train import optim as optim_lib
    opt = optim_lib.make_optimizer(cfg.optim)
    if cfg.sync.mode == "interval":
        raise ValueError("build_weight_update_step models the per-step "
                         "apply disciplines; interval mode applies on a "
                         "wall-clock window (use build_train_step)")
    state_specs = state_partition_specs(model, cfg, topo)
    grad_specs = params_partition_specs(model, cfg, topo)
    z_plan = zero1_plan_for(model, cfg, topo)

    def shard_fn(state: TrainState, grads: Any) -> TrainState:
        flag = jnp.ones((), jnp.float32)
        lr = schedule(state.updates_applied)
        t_next = state.updates_applied.astype(jnp.float32) + 1.0
        if z_plan is not None:
            new_params, new_opt, _, applied = _zero1_update(
                state.params, grads, state.momentum, flag, lr, t_next,
                axis, z_plan, opt, grad_specs)
        else:
            mean_grads, num = masked_mean_psum(grads, flag, axis)
            new_params, new_opt = _apply_tree_update(
                opt, state.params, mean_grads, state.momentum, lr,
                t_next, grad_specs)
            applied = (num > 0).astype(jnp.int32)
        return state.replace(params=new_params, momentum=new_opt,
                             step=state.step + 1,
                             updates_applied=state.updates_applied + applied)

    sharded = mesh_lib.shard_map(
        shard_fn, mesh=topo.mesh,
        in_specs=(state_specs, grad_specs),
        out_specs=state_specs)
    return jax.jit(sharded, donate_argnums=0)
