"""The SPMD train step — replaces reference layers L3 (modified sync
optimizer) and L4 (Twisted RPC mesh) with one compiled program.

Where the reference pushes gradients into PS-hosted accumulators,
blocks on per-worker token queues, and lets a chief thread apply the
update (sync_replicas_optimizer_modified.py:237-429), here every
replica computes its gradient, a masked-mean ``lax.psum`` over the ICI
mesh aggregates exactly the contributions the active policy allows,
and every replica applies the identical update to its replicated
parameters. Barriers, tokens, staleness checks and the chief role all
disappear into collective semantics.

The step is built once per (model, config, topology) and jitted with
donated state; everything inside is static-shaped and control flow is
`lax.cond`, so XLA compiles a single fused program per mode.
"""

from __future__ import annotations

import dataclasses
import functools
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core import mesh as mesh_lib
from ..core import prng
from ..core.config import ExperimentConfig
from ..core.log import get_logger
from ..core.mesh import Topology
from ..models.registry import Model, replicated_partition_rules
from ..ops.drop_connect import drop_connect_grads
from ..ops.masked_psum import contribution_scale, masked_mean_psum
from . import policies
from .partition_rules import (RuleAxes, Zero1Plan, match_partition_rules,
                              make_zero1_plan, zero1_init_state, zero1_pack,
                              zero1_state_specs, zero1_unpack)

logger = get_logger("parallel")

# LR schedule: updates_applied -> lr (see train.lr_schedule; kept as a
# plain callable type here to avoid a parallel<->train import cycle).
Schedule = Callable[[jax.Array], jax.Array]


class TrainState(struct.PyTreeNode):
    """Replicated training state (a pure pytree).

    ``updates_applied`` is the reference's global_step — it counts
    *applied updates* (PS applies, src/distributed_train.py:140), while
    ``step`` counts loop iterations; the two differ in interval mode.
    """

    params: Any
    momentum: Any            # momentum buffers or None
    step: jax.Array          # int32, loop iterations
    updates_applied: jax.Array  # int32, ≙ global_step
    root_key: jax.Array
    # interval mode only (None otherwise):
    window_acc: Any          # accumulated sum of per-step masked means
    window_rounds: jax.Array  # float32 rounds accumulated in this window
    wall_ms: jax.Array       # modeled wall clock
    next_apply_ms: jax.Array


def _build_params(model: Model, cfg: ExperimentConfig,
                  topo: Topology | None) -> Any:
    """Init params in the layout the mesh trains (pp-transformed when
    the stage axis is active) — shared by :func:`init_train_state` and
    the abstract-shape path the spec engine maps rules over."""
    params = model.init(jax.random.PRNGKey(cfg.model.init_seed))
    if (topo is not None and topo.mesh.shape[topo.stage_axis] > 1):
        if getattr(model, "pp_transform", None) is None:
            raise ValueError(f"mesh has pipeline stages but model "
                             f"{model.name!r} has no pp_transform")
        if cfg.mesh.pipeline_schedule == "1f1b":
            if getattr(model, "pp_transform_chunked", None) is None:
                raise ValueError(
                    f"pipeline_schedule='1f1b' but model {model.name!r} "
                    "has no pp_transform_chunked")
            # chunk-interleaved layer order: device d's contiguous
            # stage shard holds global chunks {d, S+d, ...}
            params = model.pp_transform_chunked(
                params, topo.mesh.shape[topo.stage_axis],
                cfg.mesh.pipeline_chunks)
        else:
            params = model.pp_transform(params)  # layer-stacked layout
    return params


@functools.lru_cache(maxsize=128)
def _abstract_train_params_cached(model: Model, cfg: ExperimentConfig,
                                  topo: Topology | None) -> Any:
    return jax.eval_shape(lambda: _build_params(model, cfg, topo))


def abstract_train_params(model: Model, cfg: ExperimentConfig,
                          topo: Topology | None) -> Any:
    """Shape/dtype skeleton of the trained param tree (no FLOPs, no
    device buffers) — what the rule engine needs to name leaves.

    Memoized on the (model, cfg, topo) triple: one Trainer build calls
    through here several times (state specs, train step, eval step,
    ZeRO-1 plan) with the same frozen objects, and re-tracing init each
    time is pure waste (~0.1 s per trace). Falls back to a direct trace
    for unhashable inputs."""
    try:
        return _abstract_train_params_cached(model, cfg, topo)
    except TypeError:
        return jax.eval_shape(lambda: _build_params(model, cfg, topo))


def params_partition_specs(model: Model, cfg: ExperimentConfig,
                           topo: Topology, params: Any = None) -> Any:
    """The per-leaf PartitionSpec tree for the trained params, derived
    by mapping the model's declarative rule table
    (``models/registry.py``) over the real param tree with the active
    mesh axes bound — ``parallel/partition_rules.py``. Replaces the
    hand-built spec trees ``state_partition_specs`` used to assemble
    per layout; the models' spec builders remain as the parity oracle
    (tests/test_partition_rules.py)."""
    n_model = topo.mesh.shape[topo.model_axis]
    n_stage = topo.mesh.shape[topo.stage_axis]
    n_expert = topo.mesh.shape[topo.expert_axis]
    if n_model > 1 and getattr(model, "tp_param_specs", None) is None:
        raise ValueError(f"mesh has model_parallelism={n_model} but model "
                         f"{model.name!r} has no tensor-parallel parameter "
                         "specs")
    if n_expert > 1 and (getattr(model, "tp_param_specs", None) is None
                         or not getattr(model, "has_aux", False)):
        raise ValueError(f"mesh has expert_parallelism={n_expert} but model "
                         f"{model.name!r} has no experts to shard")
    if n_stage > 1 and getattr(model, "pp_param_specs", None) is None:
        raise ValueError(f"mesh has pipeline_parallelism={n_stage} but model "
                         f"{model.name!r} has no pipeline parameter specs")
    axes = RuleAxes(
        model=topo.model_axis if n_model > 1 else None,
        expert=topo.expert_axis if n_expert > 1 else None,
        stage=topo.stage_axis if n_stage > 1 else None)
    if model.partition_rules is None and (axes.model or axes.expert
                                          or axes.stage):
        # the replicated fallback table is only safe when nothing needs
        # sharding — silently replicating a TP/PP/EP model's weights
        # would double-count its model-axis psums, the exact failure
        # the rule engine's unmatched-leaf error exists to prevent
        raise ValueError(
            f"model {model.name!r} declares sharded-parallelism support "
            "but no partition_rules table (models/registry.py) — cannot "
            f"derive placements for active axes {axes}")
    rules = (model.partition_rules or replicated_partition_rules)(axes)
    if params is None:
        params = abstract_train_params(model, cfg, topo)
    return match_partition_rules(rules, params)


def zero1_plan_for(model: Model, cfg: ExperimentConfig, topo: Topology,
                   params: Any = None) -> Zero1Plan | None:
    """The ZeRO-1 shard plan when ``parallel.shard_weight_update`` is
    both enabled and applicable, else None. Inapplicable: a replica
    axis of 1 (nothing is redundant), or interval mode (the windowed
    accumulator averages the FULL mean across steps; sharding it too is
    possible but not worth the extra state surface — documented
    fallback, see README Performance)."""
    par = cfg.parallel
    if not par.shard_weight_update:
        return None
    if topo.num_replicas <= 1 or cfg.sync.mode == "interval":
        return None
    if params is None:
        params = abstract_train_params(model, cfg, topo)
    pspecs = params_partition_specs(model, cfg, topo, params=params)
    return make_zero1_plan(params, pspecs, topo.replica_axis,
                           topo.num_replicas,
                           min_leaf_size=par.shard_min_leaf_size)


def state_partition_specs(model: Model, cfg: ExperimentConfig,
                          topo: Topology) -> TrainState:
    """A TrainState-shaped pytree of PartitionSpecs: P() (replicated)
    scalars, per-leaf engine-derived specs for param-shaped subtrees
    (tensor/pipeline/expert placements per the model's rule table), and
    — under ``parallel.shard_weight_update`` — momentum buffers split
    over the replica axis per the ZeRO-1 plan."""
    from jax.sharding import PartitionSpec as P_

    abstract = abstract_train_params(model, cfg, topo)
    pspec = params_partition_specs(model, cfg, topo, params=abstract)
    has_momentum = cfg.optim.momentum > 0.0
    interval = cfg.sync.mode == "interval"
    plan = zero1_plan_for(model, cfg, topo, params=abstract)
    mspec = None
    if has_momentum:
        mspec = (zero1_state_specs(plan, pspec) if plan is not None
                 else pspec)
    return TrainState(
        params=pspec,
        momentum=mspec,
        step=P_(), updates_applied=P_(), root_key=P_(),
        window_acc=pspec if interval else None,
        window_rounds=P_(), wall_ms=P_(), next_apply_ms=P_())


def init_train_state(model: Model, cfg: ExperimentConfig,
                     topo: Topology | None = None) -> TrainState:
    params = _build_params(model, cfg, topo)
    plan = (zero1_plan_for(model, cfg, topo, params=params)
            if topo is not None else None)
    if cfg.optim.momentum > 0.0:
        momentum = (zero1_init_state(params, plan) if plan is not None
                    else jax.tree.map(jnp.zeros_like, params))
    else:
        momentum = None
    interval = cfg.sync.mode == "interval"
    return TrainState(
        params=params,
        momentum=momentum,
        step=jnp.zeros((), jnp.int32),
        updates_applied=jnp.zeros((), jnp.int32),
        root_key=prng.root_key(cfg.train.seed),
        window_acc=jax.tree.map(jnp.zeros_like, params) if interval else None,
        window_rounds=jnp.zeros((), jnp.float32),
        wall_ms=jnp.zeros((), jnp.float32),
        next_apply_ms=jnp.asarray(cfg.sync.interval_ms, jnp.float32),
    )


# ---------------------------------------------------------------------------
# canonical checkpoint layout (ZeRO-1 pack/unpack) + mesh portability
# ---------------------------------------------------------------------------

def world_signature(topo: Topology) -> dict:
    """The world a checkpoint is saved under — JSON-clean, stamped into
    every artifact's ``extra["world"]`` (train/loop.py ``_save``) so a
    restore can tell "same world, graft directly" from "resized world,
    reshard" and name both sides in errors
    (train/checkpoint.py ``WorldSizeMismatchError``). Only axes > 1
    enter the mesh record, so a pure-DP world compares equal however
    many size-1 axes the mesh spells out."""
    return {"num_replicas": int(topo.num_replicas),
            "process_count": int(jax.process_count()),
            "mesh": {ax: int(topo.mesh.shape[ax])
                     for ax in topo.mesh.axis_names
                     if int(topo.mesh.shape[ax]) > 1}}


def restore_for_topology(model: Model, cfg: ExperimentConfig,
                         topo: Topology, train_dir, template_state: TrainState,
                         step: int | None = None,
                         on_event: Callable[[dict], None] | None = None,
                         ) -> tuple[TrainState, dict, int] | None:
    """Mesh-portable restore (ROADMAP item 2, TF-Replicator's
    resource-shape-agnostic replicas): load an artifact saved under ANY
    world size and reshard it for the CURRENT mesh.

    Why this works without migration code:

    * **Params / sharded (tp/pp) state** — checkpoints store logical
      global arrays (the per-host sharded layout reassembles them from
      every saver process's shard file regardless of the reader's
      process count); the caller re-splits them per the NEW spec trees
      by placing the result with ``Topology.device_put_state`` over
      ``state_partition_specs`` — the rule engine derives those from
      the current mesh, not the saver's.
    * **ZeRO-1 optimizer state** — the canonical-layout contract
      unpacks momentum to logical shapes on save, so restore re-derives
      the :class:`~..parallel.partition_rules.Zero1Plan` (padding,
      chunk ownership) from the NEW replica count and repacks; an
      artifact that kept the flat layout (cross-process sharded saves)
      carries a foreign ``pad`` and is re-padded exactly
      (``zero1_pack`` truncates zero padding, never data).
    * **Data cursor** — ``extra["data_iter"]`` carries the lockstep
      ``batches`` coordinate plus the saver's world; the new world's
      ``BatchIterator.restore`` reassigns it so no sample range is
      dropped or double-visited (data/pipeline.py).

    A world change is reported through ``on_event`` as
    ``action: "cross_world_restore"`` naming both worlds — the
    journaled evidence the chaos cross-world resume invariant pairs
    with the supervisor's ``event: "reconfigure"`` license."""
    from ..train import checkpoint as ckpt
    restored = ckpt.restore_checkpoint(train_dir, template_state,
                                       step=step, on_event=on_event)
    if restored is None:
        return None
    state, extra, got_step = restored
    # the plan (padding, chunk ownership) comes from the CURRENT
    # replica count — never the saver's n
    plan = zero1_plan_for(model, cfg, topo)
    state = pack_restored_state(state, plan)
    saved_world = (extra or {}).get("world")
    current = world_signature(topo)
    if isinstance(saved_world, dict) and saved_world != current:
        logger.info("cross-world restore: checkpoint step=%d saved under "
                    "world %s resharded onto %s", got_step, saved_world,
                    current)
        if on_event is not None:
            on_event({"layer": "checkpoint",
                      "action": "cross_world_restore", "step": got_step,
                      "saved_world": saved_world, "new_world": current})
    return state, extra, got_step


def canonical_save_state(state: TrainState,
                         plan: Zero1Plan | None) -> TrainState:
    """The state as checkpoints store it: optimizer buffers in their
    LOGICAL shapes regardless of the in-memory ZeRO-1 layout, so the
    artifact (and its canonical path digest, train/checkpoint.py) is
    byte-stable across ``parallel.shard_weight_update`` settings and a
    sharded run's checkpoint restores onto a replicated config (and
    vice versa) with no migration. Host-side; a no-op without a plan."""
    if plan is None or state.momentum is None:
        return state
    return state.replace(momentum=zero1_unpack(state.momentum, plan))


def pack_restored_state(state: TrainState,
                        plan: Zero1Plan | None) -> TrainState:
    """Inverse of :func:`canonical_save_state` on the restore path:
    fold canonically-saved (logical-shape) momentum back into the
    flattened-padded replica-shard layout the live state uses. Exact —
    padding is zeros, truncation only ever removes padding."""
    if plan is None or state.momentum is None:
        return state
    return state.replace(momentum=zero1_pack(state.momentum, plan))


def _sgd(params: Any, grads: Any, momentum_bufs: Any, lr: jax.Array,
         momentum: float) -> tuple[Any, Any]:
    """Plain SGD (≙ tf.train.GradientDescentOptimizer,
    src/distributed_train.py:176), with optional heavyball momentum."""
    if momentum_bufs is None:
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, None
    new_bufs = jax.tree.map(lambda b, g: momentum * b + g, momentum_bufs, grads)
    new_params = jax.tree.map(lambda p, b: p - lr * b, params, new_bufs)
    return new_params, new_bufs


def _pad_flat(x: jax.Array, lp) -> jax.Array:
    """Flatten a logical leaf and zero-pad it to the plan's ``pad``
    length (the even-split layout; padding math lives in the engine,
    partition_rules.LeafShardPlan)."""
    flat = x.reshape(-1)
    if lp.pad == lp.size:
        return flat
    return jnp.concatenate(
        [flat, jnp.zeros((lp.pad - lp.size,), flat.dtype)])


def _zero1_update(params: Any, grads: Any, momentum_bufs: Any,
                  flag: jax.Array, lr: jax.Array, momentum: float,
                  axis: str, plan: Zero1Plan
                  ) -> tuple[Any, Any, jax.Array, jax.Array]:
    """The ZeRO-1 weight-update discipline (arXiv:2004.13336), inside
    shard_map: per sharded leaf, the masked gradients are
    REDUCE-SCATTERED over the replica axis (each replica receives the
    summed 1/n slice — the full mean gradient is never materialized),
    the optimizer state and param slice are updated locally, and the
    fresh param slices are allgathered back to the replicated layout
    the forward pass consumes. Fallback leaves (tensor-parallel
    placements, leaves below the shard floor) take the classic
    replicated psum + full update.

    Masking semantics match the replicated path exactly: gradients are
    pre-scaled by ``flag / max(psum(flag), 1)`` so the scattered sum IS
    the masked mean, and an all-masked step is a true no-op (plain SGD
    scales lr by the applied flag; momentum decay is select-guarded).

    Returns ``(new_params, new_bufs, num_contributors, applied)``.
    """
    scale, num = contribution_scale(flag, axis)
    applied = (num > 0).astype(jnp.int32)
    me = lax.axis_index(axis)

    p_leaves, treedef = jax.tree.flatten(params)
    g_leaves = treedef.flatten_up_to(grads)
    lp_leaves = treedef.flatten_up_to(plan.leaf_plans)
    b_leaves = (treedef.flatten_up_to(momentum_bufs)
                if momentum_bufs is not None else [None] * len(p_leaves))
    # plain SGD: lr·0 is exact, so scaling lr by the applied flag IS
    # the all-masked no-op (same trick as the replicated path)
    lr_plain = lr * applied.astype(jnp.float32)

    new_p, new_b = [], []
    for p, g, b, lp in zip(p_leaves, g_leaves, b_leaves, lp_leaves):
        gm = g * scale.astype(g.dtype)
        if lp.sharded:
            # reduce-scatter: [pad] masked grads → this replica's
            # summed [chunk] slice (already the mean via the pre-scale)
            gsh = lax.psum_scatter(_pad_flat(gm, lp), axis,
                                   scatter_dimension=0, tiled=True)
            psh = lax.dynamic_slice(_pad_flat(p, lp), (me * lp.chunk,),
                                    (lp.chunk,))
            if b is None:
                nps, nbs = psh - lr_plain * gsh, None
            else:
                nbs = momentum * b + gsh
                nps = psh - lr * nbs
                # momentum decays even on zero grads: true no-op needs
                # the select (chunk-sized — 1/n of the replicated cost)
                nps = jnp.where(applied > 0, nps, psh)
                nbs = jnp.where(applied > 0, nbs, b)
            full = mesh_lib.gather_chunks_replicated(
                nps, axis, lp.pad, me * lp.chunk)
            new_p.append(full[:lp.size].reshape(lp.shape))
            new_b.append(nbs)
        else:
            mean = lax.psum(gm, axis)
            if b is None:
                new_p.append(p - lr_plain * mean)
                new_b.append(None)
            else:
                nb = momentum * b + mean
                npv = p - lr * nb
                new_p.append(jnp.where(applied > 0, npv, p))
                new_b.append(jnp.where(applied > 0, nb, b))
    params_out = jax.tree.unflatten(treedef, new_p)
    bufs_out = (jax.tree.unflatten(treedef, new_b)
                if momentum_bufs is not None else None)
    return params_out, bufs_out, num, applied


def _gather_replicated(x: jax.Array, axis: str, n: int) -> jax.Array:
    """All-gather a per-replica scalar into a REPLICATED [n] vector.

    Expressed as a one-hot psum instead of ``lax.all_gather`` because
    psum's output is statically known to be replicated over ``axis`` —
    so it can leave shard_map under an out_spec of P() and every host
    of a multi-host run holds the full vector (an all_gather result
    stays marked device-varying and would need a sharded out_spec,
    which non-addressable processes cannot materialize)."""
    me = lax.axis_index(axis)
    onehot = (jnp.arange(n) == me).astype(x.dtype)
    return lax.psum(onehot * x, axis)


def build_train_step(model: Model, cfg: ExperimentConfig, topo: Topology,
                     schedule: Schedule) -> Callable[[TrainState, dict], tuple[TrainState, dict]]:
    """Compile the per-step SPMD training function.

    Returns ``step_fn(state, batch, measured_ms=None) -> (state, metrics)``
    where ``batch = {"image": [B, ...], "label": [B]}`` is globally
    batched and sharded over the replica axis, and state/metrics are
    replicated. ``measured_ms`` is an optional per-replica [n] vector of
    real measured step times (ms), sharded over the replica axis: each
    host feeds the entries for its own replicas (Topology.
    device_put_measured), so quorum/timeout/interval policies select on
    genuine per-replica speed — ≙ the reference's measured per-worker
    CDF semantics (src/timeout_manager.py:48-61) without the RPC mesh.
    Defaults to zeros (pure synthetic-profile timing).
    """
    axis = topo.replica_axis
    n = topo.num_replicas
    sync = cfg.sync
    mode = sync.mode
    if mode not in ("sync", "quorum", "timeout", "interval", "cdf"):
        raise ValueError(f"unknown sync mode {mode!r}")
    k = policies.resolve_aggregate_k(sync, n)
    momentum = cfg.optim.momentum

    # Sequence parallelism: when the mesh spends devices on the seq
    # axis, the model must provide a sequence-sharded apply (the
    # transformer does, via ring/all-to-all attention). Each shard then
    # computes a PARTIAL loss/gradient over its token slice; psum over
    # the seq axis reassembles the exact full-sequence gradient before
    # the replica-axis aggregation disciplines see it.
    #
    # Tensor parallelism: when the mesh's model axis is >1, params are
    # placed per the model's TP partition specs; each rank holds its
    # head/MLP column shard, activations stay replicated over the axis
    # (psums inside apply), and each rank's param gradients are its own
    # shard's — no model-axis reduction of gradients is needed.
    seq_ax = topo.seq_axis
    n_seq = topo.mesh.shape[seq_ax]
    model_ax = topo.model_axis
    n_model = topo.mesh.shape[model_ax]
    # Pipeline parallelism: layers sharded over the stage axis, batch
    # microbatched through the activation pipeline (ops/pipeline.py).
    # Stage-sharded param grads stay local; replicated leaves (embed,
    # norms) get their stage-psum from the AD transpose of replication.
    stage_ax = topo.stage_axis
    n_stage = topo.mesh.shape[stage_ax]
    # Expert parallelism: experts sharded over the expert axis; composes
    # with TP (model axis splits heads + every expert's hidden dim).
    expert_ax = topo.expert_axis
    n_expert = topo.mesh.shape[expert_ax]
    if ((n_seq > 1 or n_model > 1 or n_expert > 1) and n_stage == 1
            and getattr(model, "sharded_apply_factory", None) is None):
        raise ValueError(
            f"mesh has seq_parallelism={n_seq} / model_parallelism="
            f"{n_model} / expert_parallelism={n_expert} but model "
            f"{model.name!r} supports none of them "
            "(no sharded_apply_factory)")
    pp_schedule = cfg.mesh.pipeline_schedule
    if pp_schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline_schedule {pp_schedule!r}")
    pp_1f1b_grads_fn = None
    if n_stage > 1:
        if getattr(model, "pp_apply_factory", None) is None:
            raise ValueError(f"mesh has pipeline_parallelism={n_stage} but "
                             f"model {model.name!r} has no pipeline apply")
        if pp_schedule == "1f1b":
            # fused interleaved schedule (ops/pipeline.py): explicit
            # forward/backward chunk-works in one scan — built below
            # instead of value_and_grad. TP/SP/EP collectives inside
            # the chunk bodies execute inside the engine's
            # stage-varying switch branches; that is safe because they
            # reduce over NON-stage axes whose participant groups share
            # a stage coordinate and hence a branch (ops/pipeline.py).
            if getattr(model, "pp_1f1b_grads_factory", None) is None:
                raise ValueError(f"model {model.name!r} has no 1f1b "
                                 "pipeline support")
            pp_1f1b_grads_fn = model.pp_1f1b_grads_factory(
                stage_ax, cfg.mesh.pipeline_microbatches,
                cfg.mesh.pipeline_chunks,
                model_ax if n_model > 1 else None,
                seq_ax if n_seq > 1 else None,
                expert_ax if n_expert > 1 else None)
            pp_apply = None
        else:
            # PP outermost; TP (model axis) inside each stage; SP (seq
            # axis) through the stage blocks' sharded attention; EP
            # (expert axis) through the blocks' grouped MoE dispatch —
            # every device runs the same tick schedule so attention and
            # expert collectives stay lockstep inside the pipeline scan
            pp_apply = model.pp_apply_factory(
                stage_ax, cfg.mesh.pipeline_microbatches,
                model_ax if n_model > 1 else None,
                seq_ax if n_seq > 1 else None,
                expert_ax if n_expert > 1 else None)
    else:
        pp_apply = None
    sharded_apply = (model.sharded_apply_factory(
        seq_ax if n_seq > 1 else None, model_ax if n_model > 1 else None,
        expert_ax if n_expert > 1 else None)
        if ((n_seq > 1 or n_model > 1 or n_expert > 1)
            and pp_apply is None and pp_1f1b_grads_fn is None)
        else None)
    # The SP/PP loss paths do not thread a dropout key; refuse loudly
    # instead of silently training a dropout model without dropout.
    if ((sharded_apply is not None or pp_apply is not None
            or pp_1f1b_grads_fn is not None)
            and getattr(model, "uses_dropout", False)):
        raise ValueError(
            f"model {model.name!r} uses dropout, but the sharded "
            "(SP/TP/PP) loss paths do not thread a dropout key; set "
            "model.dropout_rate=0 or run it data-parallel only")
    # raw per-shard grads are needed w.r.t. the axes the masks/explicit
    # psums manage; the model axis stays as-is (sharded params are
    # already device-varying there)
    grad_axes = (axis, seq_ax) if n_seq > 1 else (axis,)
    state_specs = state_partition_specs(model, cfg, topo)
    # ZeRO-1 (parallel.shard_weight_update): reduce-scatter grads,
    # update only this replica's param/momentum slice, allgather fresh
    # params — per the engine's shard plan, which state_partition_specs
    # and init_train_state derived the state layout from.
    z_plan = zero1_plan_for(model, cfg, topo)
    if cfg.parallel.shard_weight_update and z_plan is None:
        logger.warning(
            "parallel.shard_weight_update=true is a no-op here (%s); "
            "running the replicated update",
            "replica axis is 1" if n <= 1 else
            f"sync.mode={mode!r} keeps the full windowed accumulator")

    has_aux = getattr(model, "has_aux", False)
    aux_w = getattr(model, "aux_weight", 0.0)

    def local_loss(params, batch, dropout_key):
        if has_aux:
            logits, aux = model.apply(params, batch["image"], train=True,
                                      dropout_key=dropout_key,
                                      return_aux=True)
            return model.loss(logits, batch["label"]) + aux_w * aux, logits
        logits = model.apply(params, batch["image"], train=True,
                             dropout_key=dropout_key)
        return model.loss(logits, batch["label"]), logits

    def local_loss_pp(params, batch, dropout_key):
        del dropout_key
        if has_aux:  # MoE: per-group aux, tick-accumulated (apply_pp)
            logits, aux = pp_apply(params, batch["image"], return_aux=True)
            return model.loss(logits, batch["label"]) + aux_w * aux, logits
        logits = pp_apply(params, batch["image"])  # stage-replicated
        return model.loss(logits, batch["label"]), logits

    def make_sp_loss(apply_fn, with_aux):
        """Per-(replica, seq-shard) partial next-token loss over any
        seq-sharded apply (the DP×SP×TP path, or the pipeline apply for
        PP×SP).

        Targets are inputs shifted left by one GLOBAL position, so the
        target of a shard's last token lives on the next shard — one
        ppermute fetches each neighbor's first column. The global last
        position has no target (weight 0), matching the dense
        ``transformer.loss_fn`` exactly: partial sums are normalized by
        the global valid-token count so psum(partials) == dense loss.
        """
        def sp_loss(params, batch, dropout_key):
            del dropout_key
            tokens = batch["image"]
            labels = batch["label"]
            b, s_loc = tokens.shape
            me_s = lax.axis_index(seq_ax)
            positions = me_s * s_loc + jnp.arange(s_loc)
            if with_aux:  # MoE: EP-only, SP×EP, or PP×SP×EP
                logits, aux = apply_fn(params, tokens, positions,
                                       return_aux=True)
            else:
                logits = apply_fn(params, tokens, positions)  # [b, s_loc, V]
                aux = 0.0

            # shard j receives shard (j+1)'s first target column
            perm = [((j + 1) % n_seq, j) for j in range(n_seq)]
            nxt = lax.ppermute(labels[:, :1], seq_ax, perm)
            tgt = jnp.concatenate([labels[:, 1:], nxt], axis=1).astype(jnp.int32)

            from ..models.transformer import sp_partial_token_loss
            s_global = s_loc * n_seq
            # total = this replica's global token count; the shared
            # kernel keeps this path and the 1F1B seed head identical
            loss_part, acc_part = sp_partial_token_loss(
                logits, tgt, positions, s_global, b * (s_global - 1))
            # aux is already the full-token value on every seq shard
            # (moe_ffn pmeans its stats over the stats_axes), so the
            # caller's psum over the seq axis would count it n_seq
            # times — pre-divide so the psum reassembles exactly one.
            return loss_part + aux_w * aux / n_seq, acc_part
        return sp_loss

    local_loss_sp = (make_sp_loss(sharded_apply, has_aux)
                     if sharded_apply is not None else
                     make_sp_loss(pp_apply, has_aux)
                     if (pp_apply is not None and n_seq > 1) else None)

    def shard_fn(state: TrainState, batch: dict,
                 measured_ms: jax.Array) -> tuple[TrainState, dict]:
        me = lax.axis_index(axis)
        step = state.step
        my_measured_ms = measured_ms[0]  # this replica's [1]-shard

        # --- local forward+backward (one pass: the reference's second
        # forward per step, src/distributed_train.py:332-335, is a
        # documented quirk we do not replicate) -----------------------
        #
        # Params are replicated over the mesh; differentiating w.r.t. a
        # *replicated* value inside shard_map makes AD insert the
        # cross-axis psum itself (transpose of the broadcast). We need
        # the raw per-shard gradient — masks must apply BEFORE the
        # replica aggregation, and the seq-axis psum must be explicit —
        # so cast params to varying over every grad axis first.
        dkey = prng.replica_key(state.root_key, "dropout", step, me)
        local_params = jax.tree.map(
            lambda x: lax.pcast(x, grad_axes, to="varying"), state.params)
        if pp_1f1b_grads_fn is not None:
            # fused 1F1B: the engine computes loss, accuracy and grads
            # in one interleaved scan — no outer value_and_grad. Under
            # SP the engine returns per-seq-shard partials; psum
            # reassembles the exact dense values (same as the SP
            # branch below).
            loss, train_acc, grads = pp_1f1b_grads_fn(
                local_params, batch["image"], batch["label"])
            if n_seq > 1:
                loss = lax.psum(loss, seq_ax)
                train_acc = lax.psum(train_acc, seq_ax)
                grads = jax.tree.map(lambda g: lax.psum(g, seq_ax), grads)
        elif local_loss_sp is not None:  # DP×SP×TP, or PP×SP
            (loss_p, acc_p), grads = jax.value_and_grad(
                local_loss_sp, has_aux=True)(local_params, batch, dkey)
            # reassemble the full-sequence gradient / metrics
            loss = lax.psum(loss_p, seq_ax)
            train_acc = lax.psum(acc_p, seq_ax)
            grads = jax.tree.map(lambda g: lax.psum(g, seq_ax), grads)
        elif pp_apply is not None:
            (loss, logits), grads = jax.value_and_grad(
                local_loss_pp, has_aux=True)(local_params, batch, dkey)
            train_acc = model.accuracy(logits, batch["label"])
        else:
            (loss, logits), grads = jax.value_and_grad(
                local_loss, has_aux=True)(local_params, batch, dkey)
            train_acc = model.accuracy(logits, batch["label"])

        # --- per-worker drop-connect before aggregation
        # (src/distributed_train.py:194-196) --------------------------
        if sync.drop_connect:
            dckey = prng.replica_key(state.root_key, "drop_connect", step, me)
            grads = drop_connect_grads(grads, dckey, sync.drop_connect_probability)

        # --- step-time model & contribution mask ---------------------
        t_ms = policies.sample_step_time_ms(sync, state.root_key, step, me,
                                            my_measured_ms)
        if mode in ("sync", "cdf"):
            flag = jnp.ones((), jnp.float32)
        elif mode == "quorum":
            flag = policies.quorum_flag(t_ms, k, axis)
        elif mode == "timeout":
            flag = policies.timeout_flag(t_ms, sync.timeout_ms)
        else:  # interval: stale if slower than a whole window
            flag = policies.timeout_flag(t_ms, sync.interval_ms)

        # --- apply discipline ----------------------------------------
        if mode == "interval":
            mean_grads, num_contrib = masked_mean_psum(grads, flag, axis)
            new_state, applied = _interval_apply(state, mean_grads, t_ms)
        elif z_plan is not None:
            # ZeRO-1: no full mean gradient is ever built — the
            # reduce-scatter inside _zero1_update hands each replica
            # its slice of it directly
            lr = schedule(state.updates_applied)
            new_params, new_bufs, num_contrib, applied = _zero1_update(
                state.params, grads, state.momentum, flag, lr, momentum,
                axis, z_plan)
            new_state = state.replace(
                params=new_params, momentum=new_bufs,
                updates_applied=state.updates_applied + applied)
        else:
            mean_grads, num_contrib = masked_mean_psum(grads, flag, axis)
            lr = schedule(state.updates_applied)
            applied = (num_contrib > 0).astype(jnp.int32)
            # If every replica was masked out (possible under timeout),
            # the mean is zero and the update must be a true no-op.
            if state.momentum is None:
                # plain SGD: lr·0 is exact, so scaling the scalar lr by
                # the applied flag IS the no-op — no full-size
                # per-parameter select pass (a measured throughput tax
                # on small steps, bench_mode_overhead)
                new_params, new_bufs = _sgd(
                    state.params, mean_grads, None,
                    lr * applied.astype(jnp.float32), momentum)
            else:
                new_params, new_bufs = _sgd(state.params, mean_grads,
                                            state.momentum, lr, momentum)
                # momentum buffers decay even on zero gradients, so a
                # true no-op needs the select
                new_params = jax.tree.map(
                    lambda new, old: jnp.where(applied > 0, new, old),
                    new_params, state.params)
                new_bufs = jax.tree.map(
                    lambda new, old: jnp.where(applied > 0, new, old),
                    new_bufs, state.momentum)
            new_state = state.replace(
                params=new_params, momentum=new_bufs,
                updates_applied=state.updates_applied + applied)

        new_state = new_state.replace(step=step + 1)

        # --- metrics: everything comes out REPLICATED (scalars via
        # pmean/psum, per-replica series via all_gather) so every host
        # holds the full [n] timing vector — a multi-host process can
        # materialize its own copy without touching non-addressable
        # shards (≙ the CDF timing gossip, src/timeout_manager.py:48-61,
        # with no RPC mesh at all) ------------------------------------
        metrics = {
            "loss": lax.pmean(loss, axis),
            "train_acc": lax.pmean(train_acc, axis),
            "lr": schedule(state.updates_applied),
            "num_contributors": num_contrib,
            "updates_applied": new_state.updates_applied,
            "step_times_ms": _gather_replicated(t_ms, axis, n),  # [n]
            "flags": _gather_replicated(flag, axis, n),          # [n]
            "applied": applied,
        }
        return new_state, metrics

    def _interval_apply(state: TrainState, mean_grads: Any,
                        t_ms: jax.Array) -> tuple[TrainState, jax.Array]:
        """Wall-clock-windowed aggregation (≙ the chief's recurring
        Timer running take_grad(1)-average-of-arrived,
        sync_replicas_optimizer_modified.py:208-215,371-373,392-393).

        A wall-clock-async update is not expressible inside one SPMD
        program (SURVEY §7), so the window is re-expressed over the
        lockstep loop: each step's masked mean joins a window
        accumulator; the modeled wall clock advances by the mean
        replica pace; when it crosses the window boundary the
        accumulated average is applied and the window resets.
        """
        acc = jax.tree.map(lambda a, g: a + g, state.window_acc, mean_grads)
        rounds = state.window_rounds + 1.0
        wall = state.wall_ms + lax.pmean(t_ms, axis)
        fire = wall >= state.next_apply_ms

        lr = schedule(state.updates_applied)
        window_mean = jax.tree.map(lambda a: a / rounds, acc)
        applied_params, applied_bufs = _sgd(state.params, window_mean,
                                            state.momentum, lr, momentum)

        def pick(new, old):
            return jax.tree.map(lambda a, b: jnp.where(fire, a, b), new, old)

        new_params = pick(applied_params, state.params)
        new_bufs = (None if state.momentum is None
                    else pick(applied_bufs, state.momentum))
        zeros = jax.tree.map(jnp.zeros_like, acc)
        new_acc = pick(zeros, acc)
        new_rounds = jnp.where(fire, 0.0, rounds)
        # Reschedule relative to *now*, as the reference timer does by
        # re-arming after each run (skipped windows are not replayed).
        next_apply = jnp.where(fire, wall + sync.interval_ms, state.next_apply_ms)
        applied = fire.astype(jnp.int32)
        return state.replace(
            params=new_params, momentum=new_bufs, window_acc=new_acc,
            window_rounds=new_rounds, wall_ms=wall, next_apply_ms=next_apply,
            updates_applied=state.updates_applied + applied), applied

    mesh = topo.mesh
    metrics_specs = {
        "loss": P(), "train_acc": P(), "lr": P(), "num_contributors": P(),
        "updates_applied": P(), "step_times_ms": P(), "flags": P(),
        "applied": P(),
    }
    batch_spec = P(axis, seq_ax) if n_seq > 1 else P(axis)
    sharded = mesh_lib.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(state_specs, batch_spec, P(axis)),
        out_specs=(state_specs, metrics_specs))
    jitted = jax.jit(sharded, donate_argnums=0)

    zeros_ms: list[jax.Array] = []  # lazily built + cached default
    # AOT fast path (parallel/aot.py): precompile() fills this with the
    # ahead-of-time compiled executable + the argument signature it was
    # lowered for; step_fn then dispatches matching concrete calls
    # through it — the first training step after a precompile (or a
    # warm-standby promotion) never waits on jit's compile path.
    aot_box: dict[str, Any] = {}

    def _default_measured() -> jax.Array:
        if not zeros_ms:
            zeros_ms.append(topo.zeros_measured())
        return zeros_ms[0]

    def _args_sig(args):
        leaves, treedef = jax.tree.flatten(args)
        return (treedef,
                tuple((getattr(x, "shape", ()), getattr(x, "dtype", None))
                      for x in leaves))

    def step_fn(state: TrainState, batch: dict,
                measured_ms: jax.Array | None = None):
        if measured_ms is None:
            measured_ms = _default_measured()
        exe = aot_box.get("exe")
        if exe is not None:
            # one flatten covers both guards: tracers ANYWHERE in the
            # args (a caller jitting over step_fn — e.g. bench's scanned
            # chunks, or a jit closing over state but tracing the batch)
            # must take the traceable jit path, and a different
            # signature (a test swapping batch shapes) simply compiles
            # through jit as before. Compared leafwise with early exit —
            # no per-step sig allocation on this hot path.
            leaves, treedef = jax.tree.flatten((state, batch, measured_ms))
            sig_td, sig_leaves = aot_box["sig"]
            if (treedef == sig_td and len(leaves) == len(sig_leaves)
                    and not any(isinstance(x, jax.core.Tracer)
                                for x in leaves)
                    and all(getattr(x, "shape", ()) == s
                            and getattr(x, "dtype", None) == d
                            for x, (s, d) in zip(leaves, sig_leaves))):
                return exe(state, batch, measured_ms)
        return jitted(state, batch, measured_ms)

    def precompile(state: TrainState, batch: dict,
                   measured_ms: jax.Array | None = None,
                   cache_dir=None, cache_key: str | None = None,
                   trust_cross_process: bool = False) -> dict[str, Any]:
        """AOT-compile the step for these exact avals (no execution, no
        donation — lowering only reads shapes) and arm the fast path.
        With a cache_dir+key, the executable round-trips the disk cache
        where the platform supports it AND the jax release is outside
        the cross-process corruption quarantine (parallel/aot.py)."""
        from . import aot as aot_lib
        if measured_ms is None:
            measured_ms = _default_measured()
        compiled, info = aot_lib.aot_compile(
            jitted, (state, batch, measured_ms),
            cache_dir=cache_dir, key=cache_key,
            trust_cross_process=trust_cross_process)
        aot_box["exe"] = compiled
        aot_box["sig"] = _args_sig((state, batch, measured_ms))
        return info

    step_fn.precompile = precompile
    step_fn.jitted = jitted
    return step_fn


def build_eval_step(model: Model, cfg: ExperimentConfig, topo: Topology):
    """Sharded inference step: weighted accuracy/loss so padded
    examples (batch not divisible by replica count) don't bias metrics.

    ``batch = {"image", "label", "weight"}``; returns summed
    (correct, weighted_loss, weight) — caller divides.
    """
    axis = topo.replica_axis
    model_ax = topo.model_axis
    n_model = topo.mesh.shape[model_ax]
    n_stage = topo.mesh.shape[topo.stage_axis]
    n_expert = topo.mesh.shape[topo.expert_axis]
    if n_stage > 1:
        # pipeline-parallel params: stacked layout. Eval pipelines at
        # the largest microbatch count that divides the per-replica
        # eval rows (capped by the training cadence) — M=1 would run
        # the stages fully serialized, an S× eval slowdown measured in
        # the tens of minutes on deep CPU-mesh evals.
        if getattr(model, "pp_apply_factory", None) is None:
            raise ValueError(f"mesh has pipeline_parallelism={n_stage} but "
                             f"model {model.name!r} has no pipeline apply")
        tp_ax = model_ax if n_model > 1 else None
        ep_ax = topo.expert_axis if n_expert > 1 else None
        pspec: Any = params_partition_specs(model, cfg, topo)
        if (cfg.mesh.pipeline_schedule == "1f1b"
                and getattr(model, "pp_1f1b_apply_factory", None) is None):
            # mirror the train-path guard: fail with a clear error at
            # build time instead of an opaque trace-time NoneType call
            raise ValueError(f"model {model.name!r} has no 1f1b "
                             "pipeline support")
        cap = max(1, cfg.mesh.pipeline_microbatches)

        def run(params, images):
            # per-replica rows are static at trace time (eval batches
            # are padded to a fixed shape); pipeline at the largest
            # microbatch count ≤ the training cadence that divides
            # them. MoE included: token groups nest inside sequence
            # rows (ops/moe.py), so routing capacity and metrics are
            # identical for every microbatch split — the round-4 M=1
            # force is gone (tests pin M-invariance).
            b = images.shape[0]
            m_eval = max(m for m in range(1, cap + 1) if b % m == 0)
            if cfg.mesh.pipeline_schedule == "1f1b":
                apply_fn = model.pp_1f1b_apply_factory(
                    topo.stage_axis, m_eval, cfg.mesh.pipeline_chunks,
                    tp_ax, ep_ax)
            else:
                apply_fn = model.pp_apply_factory(topo.stage_axis, m_eval,
                                                  tp_ax, None, ep_ax)
            return apply_fn(params, images)
    elif n_model > 1 or n_expert > 1:
        # tensor-/expert-parallel params: sharded apply (full sequence
        # per device — eval batches are not seq-sharded), sharded in_spec
        if (getattr(model, "tp_param_specs", None) is None
                or getattr(model, "sharded_apply_factory", None) is None):
            raise ValueError(f"mesh has model_parallelism={n_model} / "
                             f"expert_parallelism={n_expert} but model "
                             f"{model.name!r} is not tensor-/expert-parallel "
                             "capable")
        tp_ax = model_ax if n_model > 1 else None
        ep_ax = topo.expert_axis if n_expert > 1 else None
        pspec: Any = params_partition_specs(model, cfg, topo)
        tp_apply = model.sharded_apply_factory(None, tp_ax, ep_ax)

        def run(params, images):
            return tp_apply(params, images, None)
    else:
        # engine-derived per-leaf tree (all P() on a pure-DP mesh) —
        # same derivation as the train step, one source of truth
        pspec = params_partition_specs(model, cfg, topo)

        def run(params, images):
            return model.apply(params, images, train=False)

    def shard_fn(params, batch):
        logits = run(params, batch["image"])
        correct, loss_sum, weight = model.eval_metrics(
            logits, batch["label"], batch["weight"])
        return (lax.psum(correct, axis), lax.psum(loss_sum, axis),
                lax.psum(weight, axis))

    sharded = mesh_lib.shard_map(
        shard_fn, mesh=topo.mesh,
        in_specs=(pspec, P(axis)),
        out_specs=(P(), P(), P()))
    return jax.jit(sharded)


def build_weight_update_step(model: Model, cfg: ExperimentConfig,
                             topo: Topology, schedule: Schedule):
    """Jitted ``(state, grads) -> state`` applying ONLY the gradient
    aggregation + weight update — no forward/backward — under the
    configured discipline (replicated, or ZeRO-1 when
    ``parallel.shard_weight_update`` applies).

    This isolates the exact region the ZeRO-1 paper optimizes so the
    ``weight_update_sharding`` bench case (bench.py) can time it and
    meter its per-chip optimizer-state bytes without the model compute
    drowning the signal. ``grads`` is a params-shaped pytree placed per
    ``params_partition_specs`` (replicated on a pure-DP mesh); its
    values only feed the update, so a bench may pass any tree of the
    right shapes.
    """
    axis = topo.replica_axis
    momentum = cfg.optim.momentum
    if cfg.sync.mode == "interval":
        raise ValueError("build_weight_update_step models the per-step "
                         "apply disciplines; interval mode applies on a "
                         "wall-clock window (use build_train_step)")
    state_specs = state_partition_specs(model, cfg, topo)
    grad_specs = params_partition_specs(model, cfg, topo)
    z_plan = zero1_plan_for(model, cfg, topo)

    def shard_fn(state: TrainState, grads: Any) -> TrainState:
        flag = jnp.ones((), jnp.float32)
        lr = schedule(state.updates_applied)
        if z_plan is not None:
            new_params, new_bufs, _, applied = _zero1_update(
                state.params, grads, state.momentum, flag, lr, momentum,
                axis, z_plan)
        else:
            mean_grads, num = masked_mean_psum(grads, flag, axis)
            new_params, new_bufs = _sgd(state.params, mean_grads,
                                        state.momentum, lr, momentum)
            applied = (num > 0).astype(jnp.int32)
        return state.replace(params=new_params, momentum=new_bufs,
                             step=state.step + 1,
                             updates_applied=state.updates_applied + applied)

    sharded = mesh_lib.shard_map(
        shard_fn, mesh=topo.mesh,
        in_specs=(state_specs, grad_specs),
        out_specs=state_specs)
    return jax.jit(sharded, donate_argnums=0)
