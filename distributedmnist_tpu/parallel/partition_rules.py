"""Declarative regex→PartitionSpec rules + the ZeRO-1 shard plan.

Two jobs, one module:

1. **The rule engine** (:func:`match_partition_rules`): map an ordered
   list of ``("regex-on-param-path", PartitionSpec)`` rules over a
   param pytree and produce the per-leaf spec tree. First match wins;
   a leaf no rule covers is an explicit :class:`UnmatchedLeafError`
   (silent replication of a tensor someone meant to shard is exactly
   the bug a declarative table exists to prevent). Leaf paths are the
   ``/``-joined pytree keys — ``blocks/0/wqkv`` in the per-layer-list
   layout, ``blocks/wqkv`` in the stacked (scan/pipeline) layout — so
   one table with both spellings covers both layouts. This replaces
   the hand-built spec trees the models used to assemble shape-by-shape
   (``transformer.param_partition_specs`` et al. remain as the parity
   oracle; ``models/registry.py`` holds the per-model rule tables).

2. **The ZeRO-1 shard plan** (:func:`make_zero1_plan`): given the param
   tree and its spec tree, decide per leaf how the optimizer state and
   the weight update shard across the ``replica`` axis (arXiv:
   2004.13336). A leaf shards when it is replicated across every
   non-replica axis and large enough to split; its flattened length is
   padded up to a multiple of the replica count (``pad = ceil(size/n)·n``)
   so uneven leaves shard evenly — the padding lives HERE, in the
   engine, and every consumer (spec derivation, state init, the update
   kernel, checkpoint pack/unpack) reads the same
   :class:`LeafShardPlan`. Leaves smaller than the replica count (or a
   configured floor), and leaves already sharded over a
   model/stage/expert axis, fall back to their param placement —
   replicated across replicas, exactly the pre-ZeRO behavior.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax.sharding import PartitionSpec

# One rule: (regex matched against the "/"-joined leaf path via
# re.search, PartitionSpec to assign). Ordered; first match wins.
Rule = tuple[str, PartitionSpec]


class UnmatchedLeafError(ValueError):
    """A param leaf no partition rule covers. Deliberately loud: an
    incomplete table must fail at build time, not silently replicate."""


@dataclasses.dataclass(frozen=True)
class RuleAxes:
    """The mesh axes a rule table may reference; ``None`` = that form
    of parallelism is inactive and the table should leave those dims
    unsharded (PartitionSpec treats None entries as replicated)."""

    model: str | None = None
    expert: str | None = None
    stage: str | None = None


def _key_name(k: Any) -> str:
    # jax key-path entries: DictKey(.key), SequenceKey(.idx),
    # GetAttrKey(.name), FlattenedIndexKey(.key)
    for attr in ("key", "idx", "name"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def tree_path_names(tree: Any) -> list[str]:
    """The "/"-joined leaf paths of ``tree``, in flatten order — the
    names :func:`match_partition_rules` matches rules against."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return ["/".join(_key_name(k) for k in path) for path, _ in flat]


def _leaf_size(leaf: Any) -> int:
    shape = tuple(getattr(leaf, "shape", ()))
    return int(np.prod(shape)) if shape else 1


def match_partition_rules(rules: Sequence[Rule], tree: Any) -> Any:
    """Map ordered ``(regex, PartitionSpec)`` rules over ``tree``.

    Returns a tree of the same structure with a PartitionSpec per leaf.
    Scalar / single-element leaves are never partitioned (always P(),
    before any rule is consulted — the SNIPPETS.md [1] idiom). Every
    other leaf takes the spec of the FIRST rule whose regex
    ``re.search``-matches its path; a leaf with no matching rule raises
    :class:`UnmatchedLeafError` naming the path and the table.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    specs = []
    for path, leaf in flat:
        name = "/".join(_key_name(k) for k in path)
        if _leaf_size(leaf) <= 1:
            specs.append(PartitionSpec())  # don't partition scalars
            continue
        for pat, spec in compiled:
            if pat.search(name) is not None:
                specs.append(spec)
                break
        else:
            raise UnmatchedLeafError(
                f"no partition rule matches param leaf {name!r} "
                f"(shape {tuple(getattr(leaf, 'shape', ()))}); rules: "
                f"{[pat for pat, _ in rules]}")
    return jax.tree_util.tree_unflatten(treedef, specs)


def spec_is_replicated(spec: PartitionSpec) -> bool:
    """True when ``spec`` pins no dim to any mesh axis."""
    return all(entry is None for entry in tuple(spec))


# ---------------------------------------------------------------------------
# ZeRO-1 shard plan
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class LeafShardPlan:
    """Per-leaf ZeRO-1 decision. ``sharded`` leaves live flattened and
    zero-padded to ``pad = chunk * n`` elements, split into one
    ``chunk``-length slice per replica; fallback leaves keep their
    logical ``shape`` and param placement. NOT a pytree node — whole
    plans travel as leaves through ``jax.tree.map``."""

    sharded: bool
    size: int          # logical element count
    pad: int           # padded flattened length (chunk * n)
    chunk: int         # per-replica slice length
    shape: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class Zero1Plan:
    """The whole-tree plan: ``leaf_plans`` mirrors the param treedef
    with a :class:`LeafShardPlan` per leaf.

    ``comm_buckets`` is the requested number of layer-ordered
    communication buckets (1 = the monolithic per-leaf discipline;
    the effective count is clamped to the sharded-leaf count —
    :func:`comm_bucket_assignment`). ``params_sharded`` marks the
    resident-sharded layout (``parallel.resident_sharded``): the
    PARAMS live flattened-padded per this plan between steps, exactly
    like the optimizer slots, and every consumer of the canonical
    checkpoint contract (pack/unpack, state specs, init) reads that
    decision from here — one source of truth, same as the padding."""

    axis: str          # the replica mesh axis
    n: int             # replica count
    leaf_plans: Any
    comm_buckets: int = 1
    params_sharded: bool = False

    @property
    def any_sharded(self) -> bool:
        return any(lp.sharded for lp in jax.tree.leaves(
            self.leaf_plans, is_leaf=lambda x: isinstance(x, LeafShardPlan)))


def make_zero1_plan(params: Any, param_specs: Any, axis: str, n: int,
                    min_leaf_size: int = 0, comm_buckets: int = 1,
                    params_sharded: bool = False) -> Zero1Plan:
    """Decide, per leaf, whether the optimizer state / weight update
    shards over ``axis`` (``n`` replicas). ``params`` may be abstract
    (``jax.eval_shape`` output). ``min_leaf_size``: smallest element
    count that shards; 0 = auto (``n`` — a leaf smaller than the
    replica count cannot give every replica a slice)."""
    floor = max(n, min_leaf_size or n)

    def leaf_plan(p: Any, spec: PartitionSpec) -> LeafShardPlan:
        shape = tuple(p.shape)
        size = _leaf_size(p)
        sharded = bool(spec_is_replicated(spec) and size >= floor and n > 1)
        chunk = -(-size // n)
        return LeafShardPlan(sharded=sharded, size=size, pad=chunk * n,
                             chunk=chunk, shape=shape)

    return Zero1Plan(axis=axis, n=n,
                     leaf_plans=jax.tree.map(leaf_plan, params, param_specs),
                     comm_buckets=max(1, int(comm_buckets)),
                     params_sharded=bool(params_sharded))


def comm_bucket_assignment(plan: Zero1Plan) -> list[list[int]]:
    """The layer-ordered communication buckets: a partition of the
    SHARDED leaves' flatten indices into ``plan.comm_buckets``
    contiguous groups balanced by padded element count.

    Flatten order is the model's layer order (param trees flatten
    depth-first by layer), so a bucket's gradients complete together
    in the backward sweep and its reduce-scatter can issue while
    earlier layers' backward is still running — the overlap schedule.
    Contiguity + the size-balanced boundary rule make the assignment a
    pure function of (plan, comm_buckets): every consumer (update
    kernel, resident-param gather, the comm-calibration probe) derives
    the identical grouping, so the scattered/gathered concatenation
    layouts can never drift. Effective bucket count is clamped to the
    sharded-leaf count; empty when nothing shards."""
    lps = jax.tree.leaves(plan.leaf_plans,
                          is_leaf=lambda x: isinstance(x, LeafShardPlan))
    sharded = [i for i, lp in enumerate(lps) if lp.sharded]
    if not sharded:
        return []
    k = max(1, min(int(plan.comm_buckets), len(sharded)))
    total = float(sum(lps[i].pad for i in sharded))
    buckets: list[list[int]] = [[] for _ in range(k)]
    cum, b = 0.0, 0
    for pos, i in enumerate(sharded):
        # advance to the bucket this leaf's start falls in (size
        # boundary), or when the remaining leaves are only just enough
        # to keep every remaining bucket non-empty (a dominant leaf
        # must not starve the tail buckets) — never past the last
        # bucket, never leaving an earlier one empty
        while (b < k - 1 and buckets[b]
               and (cum >= (b + 1) * total / k
                    or len(sharded) - pos <= k - b - 1)):
            b += 1
        buckets[b].append(i)
        cum += lps[i].pad
    return buckets


def zero1_state_specs(plan: Zero1Plan, param_specs: Any) -> Any:
    """Spec tree for replica-sharded optimizer state: sharded leaves
    are 1-D ``[pad]`` arrays split over the replica axis; fallback
    leaves keep the param placement."""
    return jax.tree.map(
        lambda lp, spec: (PartitionSpec(plan.axis) if lp.sharded else spec),
        plan.leaf_plans, param_specs)


def zero1_init_state(params: Any, plan: Zero1Plan,
                     dtype_fn: Callable[[Any], Any] | None = None) -> Any:
    """Zeros-initialized optimizer-state tree in the plan's layout.
    ``dtype_fn(param_dtype) -> slot_dtype`` lets moment slots differ
    from the param dtype (float32 moments over bf16 params — see
    train/optim.slot_dtype); default: the param dtype, the historical
    layout."""
    import jax.numpy as jnp
    dt = dtype_fn if dtype_fn is not None else (lambda d: d)
    return jax.tree.map(
        lambda p, lp: (jnp.zeros((lp.pad,), dt(p.dtype)) if lp.sharded
                       else jnp.zeros(p.shape, dt(p.dtype))),
        params, plan.leaf_plans)


def zero1_pack(tree: Any, plan: Zero1Plan) -> Any:
    """Logical-shape tree → the plan's flattened-padded layout
    (host-side numpy; the restore direction of the canonical-checkpoint
    contract). Already-packed leaves pass through, and a leaf packed
    under a DIFFERENT replica count (``pad_old = ceil(size/n_old)·n_old``
    — e.g. a cross-process sharded artifact restored onto a resized
    mesh) is re-padded for THIS plan: padding is zeros by contract, so
    truncating to the logical size and re-padding is exact. This is
    what makes the restore side of the canonical contract
    mesh-portable: the plan is always re-derived from the CURRENT
    replica count (``parallel.api.restore_for_topology``), never the
    saver's."""
    def pack(x: Any, lp: LeafShardPlan):
        if not lp.sharded:
            return x
        a = np.asarray(x)
        if a.shape == (lp.pad,):
            return a  # already packed for THIS world
        flat = a.reshape(-1)
        if flat.size != lp.size:
            if a.ndim != 1 or flat.size < lp.size:
                # not a flat-packed layout of this leaf under ANY
                # replica count — a genuine shape mismatch must stay
                # loud, not be silently truncated into "fitting"
                raise ValueError(
                    f"cannot pack leaf of shape {a.shape} into shard "
                    f"plan (logical {lp.shape}, {lp.size} elements, "
                    f"pad {lp.pad})")
            if np.any(flat[lp.size:]):
                # padding is zeros BY CONTRACT — a non-zero tail means
                # this is real data of the wrong shape (different model
                # width, wrong leaf), not a foreign world's pad;
                # truncating it would be silent numeric corruption
                raise ValueError(
                    f"flat leaf of size {flat.size} carries non-zero "
                    f"data past the logical {lp.size} elements — not a "
                    "zero-padded shard layout; refusing to truncate")
            flat = flat[:lp.size]  # drop a foreign world's zero padding
        if lp.pad != flat.size:
            flat = np.concatenate(
                [flat, np.zeros(lp.pad - flat.size, a.dtype)])
        return flat
    return jax.tree.map(pack, tree, plan.leaf_plans)


def zero1_unpack(tree: Any, plan: Zero1Plan) -> Any:
    """The plan's flattened-padded layout → logical shapes (the save
    direction: checkpoints always carry the canonical logical layout,
    so artifacts — and their path digests — are identical whether the
    run sharded its weight update or not)."""
    def unpack(x: Any, lp: LeafShardPlan):
        if not lp.sharded:
            return x
        a = np.asarray(jax.device_get(x))
        if a.shape == lp.shape:
            return a  # already logical (e.g. a replicated-run artifact)
        return a.reshape(-1)[:lp.size].reshape(lp.shape)
    return jax.tree.map(unpack, tree, plan.leaf_plans)
