from .api import TrainState, build_eval_step, build_train_step, init_train_state
from . import policies

__all__ = ["TrainState", "build_eval_step", "build_train_step",
           "init_train_state", "policies"]
