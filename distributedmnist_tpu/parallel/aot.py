"""Ahead-of-time train-step compilation + executable disk cache.

The second half of the restart-latency fast path (ROADMAP item 5;
TF-Replicator, arXiv:1902.00465, treats replica spin-up as a cheap
operation — this is the compile leg of that). Three layers, each an
honest fallback for the next:

1. **AOT compile** — ``jit(step).lower(args).compile()`` run BEFORE the
   first batch (Trainer.precompile), so compile time is measured and
   journaled separately from step time and a warm standby parks fully
   compiled.
2. **Executable disk cache** — where the jax/backend pair supports
   cross-process executable serialization
   (``jax.experimental.serialize_executable``), the compiled train-step
   executable is stored under ``<cache_dir>/aot/<key>`` keyed on
   (model, config, topology, platform) so a restarted worker skips
   compilation entirely. The CPU backend serializes fine in-process but
   raises ``Symbols not found`` deserializing a FOREIGN process's
   executable (measured in this container) — so support is discovered
   at first cross-process load, recorded in a
   ``SERIALIZATION_UNSUPPORTED`` marker, and every later process skips
   straight to layer 3 instead of re-probing.
3. **Persistent compilation cache** — ``lowered.compile()`` itself goes
   through jax's persistent cache (core/compile_cache.py) when enabled,
   so even without executable serialization a warm restart pays a cache
   deserialize, not a compile.

A corrupted disk entry (torn write, truncation) is deleted, logged, and
recompiled — cache damage costs one compile, never a crash.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from pathlib import Path
from typing import Any

import jax

from ..core.log import get_logger

logger = get_logger("aot")

_UNSUPPORTED_MARKER = "SERIALIZATION_UNSUPPORTED"


_EXECUTABLE_SHAPING_SECTIONS = ("data", "model", "optim", "sync",
                                "mesh", "parallel")


def aot_cache_key(model, cfg, topo, what: str = "train_step") -> str:
    """Deterministic cache key for a compiled step: the
    executable-shaping config sections, the mesh shape/axes, the
    platform identity, and the jax version. Same (model, cfg, topo) ⇒
    same key (the hit case a restarted worker relies on); a different
    topology or shaping config ⇒ a different key (no stale-executable
    reuse).

    Host-side sections (``train``/``eval``/``compile``/``name`` — run
    length, logging/checkpoint cadence, dirs, NaN guards) are
    deliberately EXCLUDED: they never enter the lowered program, and
    hashing them would force a full cold compile on a bitwise-identical
    step just because an operator bumped ``train.max_steps`` against
    the same cache dir — exactly the latency this cache removes."""
    d0 = jax.devices()[0]
    ident = {
        "what": what,
        "model": getattr(model, "name", str(model)),
        "config": {k: v for k, v in cfg.to_dict().items()
                   if k in _EXECUTABLE_SHAPING_SECTIONS},
        "mesh_axes": tuple(topo.mesh.axis_names),
        "mesh_shape": tuple(topo.mesh.devices.shape),
        "platform": d0.platform,
        "device_kind": getattr(d0, "device_kind", "?"),
        "num_devices": len(jax.devices()),
        "num_processes": jax.process_count(),
        "jax": jax.__version__,
    }
    blob = json.dumps(ident, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:32]


class ExecutableCache:
    """Disk cache of serialized compiled executables under
    ``<cache_dir>/aot``. All failure modes degrade to "compile it
    again": missing entry, corrupt entry (deleted + logged), platform
    that cannot deserialize foreign executables (marker written so
    later processes skip the probe)."""

    def __init__(self, cache_dir: str | Path,
                 trust_cross_process: bool = False):
        self.dir = Path(cache_dir) / "aot"
        # cross-process reuse is the cache's whole purpose AND the
        # measured corruption vector on quarantined jax releases
        # (core.compile_cache.cross_process_reuse_quarantined): both
        # directions refuse there unless the caller asserts the
        # platform was validated (compile.trust_cache_cross_process)
        self.trust_cross_process = trust_cross_process

    def _quarantined(self) -> str | None:
        if self.trust_cross_process:
            return None
        from ..core.compile_cache import cross_process_reuse_quarantined
        return cross_process_reuse_quarantined()

    def _entry(self, key: str) -> Path:
        return self.dir / f"{key}.exe"

    @property
    def _marker(self) -> Path:
        return self.dir / _UNSUPPORTED_MARKER

    @staticmethod
    def _runtime_ident() -> dict[str, str]:
        """What the unsupported verdict is ABOUT. A marker recorded
        under one (platform, device kind, jax) triple must not outlive
        it: a cache dir kept across a jaxlib upgrade or moved to a
        backend that does serialize should re-probe, not stay disabled
        forever."""
        d0 = jax.devices()[0]
        return {"platform": d0.platform,
                "device_kind": str(getattr(d0, "device_kind", "?")),
                "jax": jax.__version__}

    def serialization_known_unsupported(self) -> bool:
        try:
            rec = json.loads(self._marker.read_text())
        except (OSError, ValueError):
            return False  # no marker, or an old/torn one: probe again
        return rec.get("runtime") == self._runtime_ident()

    def _mark_unsupported(self, err: Exception) -> None:
        try:
            self.dir.mkdir(parents=True, exist_ok=True)
            self._marker.write_text(json.dumps({
                "runtime": self._runtime_ident(),
                "error": f"{type(err).__name__}: {err}",
                "note": "executable (de)serialization is unsupported on "
                        "this platform; the persistent compilation cache "
                        "is the warm path here"}, indent=2))
        except OSError:
            pass
        logger.warning("executable serialization unsupported on this "
                       "platform (%s: %s) — falling back to the "
                       "persistent compilation cache",
                       type(err).__name__, err)

    def load(self, key: str):
        """The compiled executable for ``key``, or None (miss, corrupt
        entry, unsupported platform, or an entry THIS process stored —
        never an exception).

        The same-pid skip is a measured hazard, not an optimization:
        on jaxlib 0.4.37 CPU, deserializing the full train-step
        executable back into the process that serialized it corrupts
        the runtime (later dispatches segfault or return garbage),
        while the cross-process attempt fails cleanly ("Symbols not
        found" → marker). An in-process reload also has nothing to
        win — the live process recompiles through the warm persistent
        cache in well under a second."""
        reason = self._quarantined()
        if reason is not None:
            logger.debug("AOT disk cache quarantined: %s", reason)
            return None
        path = self._entry(key)
        if self.serialization_known_unsupported() or not path.exists():
            return None
        try:
            with open(path, "rb") as fh:
                stored_pid, payload, in_tree, out_tree = pickle.load(fh)
        except Exception as e:
            # torn/corrupted entry: drop it so the slot heals, compile
            logger.warning("corrupt AOT cache entry %s (%s: %s) — "
                           "deleted, falling back to cold compile",
                           path.name, type(e).__name__, e)
            path.unlink(missing_ok=True)
            return None
        if stored_pid == os.getpid():
            logger.debug("AOT entry %s was stored by this process — "
                         "skipping same-process reload", path.name)
            return None
        try:
            from jax.experimental import serialize_executable as se
            return se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:
            # the entry pickled fine but the BACKEND refused it — the
            # foreign-executable case (CPU: "Symbols not found").
            # Record the platform verdict so later boots skip the probe.
            self._mark_unsupported(e)
            return None

    def store(self, key: str, compiled) -> bool:
        """Serialize ``compiled`` into the cache (atomic write);
        returns whether it was stored. Serialization failure marks the
        platform unsupported — same verdict as a failed load."""
        if self._quarantined() is not None:
            return False  # an entry nobody may safely load
        if self.serialization_known_unsupported():
            return False
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = se.serialize(compiled)
        except Exception as e:
            self._mark_unsupported(e)
            return False
        path = self._entry(key)
        # tmp name is per-process: every worker of a cluster shares the
        # cache dir and computes the same key, so near-simultaneous cold
        # boots would otherwise truncate each other's in-progress write
        # and install interleaved garbage as the live entry
        tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
        try:
            self.dir.mkdir(parents=True, exist_ok=True)
            with open(tmp, "wb") as fh:
                # pid stamped so load() can refuse the same-process
                # reload (see load's docstring)
                pickle.dump((os.getpid(), payload, in_tree, out_tree), fh)
            tmp.replace(path)  # readers never see a torn entry
            return True
        except OSError as e:
            logger.warning("could not store AOT executable %s: %s",
                           path.name, e)
            tmp.unlink(missing_ok=True)
            return False


def aot_compile(jitted, args: tuple, cache_dir: str | Path | None = None,
                key: str | None = None,
                trust_cross_process: bool = False
                ) -> tuple[Any, dict[str, Any]]:
    """Compile ``jitted`` for ``args`` ahead of time, through the
    executable disk cache when one is configured.

    Returns ``(compiled, info)`` where info records where the
    executable came from (``aot_disk`` / ``compiled``), the wall
    seconds it took, and whether it was (re)serialized to disk — the
    fields Trainer journals as the ``event: "compile"`` record."""
    cache = (ExecutableCache(cache_dir, trust_cross_process)
             if cache_dir is not None and key is not None else None)
    t0 = time.perf_counter()
    if cache is not None:
        loaded = cache.load(key)
        if loaded is not None:
            return loaded, {"compile_s": round(time.perf_counter() - t0, 3),
                            "source": "aot_disk", "serialized": False,
                            "key": key}
    lowered = jitted.lower(*args)
    compiled = lowered.compile()
    info: dict[str, Any] = {
        "compile_s": round(time.perf_counter() - t0, 3),
        "source": "compiled", "serialized": False}
    if key is not None:
        info["key"] = key
    if cache is not None:
        info["serialized"] = cache.store(key, compiled)
    return compiled, info
