from .registry import Model, available, get_model, register

__all__ = ["Model", "available", "get_model", "register"]
