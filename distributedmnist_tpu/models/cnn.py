"""LeNet-style MNIST CNN as a pure-function param pytree.

Capability parity with the reference model (src/mnist.py:76-167):
conv5x5x32 → ReLU → maxpool2 → conv5x5x64 → ReLU → maxpool2 → FC512 →
dropout(0.5, train only) → FC10; truncated-normal(stddev=0.1) weight
init with fixed seed 66478 (src/mnist.py:32,81-101); zero bias on
conv1, 0.1 bias elsewhere; mean sparse-softmax-xent loss
(src/mnist.py:149-159); top-1 accuracy (src/mnist.py:161-164).

TPU-first differences from the reference:
* NHWC convs lowered by XLA:TPU to MXU-tiled HLO (no cuDNN).
* Activations/matmuls run in ``compute_dtype`` (bfloat16 by default)
  while params and the loss stay float32 — the MXU's native mode.
* Dropout consumes an explicit PRNG key (no hidden graph seed state).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

Params = dict[str, Any]


def truncated_normal_init(key: jax.Array, shape: tuple[int, ...],
                          stddev: float = 0.1, dtype=jnp.float32) -> jax.Array:
    """TF-style truncated normal: N(0, stddev²) truncated to ±2σ
    (≙ tf.truncated_normal, src/mnist.py:81-99)."""
    return jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype) * stddev


def init(key: jax.Array, image_size: int = 28, num_channels: int = 1,
         num_classes: int = 10) -> Params:
    """Initialize parameters (init constants per src/mnist.py:81-101)."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    fc_in = (image_size // 4) * (image_size // 4) * 64
    return {
        "conv1": {"w": truncated_normal_init(k1, (5, 5, num_channels, 32)),
                  "b": jnp.zeros((32,), jnp.float32)},
        "conv2": {"w": truncated_normal_init(k2, (5, 5, 32, 64)),
                  "b": jnp.full((64,), 0.1, jnp.float32)},
        "fc1": {"w": truncated_normal_init(k3, (fc_in, 512)),
                "b": jnp.full((512,), 0.1, jnp.float32)},
        "fc2": {"w": truncated_normal_init(k4, (512, num_classes)),
                "b": jnp.full((num_classes,), 0.1, jnp.float32)},
    }


def _conv2d_same(x: jax.Array, w: jax.Array) -> jax.Array:
    return lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _maxpool2(x: jax.Array) -> jax.Array:
    return lax.reduce_window(x, -jnp.inf, lax.max,
                             window_dimensions=(1, 2, 2, 1),
                             window_strides=(1, 2, 2, 1),
                             padding="SAME")


def apply(params: Params, images: jax.Array, *, train: bool = False,
          dropout_key: jax.Array | None = None, dropout_rate: float = 0.5,
          compute_dtype=jnp.bfloat16) -> jax.Array:
    """Forward pass → float32 logits [batch, num_classes].

    ``images``: [batch, H, W, C] floats normalized to [-0.5, 0.5]
    (normalization parity: src/mnist_data.py:142).
    """
    x = images.astype(compute_dtype)
    p = jax.tree.map(lambda a: a.astype(compute_dtype), params)

    x = _maxpool2(jax.nn.relu(_conv2d_same(x, p["conv1"]["w"]) + p["conv1"]["b"]))
    x = _maxpool2(jax.nn.relu(_conv2d_same(x, p["conv2"]["w"]) + p["conv2"]["b"]))
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ p["fc1"]["w"] + p["fc1"]["b"])
    if train and dropout_rate > 0.0:
        if dropout_key is None:
            raise ValueError("train=True dropout requires dropout_key")
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_rate, x.shape)
        # Inverted dropout — same "no rescale at eval" semantics as
        # tf.nn.dropout (src/mnist.py:137-140).
        x = jnp.where(keep, x / (1.0 - dropout_rate), 0.0).astype(compute_dtype)
    logits = x @ p["fc2"]["w"] + p["fc2"]["b"]
    return logits.astype(jnp.float32)


def loss_fn(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean sparse softmax cross-entropy (≙ src/mnist.py:149-159)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    return jnp.mean(nll)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Top-1 accuracy (≙ src/mnist.py:161-164)."""
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))


def predictions(logits: jax.Array) -> jax.Array:
    """Softmax class probabilities [batch, num_classes] — the export
    surface (≙ tf.nn.softmax(logits), src/mnist.py:166-167)."""
    return jax.nn.softmax(logits, axis=-1)
