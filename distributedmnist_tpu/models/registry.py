"""Model registry: every model is a pure (init, apply, loss, accuracy)
bundle over a param pytree — no classes, no hidden state, trivially
compatible with jit/grad/shard_map.

Replaces the reference's single hardwired model module
(src/mnist.py, wired at src/distributed_train.py:158-171) with a
family registry covering the BASELINE.json configs (MNIST CNN,
Fashion-MNIST CNN, CIFAR-10 ResNet-20, plus a transformer for the
long-context path).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..core.config import ModelConfig

# NOTE: rule tables reference parallel/partition_rules.py only by
# convention (they receive its RuleAxes and return its (regex, spec)
# Rule pairs) — importing it here would cycle through parallel/__init__
# → parallel.api → models.registry.


def classification_eval_metrics(logits: jax.Array, labels: jax.Array,
                                weight: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-batch weighted eval sums for a [batch, classes] classifier:
    (correct_sum, loss_sum, weight_sum). Padded examples carry weight 0
    so they never bias metrics."""
    w = weight.astype(jnp.float32)
    correct = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32),
                               axis=-1)[:, 0]
    return jnp.sum(correct * w), jnp.sum(nll * w), jnp.sum(w)


def classification_predictions(logits: jax.Array) -> jax.Array:
    """Softmax class probabilities [batch, classes] — the inference
    export every classifier serves (≙ cnn.predictions; defined here so
    EVERY registered model carries one and ``servesvc`` stays
    model-agnostic the way the trainer is)."""
    return jax.nn.softmax(logits, axis=-1)


def lm_last_logits(logits: jax.Array) -> jax.Array:
    """Last-position logits [batch, vocab] of a [batch, seq, vocab]
    causal-LM forward — the shared next-token head every LM consumer
    (the one-shot ``lm_predictions`` export, the decode service's
    prefill) reads instead of each re-spelling the slice."""
    return logits[:, -1]


def lm_predictions(logits: jax.Array) -> jax.Array:
    """Next-token distribution [batch, vocab] for a causal LM: softmax
    over the last position's logits (:func:`lm_last_logits`) — the
    one-shot inference export (what the classification-shaped serving
    path ranks from)."""
    return jax.nn.softmax(lm_last_logits(logits), axis=-1)


def sample_token(logits: jax.Array, key: jax.Array | None = None,
                 temperature: float = 0.0,
                 top_k: int = 0) -> jax.Array:
    """Sample next-token ids [...] from logits [..., vocab].

    ``temperature <= 0`` is greedy argmax — deterministic, no key
    needed (and the limit temperature → 0 of the sampled path, pinned
    in tests). ``temperature > 0`` divides the logits before a
    categorical draw; ``top_k > 0`` additionally masks everything
    below the k-th highest logit (top_k=1 ≡ greedy)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if key is None:
        raise ValueError("sample_token with temperature > 0 needs a "
                         "PRNG key")
    scaled = logits.astype(jnp.float32) / temperature
    if top_k and top_k > 0:
        kth = jnp.sort(scaled, axis=-1)[..., -top_k][..., None]
        scaled = jnp.where(scaled < kth, -1e30, scaled)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)


def lm_eval_metrics(logits: jax.Array, labels: jax.Array,
                    weight: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Token-level eval sums for a [batch, seq, vocab] causal LM
    (weight is per-sequence; counts are per predicted token)."""
    w = weight.astype(jnp.float32)[:, None]
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    tgt = labels[:, 1:].astype(jnp.int32)
    correct = (jnp.argmax(logp, axis=-1) == tgt).astype(jnp.float32)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return (jnp.sum(correct * w), jnp.sum(nll * w),
            jnp.sum(w * jnp.ones_like(correct)))


@dataclasses.dataclass(frozen=True)
class Model:
    """A model family instance.

    * ``init(key) -> params``
    * ``apply(params, inputs, train=..., dropout_key=...) -> logits``
    * ``loss(logits, labels) -> scalar``
    * ``accuracy(logits, labels) -> scalar``
    * ``eval_metrics(logits, labels, weight) -> (correct_sum, loss_sum, weight_sum)``
    * ``input_shape`` excludes the batch dim.
    """

    name: str
    init: Callable[[jax.Array], Any]
    apply: Callable[..., jax.Array]
    loss: Callable[[jax.Array, jax.Array], jax.Array]
    accuracy: Callable[[jax.Array, jax.Array], jax.Array]
    input_shape: tuple[int, ...]
    input_dtype: Any = jnp.float32
    eval_metrics: Callable[..., tuple] = classification_eval_metrics
    # ``predictions(logits) -> per-example distribution`` — the
    # inference export (softmax class probs for classifiers, next-token
    # distribution for LMs). Every registered model carries one, so the
    # serving tier (servesvc) builds its predict step from the registry
    # exactly the way the trainer builds its train step.
    predictions: Callable[[jax.Array], jax.Array] = classification_predictions
    # Sharded-execution support (long-context models only):
    # factory(seq_axis, model_axis, expert_axis=None) -> apply(params,
    # tokens_local, positions_local) -> logits_local, run inside
    # shard_map. Any axis may be None (unsharded); with seq_axis the
    # sequence dim is sharded (ring/all-to-all attention), with
    # model_axis params are tensor-parallel per ``tp_param_specs``,
    # with expert_axis MoE experts are sharded over it.
    sharded_apply_factory: (Callable[...,
                                     Callable[..., jax.Array]] | None) = None
    # factory(model_axis, expert_axis=None) -> params-shaped pytree of
    # PartitionSpec for tensor-/expert-parallel parameter placement.
    tp_param_specs: Callable[..., Any] | None = None
    # Pipeline-parallel support: pp_transform restacks init params into
    # the layer-stacked layout; pp_param_specs(stage_axis) are its
    # placement specs; pp_apply_factory(stage_axis, num_microbatches)
    # -> apply(params, tokens) -> logits inside shard_map.
    pp_transform: Callable[[Any], Any] | None = None
    pp_param_specs: Callable[[str], Any] | None = None
    pp_apply_factory: (Callable[[str, int], Callable[..., jax.Array]]
                       | None) = None
    # Interleaved-1F1B schedule support (mesh.pipeline_schedule="1f1b"):
    # pp_transform_chunked(params, S, v) restacks into the
    # chunk-interleaved layout; pp_1f1b_grads_factory(stage_axis, M, v,
    # model_axis=None, seq_axis=None, expert_axis=None) ->
    # grads_fn(params, tokens, labels) -> (loss, acc, grads) (the
    # fused forward/backward engine — no outer value_and_grad; under
    # seq_axis the outputs are per-shard partials the caller psums);
    # pp_1f1b_apply_factory(stage_axis, M, v, model_axis=None) ->
    # apply for eval.
    pp_transform_chunked: Callable[..., Any] | None = None
    pp_1f1b_grads_factory: Callable[..., Callable[..., tuple]] | None = None
    pp_1f1b_apply_factory: (Callable[..., Callable[..., jax.Array]]
                            | None) = None
    # Declarative parameter-placement rules (parallel/partition_rules):
    # partition_rules(axes: RuleAxes) -> ordered [(path-regex,
    # PartitionSpec)] list covering EVERY param leaf for whatever mix
    # of tp/pp/ep axes is active (inactive axes arrive as None and the
    # table leaves those dims unsharded). This is the single source the
    # spec engine maps over the real param tree — the per-shape
    # tp_param_specs/pp_param_specs builders above remain the models'
    # hand-built originals and the parity oracle for the tables.
    partition_rules: Callable[..., list] | None = None
    # Autoregressive-decode exports (causal LMs with dense FFNs only;
    # None elsewhere — the decode service refuses models without them):
    # decode_prefill(params, tokens [b, s]) -> (logits [b, s, vocab],
    # k [L, b, s, h, hd], v [L, b, s, h, hd]) — the prompt forward
    # through the configured attention kernel that also exports every
    # layer's K/V for seeding a paged cache; decode_step(params,
    # tokens [S], positions [S], k_cache, v_cache [L, N, B, h, hd],
    # block_tables [S, P], lengths [S], block_size=B) -> (logits
    # [S, vocab], k_cache, v_cache) — one incremental token over the
    # paged cache, a single compiled shape for any mix of sequence
    # lengths. decode_cache_shape = (num_layers, num_heads, head_dim),
    # the geometry the cache is allocated with.
    decode_prefill: Callable[..., tuple] | None = None
    decode_step: Callable[..., tuple] | None = None
    decode_cache_shape: tuple[int, int, int] | None = None
    # Auxiliary loss (MoE load balancing): when True, ``apply`` and the
    # sharded applies accept ``return_aux=True`` and return
    # (logits, aux); the train step adds ``aux_weight * aux``.
    has_aux: bool = False
    aux_weight: float = 0.0
    # True when ``apply(train=True)`` consumes ``dropout_key``. The
    # SP/PP loss paths do not thread a dropout key (parallel/api.py);
    # they refuse such a model rather than silently training without
    # dropout.
    uses_dropout: bool = False


# ---------------------------------------------------------------------------
# Default partition-rule tables (the per-model regex→PartitionSpec
# tables the spec engine maps over real param trees; see
# parallel/partition_rules.match_partition_rules)
# ---------------------------------------------------------------------------

def replicated_partition_rules(axes) -> list:
    """Every leaf replicated — the table for models with no
    tensor/pipeline/expert parallelism support (cnn, resnet)."""
    del axes
    return [(r".*", PartitionSpec())]


def transformer_partition_rules(num_experts: int):
    """The transformer's table, parameterized like its hand-built spec
    functions: Megatron column/row TP on the model axis, experts on the
    expert axis, and — when ``axes.stage`` is set — the stacked
    (pipeline) layout whose block leaves carry a leading layer dim
    sharded over the stage axis. Flat-layout block paths look like
    ``blocks/3/wqkv``; stacked ones like ``blocks/wqkv`` — distinct
    regexes, so one call's table is unambiguous either way."""
    def rules(axes) -> list:
        P = PartitionSpec
        m, e, s = axes.model, axes.expert, axes.stage
        out: list = []
        if s is not None:
            # stacked layout: leading layer dim over the stage axis
            out += [
                (r"blocks/wqkv$", P(s, None, None, m)),
                (r"blocks/wo$", P(s, m, None)),
                (r"blocks/(ln1|ln2)/scale$", P(s)),
            ]
            if num_experts > 0:
                out += [(r"blocks/router$", P(s)),
                        (r"blocks/w1$", P(s, e, None, m)),
                        (r"blocks/w2$", P(s, e, m, None))]
            else:
                out += [(r"blocks/w1$", P(s, None, m)),
                        (r"blocks/w2$", P(s, m, None))]
        else:
            out += [
                (r"blocks/\d+/wqkv$", P(None, None, m)),
                (r"blocks/\d+/wo$", P(m, None)),
            ]
            if num_experts > 0:
                out += [(r"blocks/\d+/router$", P()),
                        (r"blocks/\d+/w1$", P(e, None, m)),
                        (r"blocks/\d+/w2$", P(e, m, None))]
            else:
                out += [(r"blocks/\d+/w1$", P(None, m)),
                        (r"blocks/\d+/w2$", P(m, None))]
        # embeddings and norms replicated in every layout (stacked block
        # norms matched above first — first match wins)
        out += [(r"(^|/)(ln1|ln2|final_norm)/scale$", P()),
                (r"^(embed|pos)$", P())]
        return out
    return rules


_REGISTRY: dict[str, Callable[[ModelConfig], Model]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def available() -> list[str]:
    return sorted(_REGISTRY)


def get_model(cfg: ModelConfig) -> Model:
    if cfg.name not in _REGISTRY:
        raise ValueError(f"unknown model {cfg.name!r}; available: {available()}")
    return _REGISTRY[cfg.name](cfg)


@register("mnist_cnn")
def _mnist_cnn(cfg: ModelConfig) -> Model:
    from . import cnn
    compute_dtype = jnp.dtype(cfg.compute_dtype)

    def init(key):
        return cnn.init(key, image_size=cfg.image_size,
                        num_channels=cfg.num_channels,
                        num_classes=cfg.num_classes)

    def apply(params, x, *, train=False, dropout_key=None):
        return cnn.apply(params, x, train=train, dropout_key=dropout_key,
                         dropout_rate=cfg.dropout_rate,
                         compute_dtype=compute_dtype)

    return Model(name=cfg.name, init=init, apply=apply,
                 loss=cnn.loss_fn, accuracy=cnn.accuracy,
                 input_shape=(cfg.image_size, cfg.image_size, cfg.num_channels),
                 partition_rules=replicated_partition_rules,
                 predictions=cnn.predictions,  # the reference's export
                 uses_dropout=cfg.dropout_rate > 0.0)


@register("resnet20")
def _resnet20(cfg: ModelConfig) -> Model:
    from . import resnet
    compute_dtype = jnp.dtype(cfg.compute_dtype)

    def init(key):
        return resnet.init(key, num_classes=cfg.num_classes,
                           num_channels=cfg.num_channels)

    def apply(params, x, *, train=False, dropout_key=None):
        del dropout_key  # resnet20 has no dropout
        return resnet.apply(params, x, train=train, compute_dtype=compute_dtype)

    from . import cnn
    return Model(name=cfg.name, init=init, apply=apply,
                 loss=cnn.loss_fn, accuracy=cnn.accuracy,
                 input_shape=(cfg.image_size, cfg.image_size, cfg.num_channels),
                 partition_rules=replicated_partition_rules)


@register("transformer")
def _transformer(cfg: ModelConfig) -> Model:
    from . import transformer
    compute_dtype = jnp.dtype(cfg.compute_dtype)

    moe = cfg.num_experts > 0

    def init(key):
        return transformer.init(
            key, vocab_size=cfg.vocab_size, model_dim=cfg.model_dim,
            num_heads=cfg.num_heads, num_layers=cfg.num_layers,
            max_seq_len=cfg.seq_len, num_experts=cfg.num_experts)

    if cfg.attention_impl == "flash":
        from ..ops.pallas_attention import (flash_attention,
                                            flash_attention_bshd)
        # the model body sees the bshd entry (no head transposes); the
        # SP wrappers below keep the bhsd entry — Ulysses' all-to-all
        # output is already head-major
        attention_fn = flash_attention_bshd
        inner_bhsd = flash_attention
    elif cfg.attention_impl == "dense":
        attention_fn = None  # transformer defaults to local_self_attention
        inner_bhsd = None
    else:
        raise ValueError(f"unknown attention_impl {cfg.attention_impl!r}")

    if (cfg.remat and cfg.remat_policy == "save_attn"
            and cfg.attention_impl != "flash"):
        # save_attn keeps the attention sublayer's AD residuals
        # resident; only the flash kernel's custom VJP bounds those at
        # O(s·d) — dense attention would park the [b, h, s, s] softmax
        # probabilities in HBM per layer, defeating remat entirely
        raise ValueError(
            "model.remat_policy='save_attn' requires "
            "attention_impl='flash' (dense attention has no fused VJP; "
            "its resident residuals would be O(seq²) per layer)")

    def apply(params, x, *, train=False, dropout_key=None, return_aux=False):
        del dropout_key
        return transformer.apply(params, x, num_heads=cfg.num_heads,
                                 attention_fn=attention_fn,
                                 compute_dtype=compute_dtype,
                                 num_experts=cfg.num_experts,
                                 capacity_factor=cfg.expert_capacity_factor,
                                 moe_num_groups=cfg.moe_num_groups,
                                 moe_router_top_k=cfg.moe_router_top_k,
                                 remat=cfg.remat,
                                 remat_policy=cfg.remat_policy,
                                 return_aux=return_aux)

    def make_seq_attn(seq_axis: str | None):
        """The attention callable for a given seq sharding: the plain
        configured kernel when unsharded, else ring / Ulysses over the
        axis (shared by the SP/TP path and the pipeline path)."""
        if seq_axis is None:
            return attention_fn  # flash or dense, per attention_impl
        if cfg.sp_attention == "ring":
            from ..ops.ring_attention import ring_self_attention

            def sharded_attn(q, k, v, causal=True, scale=None):
                return ring_self_attention(q, k, v, seq_axis, causal=causal,
                                           scale=scale)
            return sharded_attn
        if cfg.sp_attention == "ulysses":
            from ..ops.ulysses_attention import ulysses_self_attention
            inner = inner_bhsd

            def sharded_attn(q, k, v, causal=True, scale=None):
                return ulysses_self_attention(q, k, v, seq_axis,
                                              causal=causal, scale=scale,
                                              attention_fn=inner)
            return sharded_attn
        raise ValueError(f"unknown sp_attention {cfg.sp_attention!r}")

    def sharded_apply_factory(seq_axis: str | None, model_axis: str | None,
                              expert_axis: str | None = None):
        """Sharded apply for the DP×SP×TP×EP train step: tokens arrive
        as [b, seq_local] slices; attention crosses seq shards via the
        configured strategy; params may be tensor-parallel and/or
        expert-parallel shards."""
        sharded_attn = make_seq_attn(seq_axis)

        if expert_axis is not None and not moe:
            raise ValueError("mesh has expert parallelism but the model has "
                             "no experts (model.num_experts == 0)")
        if (cfg.remat and cfg.remat_policy == "save_attn"
                and seq_axis is not None and cfg.sp_attention == "ring"):
            # save_attn keeps the attention sublayer outside the
            # checkpoint so the flash kernel's O(s·d) custom-vjp
            # residuals stay resident; ring attention has no custom
            # vjp — AD would save its per-ppermute-step scan residuals
            # instead, exactly the memory remat exists to avoid
            raise ValueError(
                "model.remat_policy='save_attn' requires an attention "
                "with a fused VJP (flash / Ulysses-over-flash); ring "
                "attention under sequence parallelism needs "
                "remat_policy='full'")

        # SP×MoE: tokens are already seq-sharded; routing runs on each
        # shard's slice with shard-local capacity (ops/moe.py module
        # doc), while the aux statistics average over the seq axis so
        # the load-balance loss stays the exact full-token value.
        stats_axes = (seq_axis,) if (moe and seq_axis is not None) else ()

        def apply_sharded(params, tokens, positions, return_aux=False):
            return transformer.apply(params, tokens, num_heads=cfg.num_heads,
                                     attention_fn=sharded_attn,
                                     positions=positions,
                                     compute_dtype=compute_dtype,
                                     model_axis=model_axis,
                                     expert_axis=expert_axis,
                                     num_experts=cfg.num_experts,
                                     capacity_factor=cfg.expert_capacity_factor,
                                     moe_num_groups=cfg.moe_num_groups,
                                     moe_router_top_k=cfg.moe_router_top_k,
                                     remat=cfg.remat,
                                     remat_policy=cfg.remat_policy,
                                     moe_stats_axes=stats_axes,
                                     return_aux=return_aux)

        return apply_sharded

    def pp_apply_factory(stage_axis: str, num_microbatches: int,
                         model_axis: str | None = None,
                         seq_axis: str | None = None,
                         expert_axis: str | None = None):
        if expert_axis is not None and not moe:
            raise ValueError("mesh has expert parallelism but the model has "
                             "no experts (model.num_experts == 0)")
        if cfg.remat and cfg.remat_policy != "full":
            # the pipeline stage scans checkpoint whole layers; a
            # silently-ignored policy would leave the user at full-remat
            # throughput while believing save_attn is on
            raise ValueError(
                f"model.remat_policy={cfg.remat_policy!r} is not "
                "supported under pipeline parallelism (stage scans use "
                "full per-layer remat); set remat_policy='full'")
        pp_attn = make_seq_attn(seq_axis)
        # PP×SP×MoE: each tick's MoE calls see one microbatch's SLICE
        # of one seq shard; averaging the routing stats over the seq
        # axis (plus the tick accumulation) reconstructs the exact
        # full-token aux (see sharded_apply_factory's SP×MoE note)
        stats_axes = (seq_axis,) if (moe and seq_axis is not None) else ()

        def apply_pp(params, tokens, positions=None, return_aux=False):
            return transformer.apply_pp(
                params, tokens, num_heads=cfg.num_heads,
                stage_axis=stage_axis, num_microbatches=num_microbatches,
                attention_fn=pp_attn, positions=positions,
                model_axis=model_axis, expert_axis=expert_axis,
                num_experts=cfg.num_experts,
                capacity_factor=cfg.expert_capacity_factor,
                moe_num_groups=cfg.moe_num_groups,
                moe_router_top_k=cfg.moe_router_top_k,
                moe_stats_axes=stats_axes,
                compute_dtype=compute_dtype, remat=cfg.remat,
                return_aux=return_aux)
        return apply_pp

    def pp_1f1b_grads_factory(stage_axis: str, num_microbatches: int,
                              num_chunks: int,
                              model_axis: str | None = None,
                              seq_axis: str | None = None,
                              expert_axis: str | None = None):
        if expert_axis is not None and not moe:
            raise ValueError("mesh has expert parallelism but the model has "
                             "no experts (model.num_experts == 0)")
        if cfg.remat and cfg.remat_policy != "full":
            raise ValueError(
                f"model.remat_policy={cfg.remat_policy!r} is not "
                "supported under the 1f1b schedule (chunk recompute is "
                "built into the engine); set remat_policy='full'")
        if seq_axis is not None and cfg.sp_attention == "ring":
            raise ValueError(
                "pipeline_schedule='1f1b' with sequence parallelism "
                "requires model.sp_attention='ulysses': ring attention's "
                "ppermute rendezvouses globally and deadlocks inside the "
                "fused engine's stage-varying branches (all_to_all is "
                "group-local and composes; use 'gpipe' for ring)")
        pp_attn = make_seq_attn(seq_axis)

        def grads_fn(params, tokens, labels):
            return transformer.grads_pp_1f1b(
                params, tokens, labels, num_heads=cfg.num_heads,
                stage_axis=stage_axis, num_microbatches=num_microbatches,
                num_chunks=num_chunks, attention_fn=pp_attn,
                model_axis=model_axis, seq_axis=seq_axis,
                expert_axis=expert_axis, num_experts=cfg.num_experts,
                capacity_factor=cfg.expert_capacity_factor,
                moe_num_groups=cfg.moe_num_groups,
                moe_router_top_k=cfg.moe_router_top_k,
                aux_weight=cfg.moe_aux_weight,
                compute_dtype=compute_dtype)
        return grads_fn

    def pp_1f1b_apply_factory(stage_axis: str, num_microbatches: int,
                              num_chunks: int,
                              model_axis: str | None = None,
                              expert_axis: str | None = None):
        if expert_axis is not None and not moe:
            raise ValueError("mesh has expert parallelism but the model has "
                             "no experts (model.num_experts == 0)")

        def apply_1f1b(params, tokens):
            return transformer.apply_pp_1f1b(
                params, tokens, num_heads=cfg.num_heads,
                stage_axis=stage_axis, num_microbatches=num_microbatches,
                num_chunks=num_chunks, attention_fn=attention_fn,
                model_axis=model_axis,
                expert_axis=expert_axis, num_experts=cfg.num_experts,
                capacity_factor=cfg.expert_capacity_factor,
                moe_num_groups=cfg.moe_num_groups,
                moe_router_top_k=cfg.moe_router_top_k,
                compute_dtype=compute_dtype)
        return apply_1f1b

    # Decode exports: dense-FFN causal LMs only (MoE routing is
    # batch-statistics-shaped; an incremental one-token step has no
    # well-defined group routing to run)
    decode_prefill = decode_step_fn = decode_cache_shape = None
    if not moe:
        def decode_prefill(params, tokens, positions=None):
            return transformer.prefill_with_kv(
                params, tokens, num_heads=cfg.num_heads,
                attention_fn=attention_fn, positions=positions,
                compute_dtype=compute_dtype)

        def decode_step_fn(params, tokens, positions, k_cache, v_cache,
                           block_tables, lengths, *, block_size,
                           attention_kernel="dense"):
            return transformer.decode_step(
                params, tokens, positions, k_cache, v_cache,
                block_tables, lengths, num_heads=cfg.num_heads,
                block_size=block_size, compute_dtype=compute_dtype,
                attention_kernel=attention_kernel)

        decode_cache_shape = (cfg.num_layers, cfg.num_heads,
                              cfg.model_dim // cfg.num_heads)

    return Model(name=cfg.name, init=init, apply=apply,
                 loss=transformer.loss_fn, accuracy=transformer.accuracy,
                 input_shape=(cfg.seq_len,), input_dtype=jnp.int32,
                 eval_metrics=lm_eval_metrics,
                 predictions=lm_predictions,
                 decode_prefill=decode_prefill,
                 decode_step=decode_step_fn,
                 decode_cache_shape=decode_cache_shape,
                 sharded_apply_factory=sharded_apply_factory,
                 partition_rules=transformer_partition_rules(cfg.num_experts),
                 has_aux=moe, aux_weight=cfg.moe_aux_weight,
                 tp_param_specs=lambda axis, expert_axis=None:
                     transformer.param_partition_specs(
                         cfg.num_layers, axis, cfg.num_experts, expert_axis),
                 pp_transform=transformer.stack_block_params,
                 pp_param_specs=lambda stage_axis, model_axis=None,
                 expert_axis=None: transformer.pp_param_partition_specs(
                     stage_axis, model_axis, cfg.num_experts, expert_axis),
                 pp_apply_factory=pp_apply_factory,
                 pp_transform_chunked=transformer.stack_block_params_chunked,
                 pp_1f1b_grads_factory=pp_1f1b_grads_factory,
                 pp_1f1b_apply_factory=pp_1f1b_apply_factory)
