"""A compact causal-LM transformer — the long-context model family.

Not a reference-parity model (the reference has no attention anywhere,
SURVEY §5.7); this exists so the framework's sequence-parallel path —
ring attention over the mesh's ``seq`` axis (ops/ring_attention.py) —
has a first-class consumer, and so the aggregation disciplines can be
exercised on a transformer-shaped allreduce payload.

Pure init/apply over a param pytree, pre-norm blocks, learned
positional embeddings, weight-tied LM head. ``attention_fn`` is
injectable: ``local_self_attention`` single-device, or a closure over
``ring_self_attention(axis_name=...)`` under a seq-sharded shard_map.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from .cnn import truncated_normal_init
from ..ops.ring_attention import local_self_attention

Params = dict[str, Any]


def init(key: jax.Array, vocab_size: int = 256, model_dim: int = 128,
         num_heads: int = 4, num_layers: int = 2,
         max_seq_len: int = 512) -> Params:
    assert model_dim % num_heads == 0
    keys = iter(jax.random.split(key, 4 + 4 * num_layers))
    scale = 0.02
    params: Params = {
        "embed": truncated_normal_init(next(keys), (vocab_size, model_dim), scale),
        "pos": truncated_normal_init(next(keys), (max_seq_len, model_dim), scale),
        "blocks": [],
        "final_norm": {"scale": jnp.ones((model_dim,), jnp.float32)},
    }
    for _ in range(num_layers):
        params["blocks"].append({
            "ln1": {"scale": jnp.ones((model_dim,), jnp.float32)},
            "wqkv": truncated_normal_init(next(keys), (model_dim, 3 * model_dim), scale),
            "wo": truncated_normal_init(next(keys), (model_dim, model_dim), scale),
            "ln2": {"scale": jnp.ones((model_dim,), jnp.float32)},
            "w1": truncated_normal_init(next(keys), (model_dim, 4 * model_dim), scale),
            "w2": truncated_normal_init(next(keys), (4 * model_dim, model_dim), scale),
        })
    return params


def _rms_norm(x: jax.Array, p: Params) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6) * p["scale"]).astype(x.dtype)


def apply(params: Params, tokens: jax.Array, *, num_heads: int = 4,
          attention_fn: Callable | None = None,
          positions: jax.Array | None = None,
          compute_dtype=jnp.bfloat16) -> jax.Array:
    """tokens [batch, seq] int32 → logits [batch, seq, vocab] float32.

    ``positions`` (global positions of this shard's tokens) must be
    passed when the sequence is sharded; defaults to arange(seq).
    """
    attn = attention_fn or local_self_attention
    b, s = tokens.shape
    if positions is None:
        positions = jnp.arange(s)
    p = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    x = p["embed"][tokens] + p["pos"][positions]
    d = x.shape[-1]
    hd = d // num_heads
    for blk in p["blocks"]:
        h = _rms_norm(x, blk["ln1"])
        qkv = h @ blk["wqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, -1, num_heads, hd).transpose(0, 2, 1, 3)

        o = attn(heads(q), heads(k), heads(v))
        o = o.transpose(0, 2, 1, 3).reshape(b, -1, d)
        x = x + o @ blk["wo"]
        h = _rms_norm(x, blk["ln2"])
        x = x + jax.nn.relu(h @ blk["w1"]) @ blk["w2"]
    x = _rms_norm(x, p["final_norm"])
    logits = x @ p["embed"].T  # tied head
    return logits.astype(jnp.float32)


def loss_fn(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Next-token mean xent. ``labels`` are the input tokens; targets
    are labels shifted left (last position dropped)."""
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    tgt = labels[:, 1:].astype(jnp.int32)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    pred = jnp.argmax(logits[:, :-1], axis=-1)
    return jnp.mean((pred == labels[:, 1:]).astype(jnp.float32))
