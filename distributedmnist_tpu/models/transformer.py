"""A compact causal-LM transformer — the long-context model family.

Not a reference-parity model (the reference has no attention anywhere,
SURVEY §5.7); this exists so the framework's sequence-parallel path —
ring attention over the mesh's ``seq`` axis (ops/ring_attention.py) —
has a first-class consumer, and so the aggregation disciplines can be
exercised on a transformer-shaped allreduce payload.

Pure init/apply over a param pytree, pre-norm blocks, learned
positional embeddings, weight-tied LM head. ``attention_fn`` is
injectable: ``local_self_attention`` single-device, or a closure over
``ring_self_attention(axis_name=...)`` under a seq-sharded shard_map.

Tensor parallelism (Megatron-style) is built in: pass ``model_axis``
when params are sharded per :func:`param_partition_specs` — qkv/w1
column-parallel, wo/w2 row-parallel with one psum per residual add,
attention heads split across the axis.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from .cnn import truncated_normal_init
from ..ops.ring_attention import local_self_attention

Params = dict[str, Any]


def init(key: jax.Array, vocab_size: int = 256, model_dim: int = 128,
         num_heads: int = 4, num_layers: int = 2,
         max_seq_len: int = 512, num_experts: int = 0) -> Params:
    """``num_experts > 0`` makes every block's FFN a top-1-routed
    mixture of experts (ops/moe.py) instead of a dense MLP."""
    assert model_dim % num_heads == 0
    keys = iter(jax.random.split(key, 4 + 5 * num_layers))
    scale = 0.02
    params: Params = {
        "embed": truncated_normal_init(next(keys), (vocab_size, model_dim), scale),
        "pos": truncated_normal_init(next(keys), (max_seq_len, model_dim), scale),
        "blocks": [],
        "final_norm": {"scale": jnp.ones((model_dim,), jnp.float32)},
    }
    ff = 4 * model_dim
    for _ in range(num_layers):
        blk = {
            "ln1": {"scale": jnp.ones((model_dim,), jnp.float32)},
            # [d, 3, d] (not [d, 3d]): the last dim is the shardable
            # per-head output dim, so a model-axis column shard keeps
            # whole q/k/v head groups together
            "wqkv": truncated_normal_init(next(keys), (model_dim, 3, model_dim), scale),
            "wo": truncated_normal_init(next(keys), (model_dim, model_dim), scale),
            "ln2": {"scale": jnp.ones((model_dim,), jnp.float32)},
        }
        if num_experts > 0:
            blk["router"] = truncated_normal_init(
                next(keys), (model_dim, num_experts), scale)
            k1, k2 = jax.random.split(next(keys))
            blk["w1"] = truncated_normal_init(k1, (num_experts, model_dim, ff), scale)
            blk["w2"] = truncated_normal_init(k2, (num_experts, ff, model_dim), scale)
        else:
            blk["w1"] = truncated_normal_init(next(keys), (model_dim, ff), scale)
            blk["w2"] = truncated_normal_init(next(keys), (ff, model_dim), scale)
        params["blocks"].append(blk)
    return params


def param_partition_specs(num_layers: int, model_axis: str | None,
                          num_experts: int = 0,
                          expert_axis: str | None = None) -> Params:
    """Mesh placement for the flat (per-layer list) layout.

    ``model_axis`` (TP) → Megatron layout: qkv & MLP-in column-parallel
    (output dim sharded), their consumers wo & MLP-out row-parallel
    (input dim sharded → one psum each per block); embeddings and norms
    replicated.

    ``expert_axis`` (EP, num_experts > 0) → w1/w2's leading EXPERT dim
    sharded; the router stays replicated. The two compose: EP picks
    which experts a rank holds, TP splits each expert's hidden dim (and
    the attention heads) across the model axis."""
    P = PartitionSpec
    m = model_axis  # None → replicated on the TP dims
    if num_experts > 0:
        e = expert_axis
        blk = {
            "ln1": {"scale": P()},
            "wqkv": P(None, None, m),
            "wo": P(m, None),
            "ln2": {"scale": P()}, "router": P(),
            "w1": P(e, None, m),
            "w2": P(e, m, None),
        }
    else:
        blk = {
            "ln1": {"scale": P()},
            "wqkv": P(None, None, m),
            "wo": P(m, None),
            "ln2": {"scale": P()},
            "w1": P(None, m),
            "w2": P(m, None),
        }
    return {"embed": P(), "pos": P(), "blocks": [dict(blk) for _ in range(num_layers)],
            "final_norm": {"scale": P()}}


def _rms_norm(x: jax.Array, p: Params) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6) * p["scale"]).astype(x.dtype)


def apply(params: Params, tokens: jax.Array, *, num_heads: int = 4,
          attention_fn: Callable | None = None,
          positions: jax.Array | None = None,
          compute_dtype=jnp.bfloat16,
          model_axis: str | None = None,
          expert_axis: str | None = None, num_experts: int = 0,
          capacity_factor: float = 1.25, remat: bool = False,
          remat_policy: str = "full",
          moe_num_groups: int = 0, moe_router_top_k: int = 1,
          moe_stats_axes: tuple[str, ...] = (),
          return_aux: bool = False) -> jax.Array:
    """tokens [batch, seq] int32 → logits [batch, seq, vocab] float32.

    ``positions`` (global positions of this shard's tokens) must be
    passed when the sequence is sharded; defaults to arange(seq).

    ``model_axis``: when set (inside shard_map, params sharded per
    :func:`param_partition_specs`), runs tensor-parallel — this rank
    computes its ``num_heads / axis_size`` heads and its MLP column
    slice; row-parallel projections psum partial sums back to the full
    residual. Activations stay replicated over the axis, so the logits
    (and any loss) are identical on every TP rank.

    ``expert_axis``/``num_experts``: mixture-of-experts FFNs with the
    experts sharded over the axis (expert parallelism). Composes with
    ``model_axis``: heads and every expert's hidden dim are
    tensor-parallel over the model axis, experts over the expert axis,
    with one fused psum per MoE block covering both.
    ``moe_stats_axes``: extra token-sharding axes (the seq axis under
    SP×MoE) the load-balance statistics average over, so the aux loss
    is the full-token value replicated on every shard.
    ``return_aux``: also return the summed load-balancing aux loss.
    """
    attn = attention_fn or local_self_attention
    b, s = tokens.shape
    if positions is None:
        positions = jnp.arange(s)
    p = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    x = p["embed"][tokens] + p["pos"][positions]
    d = x.shape[-1]
    hd = d // num_heads
    m = lax.axis_size(model_axis) if model_axis else 1
    if num_heads % m != 0:
        raise ValueError(f"num_heads={num_heads} not divisible by "
                         f"model-parallel size {m}")
    h_local = num_heads // m

    def ffn(x, blk):
        return _ffn_sublayer(x, blk, model_axis=model_axis,
                             expert_axis=expert_axis,
                             num_experts=num_experts,
                             capacity_factor=capacity_factor,
                             moe_num_groups=moe_num_groups,
                             moe_router_top_k=moe_router_top_k,
                             moe_stats_axes=moe_stats_axes)

    def block(x, blk):
        x = _attn_sublayer(x, blk, h_local=h_local, hd=hd, attn=attn,
                           model_axis=model_axis)
        return ffn(x, blk)

    if remat:
        if remat_policy == "save_attn":
            # Selective remat: the FFN sublayer (and its norms)
            # recomputes in the backward, but the attention sublayer
            # stays OUTSIDE the checkpoint, so the flash kernel's
            # custom-vjp residuals (q/k/v/out/lse) remain resident and
            # the backward never re-runs the attention forward. Costs
            # O(b·s·d) extra bytes per layer over full remat; at the
            # S=8192 long-context bench it buys 1.14x tokens/sec.
            ffn_ckpt = jax.checkpoint(ffn)

            def block(x, blk):  # noqa: F811 — policy-selected body
                x = _attn_sublayer(x, blk, h_local=h_local, hd=hd,
                                   attn=attn, model_axis=model_axis)
                return ffn_ckpt(x, blk)
        elif remat_policy == "full":
            # trade one extra forward per block for O(layer-boundary)
            # activation memory — the long-sequence HBM lever
            block = jax.checkpoint(block)
        else:
            raise ValueError(f"unknown remat_policy {remat_policy!r} "
                             "(expected 'full' or 'save_attn')")
    aux_total = jnp.zeros((), jnp.float32)
    for blk in p["blocks"]:
        x, aux = block(x, blk)
        aux_total = aux_total + aux
    x = _rms_norm(x, p["final_norm"])
    logits = (x @ p["embed"].T).astype(jnp.float32)  # tied head
    return (logits, aux_total) if return_aux else logits


def _attn_sublayer(x: jax.Array, blk: Params, *, h_local: int, hd: int,
                   attn: Callable,
                   model_axis: str | None,
                   return_kv: bool = False):
    """Pre-norm attention sublayer: x + wo(attn(qkv(ln1(x)))).

    ``return_kv``: also return this layer's K/V in the [b, s, h, hd]
    residual layout (a free reshape) — what the decode prefill scatters
    into the paged KV cache."""
    b = x.shape[0]
    h = _rms_norm(x, blk["ln1"])
    qkv = jnp.einsum("bsd,dte->bste", h, blk["wqkv"])  # e = d/m
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if getattr(attn, "layout", "bhsd") == "bshd":
        # kernel reads the residual layout directly ([b, s, h, hd] is a
        # free reshape of [b, s, e]) — no head transpose on either side.
        # At the flash bench shape the transposes a bhsd attention
        # forces cost ~20 ms/step, 2.5× the kernel itself. (A fully
        # fused qkv-packed kernel input was also measured: the strided
        # k/v lane reads cost MORE than the slice copies they save.)
        bshd = lambda t: t.reshape(b, -1, h_local, hd)
        o = attn(bshd(q), bshd(k), bshd(v)).reshape(b, -1, h_local * hd)
    else:
        def heads(t):
            return t.reshape(b, -1, h_local, hd).transpose(0, 2, 1, 3)

        o = attn(heads(q), heads(k), heads(v))
        o = o.transpose(0, 2, 1, 3).reshape(b, -1, h_local * hd)
    proj = o @ blk["wo"]  # row-parallel: partial sum of the full d
    if model_axis:
        proj = lax.psum(proj, model_axis)
    out = x + proj
    if return_kv:
        return (out, k.reshape(b, -1, h_local, hd),
                v.reshape(b, -1, h_local, hd))
    return out


def _ffn_sublayer(x: jax.Array, blk: Params, *, model_axis: str | None,
                  expert_axis: str | None = None, num_experts: int = 0,
                  capacity_factor: float = 1.25, moe_num_groups: int = 0,
                  moe_router_top_k: int = 1,
                  moe_stats_axes: tuple[str, ...] = ()) -> tuple[jax.Array,
                                                                 jax.Array]:
    """Pre-norm FFN sublayer (dense or MoE): x + mlp(ln2(x)), aux."""
    h = _rms_norm(x, blk["ln2"])
    if "router" in blk:
        from ..ops.moe import moe_ffn
        mlp, aux = moe_ffn(h, blk["router"], blk["w1"], blk["w2"],
                           num_experts=num_experts,
                           capacity_factor=capacity_factor,
                           router_top_k=moe_router_top_k,
                           num_groups=moe_num_groups,
                           expert_axis=expert_axis,
                           tp_axis=model_axis,
                           stats_axes=moe_stats_axes)
    else:
        mlp = jax.nn.relu(h @ blk["w1"]) @ blk["w2"]
        aux = jnp.zeros((), jnp.float32)
        if model_axis:
            mlp = lax.psum(mlp, model_axis)
    return x + mlp, aux


def _apply_block(x: jax.Array, blk: Params, *, h_local: int, hd: int,
                 attn: Callable, model_axis: str | None,
                 expert_axis: str | None = None, num_experts: int = 0,
                 capacity_factor: float = 1.25,
                 moe_num_groups: int = 0, moe_router_top_k: int = 1,
                 moe_stats_axes: tuple[str, ...] = ()) -> tuple[jax.Array, jax.Array]:
    """One pre-norm transformer block (shared by the dense/TP loop, the
    pipeline stage scans, and the 1F1B chunk bodies). Returns
    (x, moe_aux) — aux is 0 for dense-FFN blocks, else the mean
    per-group load-balance loss of this block's routing (linear across
    blocks/ticks/shards: callers sum over layers and average over
    microbatches)."""
    x = _attn_sublayer(x, blk, h_local=h_local, hd=hd, attn=attn,
                       model_axis=model_axis)
    return _ffn_sublayer(x, blk, model_axis=model_axis,
                         expert_axis=expert_axis, num_experts=num_experts,
                         capacity_factor=capacity_factor,
                         moe_num_groups=moe_num_groups,
                         moe_router_top_k=moe_router_top_k,
                         moe_stats_axes=moe_stats_axes)


# ---------------------------------------------------------------------------
# Autoregressive decode: prompt prefill with K/V export + one-token
# incremental step over a paged KV cache (servesvc/decode.py)
# ---------------------------------------------------------------------------

_DECODE_NEG = -1e30  # finite mask value: an all-masked idle slot's
# softmax degrades to uniform-over-garbage (ignored) instead of NaN


def prefill_with_kv(params: Params, tokens: jax.Array, *,
                    num_heads: int = 4,
                    attention_fn: Callable | None = None,
                    positions: jax.Array | None = None,
                    compute_dtype=jnp.bfloat16
                    ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Prompt prefill: the standard causal forward (through the
    CONFIGURED attention kernel — the fused pallas flash path or dense)
    that also returns every layer's K/V for seeding a decode cache.

    tokens [b, s] int32 → (logits [b, s, vocab] float32,
    k [L, b, s, h, hd], v [L, b, s, h, hd]) with K/V in the compute
    dtype (the cache dtype). Dense-FFN models only (MoE routing is
    batch-shaped; the registry never exports decode for it)."""
    attn = attention_fn or local_self_attention
    b, s = tokens.shape
    if positions is None:
        positions = jnp.arange(s)
    p = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    x = p["embed"][tokens] + p["pos"][positions]
    d = x.shape[-1]
    hd = d // num_heads
    ks, vs = [], []
    for blk in p["blocks"]:
        x, k, v = _attn_sublayer(x, blk, h_local=num_heads, hd=hd,
                                 attn=attn, model_axis=None,
                                 return_kv=True)
        ks.append(k)
        vs.append(v)
        x, _ = _ffn_sublayer(x, blk, model_axis=None)
    x = _rms_norm(x, p["final_norm"])
    logits = (x @ p["embed"].T).astype(jnp.float32)
    return logits, jnp.stack(ks), jnp.stack(vs)


def decode_step(params: Params, tokens: jax.Array, positions: jax.Array,
                k_cache: jax.Array, v_cache: jax.Array,
                block_tables: jax.Array, lengths: jax.Array, *,
                num_heads: int = 4, block_size: int = 16,
                compute_dtype=jnp.bfloat16,
                attention_kernel: str = "dense"
                ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One incremental decode step over S slots sharing one paged KV
    cache — the single compiled shape every in-flight sequence runs
    in, whatever its length.

    * ``tokens`` [S] int32 — each slot's newest token,
    * ``positions`` [S] — that token's 0-based sequence position,
    * ``k_cache``/``v_cache`` [L, N, B, h, hd] — the paged cache
      (N blocks of B positions; block 0 is the reserved null block),
    * ``block_tables`` [S, P] int32 — each slot's position→block map
      (idle slots: all zeros),
    * ``lengths`` [S] — context length INCLUDING this token
      (``positions + 1``; 0 for idle slots, whose rows compute masked
      garbage the caller ignores).

    ``attention_kernel`` selects the cache read: ``"dense"`` gathers
    every table entry into a [S, max_context, h, hd] view (the oracle
    path — O(max context) traffic per token), ``"paged"`` runs the
    fused Pallas kernel that walks the table in-kernel (O(actual
    context); see ops/pallas_paged_attention.py). Both share the
    pinned numerics below; parity across them is tested in
    tests/test_paged_attention.py.

    Returns (logits [S, vocab] float32, k_cache, v_cache) with this
    token's K/V written at its block/offset. Attention numerics match
    ``local_self_attention`` (f32 scores/softmax, 1/sqrt(hd) scale),
    so greedy decode through the cache reproduces the full-context
    forward (pinned in tests/test_decode.py)."""
    if attention_kernel not in ("dense", "paged"):
        raise ValueError(
            f"decode.attention_kernel must be 'dense' or 'paged', "
            f"got {attention_kernel!r}")
    p = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    num_slots = tokens.shape[0]
    x = p["embed"][tokens] + p["pos"][positions]  # [S, d]
    d = x.shape[-1]
    hd = d // num_heads
    scale = 1.0 / (hd ** 0.5)
    ctx = block_tables.shape[1] * block_size
    ctx_pos = jnp.arange(ctx)
    blk_ids = jnp.take_along_axis(
        block_tables, (positions // block_size)[:, None], axis=1)[:, 0]
    offs = positions % block_size
    live = ctx_pos[None, :] < lengths[:, None]  # [S, ctx]
    for li, blk in enumerate(p["blocks"]):
        h = _rms_norm(x, blk["ln1"])
        qkv = jnp.einsum("sd,dte->ste", h, blk["wqkv"])
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [S, d]
        kh = k.reshape(num_slots, num_heads, hd)
        vh = v.reshape(num_slots, num_heads, hd)
        k_cache = k_cache.at[li, blk_ids, offs].set(
            kh.astype(k_cache.dtype))
        v_cache = v_cache.at[li, blk_ids, offs].set(
            vh.astype(v_cache.dtype))
        qh = q.reshape(num_slots, num_heads, hd)
        if attention_kernel == "paged":
            # fused path: the kernel walks the block table itself, so
            # per-token traffic is O(actual context) — no dense view
            from ..ops.pallas_paged_attention import paged_attention
            o = paged_attention(qh, k_cache[li], v_cache[li],
                                block_tables, lengths, scale=scale)
        else:
            # gather the slot's pages into one dense context view: the
            # block table IS the indirection, so this read is identical
            # for a 3-token and a 90-token sequence — one compiled shape
            kp = k_cache[li][block_tables].reshape(
                num_slots, ctx, num_heads, hd)
            vp = v_cache[li][block_tables].reshape(
                num_slots, ctx, num_heads, hd)
            scores = jnp.einsum("shd,skhd->shk", qh.astype(jnp.float32),
                                kp.astype(jnp.float32)) * scale
            scores = jnp.where(live[:, None, :], scores, _DECODE_NEG)
            w = jax.nn.softmax(scores, axis=-1)
            o = jnp.einsum("shk,skhd->shd", w, vp.astype(jnp.float32))
        o = o.astype(compute_dtype).reshape(num_slots, d)
        x = x + o @ blk["wo"]
        x, _ = _ffn_sublayer(x, blk, model_axis=None)
    x = _rms_norm(x, p["final_norm"])
    logits = (x @ p["embed"].T).astype(jnp.float32)
    return logits, k_cache, v_cache


# ---------------------------------------------------------------------------
# Pipeline parallelism: layer-stacked params + microbatched apply
# ---------------------------------------------------------------------------

def stack_block_params(params: Params) -> Params:
    """Convert ``blocks`` from a list of per-layer dicts to one dict of
    leaves stacked on a leading layer dim — the shardable layout for a
    mesh ``stage`` axis (layer dim split across stages)."""
    blocks = params["blocks"]
    stacked = jax.tree.map(lambda *leaves: jnp.stack(leaves), *blocks)
    return {**{k: v for k, v in params.items() if k != "blocks"},
            "blocks": stacked}


def pp_param_partition_specs(stage_axis: str,
                             model_axis: str | None = None,
                             num_experts: int = 0,
                             expert_axis: str | None = None) -> Params:
    """Stacked-layout specs: block leaves sharded on the layer dim over
    the stage axis; embeddings/norms replicated (their gradients psum
    over stages via the AD transpose of the replication).

    ``model_axis`` composes Megatron TP inside each stage: the same
    column/row dims as :func:`param_partition_specs`, one position to
    the right of the stacked layer dim (PP outermost, TP within the
    stage's layer slice). ``expert_axis`` (MoE, num_experts > 0)
    additionally shards each block's expert dim — PP picks the layer,
    EP the expert, TP the expert's hidden slice."""
    P = PartitionSpec
    m = model_axis  # None → replicated on the TP dims
    if num_experts > 0:
        e = expert_axis
        blk = {"ln1": {"scale": P(stage_axis)},
               "wqkv": P(stage_axis, None, None, m),
               "wo": P(stage_axis, m, None),
               "ln2": {"scale": P(stage_axis)},
               "router": P(stage_axis),
               "w1": P(stage_axis, e, None, m),
               "w2": P(stage_axis, e, m, None)}
    else:
        blk = {"ln1": {"scale": P(stage_axis)},
               "wqkv": P(stage_axis, None, None, m),
               "wo": P(stage_axis, m, None),
               "ln2": {"scale": P(stage_axis)},
               "w1": P(stage_axis, None, m),
               "w2": P(stage_axis, m, None)}
    return {"embed": P(), "pos": P(), "blocks": blk,
            "final_norm": {"scale": P()}}


def apply_pp(params: Params, tokens: jax.Array, *, num_heads: int,
             stage_axis: str, num_microbatches: int,
             attention_fn: Callable | None = None,
             positions: jax.Array | None = None,
             model_axis: str | None = None,
             expert_axis: str | None = None, num_experts: int = 0,
             capacity_factor: float = 1.25,
             moe_num_groups: int = 0, moe_router_top_k: int = 1,
             moe_stats_axes: tuple[str, ...] = (),
             compute_dtype=jnp.bfloat16, remat: bool = False,
             return_aux: bool = False) -> jax.Array:
    """Pipeline-parallel forward (inside shard_map, params in the
    stacked layout with block leaves sharded over ``stage_axis``).

    The batch is split into ``num_microbatches``; each stage scans its
    local layer slice; activations flow via the microbatch pipeline
    (ops/pipeline.py). Embedding/head run replicated on every stage —
    outputs are stage-replicated logits, so loss code is unchanged.

    ``model_axis`` composes tensor parallelism INSIDE each stage: block
    params additionally carry Megatron column/row shards
    (``pp_param_partition_specs(stage, model)``), each rank computes its
    head/MLP slice, and the row-parallel psums inside ``_apply_block``
    reassemble activations per tick — PP outermost, TP within.

    Sequence parallelism composes through ``attention_fn`` +
    ``positions``: pass a seq-sharded attention (ring/Ulysses over the
    seq axis) and this shard's global positions; every (stage, seq)
    device runs the same tick schedule, so the attention collectives
    stay lockstep inside the pipeline scan — bubbles included.

    Mixture-of-experts (``num_experts > 0``, optionally expert-sharded
    over ``expert_axis``) composes too: each tick's MoE calls run the
    grouped dispatch on that microbatch's tokens, all-to-alls lockstep
    across stages since every device runs every tick. Token groups nest
    inside sequence rows (ops/moe.py), so routing capacity, drops, and
    the per-group aux are IDENTICAL for every microbatch count — the
    aux is linear in per-group contributions, so each real tick's aux
    simply accumulates (pipeline_apply ``with_stats``, bubbles masked)
    and the mean over microbatches equals the dense full-batch value
    exactly. ``return_aux`` returns it. ``moe_stats_axes``: extra
    token-sharding axes (the seq axis under PP×SP×EP) each call's aux
    additionally pmeans over.
    """
    from ..ops.pipeline import pipeline_apply

    attn = attention_fn or local_self_attention
    b, s = tokens.shape
    if b % num_microbatches != 0:
        raise ValueError(f"batch {b} not divisible by "
                         f"num_microbatches={num_microbatches}")
    if positions is None:
        positions = jnp.arange(s)
    p = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    d = p["embed"].shape[-1]
    hd = d // num_heads
    m = lax.axis_size(model_axis) if model_axis else 1
    if num_heads % m != 0:
        raise ValueError(f"num_heads={num_heads} not divisible by "
                         f"model-parallel size {m}")
    x = p["embed"][tokens] + p["pos"][positions]
    mb = b // num_microbatches
    micro = x.reshape(num_microbatches, mb, s, d)

    moe = num_experts > 0

    def stage_fn(act):
        def layer(carry, blk):
            out, aux_l = _apply_block(carry, blk, h_local=num_heads // m,
                                      hd=hd, attn=attn,
                                      model_axis=model_axis,
                                      expert_axis=expert_axis,
                                      num_experts=num_experts,
                                      capacity_factor=capacity_factor,
                                      moe_num_groups=moe_num_groups,
                                      moe_router_top_k=moe_router_top_k,
                                      moe_stats_axes=moe_stats_axes)
            return out, (aux_l if moe else None)

        if remat:
            layer = jax.checkpoint(layer)
        out, aux_layers = lax.scan(layer, act, p["blocks"])
        # aux_layers: per-LOCAL-layer mean-per-group aux [L_local] (MoE)
        return (out, aux_layers) if moe else out

    if moe:
        out, aux_layers = pipeline_apply(stage_fn, micro, stage_axis,
                                         with_stats=True)
        # pipeline_apply averaged each layer's aux over the real ticks
        # (= over microbatches — exact, the aux is per-group linear);
        # stages hold disjoint layers, so one psum totals the model
        aux = lax.psum(jnp.sum(aux_layers.astype(jnp.float32)), stage_axis)
    else:
        out = pipeline_apply(stage_fn, micro, stage_axis)
        aux = jnp.zeros((), jnp.float32)
    x = out.reshape(b, s, d)
    x = _rms_norm(x, p["final_norm"])
    logits = (x @ p["embed"].T).astype(jnp.float32)
    return (logits, aux) if return_aux else logits


def stack_block_params_chunked(params: Params, num_stages: int,
                               num_chunks: int) -> Params:
    """Chunk-interleaved stacking for the 1F1B schedule: like
    :func:`stack_block_params`, but layer ORDER is permuted so that the
    contiguous stage shard of device ``d`` holds global chunks
    ``{d, S+d, …, (v-1)·S+d}`` (slot-major: [slot j, layers of chunk
    j·S+d]) — the placement the interleaved schedule's ring traversal
    requires (ops/pipeline.py). Sharding specs are unchanged
    (:func:`pp_param_partition_specs`); only the order differs.
    """
    blocks = params["blocks"]
    L = len(blocks)
    if L % (num_stages * num_chunks):
        raise ValueError(
            f"num_layers={L} not divisible by stages×chunks="
            f"{num_stages}×{num_chunks}")
    per = L // (num_stages * num_chunks)
    order = [c * per + l
             for d in range(num_stages)
             for j in range(num_chunks)
             for c in [j * num_stages + d]
             for l in range(per)]
    stacked = jax.tree.map(lambda *leaves: jnp.stack(leaves),
                           *[blocks[i] for i in order])
    return {**{k: v for k, v in params.items() if k != "blocks"},
            "blocks": stacked}


def grads_pp_1f1b(params: Params, tokens: jax.Array, labels: jax.Array, *,
                  num_heads: int, stage_axis: str, num_microbatches: int,
                  num_chunks: int, attention_fn: Callable | None = None,
                  model_axis: str | None = None,
                  seq_axis: str | None = None,
                  expert_axis: str | None = None, num_experts: int = 0,
                  capacity_factor: float = 1.25,
                  moe_num_groups: int = 0, moe_router_top_k: int = 1,
                  aux_weight: float = 0.0,
                  compute_dtype=jnp.bfloat16):
    """Fused interleaved-1F1B training step body (inside shard_map,
    params in the chunk-interleaved stacked layout of
    :func:`stack_block_params_chunked`).

    Unlike :func:`apply_pp` + AD (the GPipe path), forward and backward
    chunk-works interleave inside ONE scan (ops/pipeline.py:
    pipeline_1f1b_grads), shrinking the pipeline bubble by the chunk
    factor; the backward recomputes each chunk from its saved input
    (rematerialization built in). Embedding/positions run replicated
    outside the pipeline; their gradients combine the lookup transpose
    (via the banked input-cotangents) with the tied head's
    contribution. Returns (loss, train_acc, grads) with ``grads``
    matching the parameter layout.

    ``model_axis`` composes Megatron TP inside every chunk and
    ``seq_axis`` composes SP (a seq-sharded ``attention_fn`` +
    cross-shard partial loss). Chunk-internal collectives execute
    INSIDE the engine's device-varying ``lax.switch`` branches; that is
    safe exactly when the collective's runtime rendezvous is
    GROUP-LOCAL and its participant group shares one stage coordinate
    (so every participant takes the same branch each tick): psum /
    all_to_all over the model, seq, or expert axes qualify. It is NOT
    safe for ``lax.ppermute`` — XLA lowers collective-permute with a
    GLOBAL participant list, so devices on other stages (in other
    branches) would be waited on forever (measured deadlock on the CPU
    backend's rendezvous). Hence SP under this schedule requires the
    all-to-all (Ulysses) attention — the registry refuses ring — and
    the cross-shard target shift runs OUTSIDE the engine, below.
    Stage-axis collectives stay forbidden in branches entirely (the
    engine's lockstep ppermutes handle stage transfer).

    Under SP the returned loss/accuracy/grads are this seq shard's
    PARTIALS (normalized so a psum over the seq axis reassembles the
    exact dense values — same contract as the GPipe PP×SP path); the
    caller performs that psum.

    ``expert_axis``/``num_experts`` compose mixture-of-experts: the
    per-row-group aux (ops/moe.py) is LINEAR across chunks and
    microbatches, so each chunk returns its summed layer aux, the
    engine accumulates it over forward works and seeds each backward
    chunk's aux output with the constant weight — no cross-chunk
    statistics. The returned loss includes the aux term.
    """
    from ..ops.pipeline import pipeline_1f1b_grads

    attn = attention_fn or local_self_attention
    b, s_loc = tokens.shape
    if b % num_microbatches != 0:
        raise ValueError(f"batch {b} not divisible by "
                         f"num_microbatches={num_microbatches}")
    m_tp = lax.axis_size(model_axis) if model_axis else 1
    if num_heads % m_tp != 0:
        raise ValueError(f"num_heads={num_heads} not divisible by "
                         f"model-parallel size {m_tp}")
    n_seq = lax.axis_size(seq_axis) if seq_axis else 1
    p = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    d = p["embed"].shape[-1]
    hd = d // num_heads
    if seq_axis is not None:
        positions = lax.axis_index(seq_axis) * s_loc + jnp.arange(s_loc)
    else:
        positions = jnp.arange(s_loc)
    mb = b // num_microbatches
    M = num_microbatches

    def emb_fn(embed, pos):
        return (embed[tokens] + pos[positions]).reshape(M, mb, s_loc, d)

    micro, emb_vjp = jax.vjp(emb_fn, p["embed"], p["pos"])

    L_local = jax.tree.leaves(p["blocks"])[0].shape[0]
    per = L_local // num_chunks
    chunk_params = jax.tree.map(
        lambda a: a.reshape((num_chunks, per) + a.shape[1:]), p["blocks"])

    moe = num_experts > 0
    moe_stats_axes = (seq_axis,) if (moe and seq_axis is not None) else ()

    def chunk_fn(slot_params, act):
        def layer(carry, blk):
            out, aux_l = _apply_block(carry, blk, h_local=num_heads // m_tp,
                                      hd=hd, attn=attn,
                                      model_axis=model_axis,
                                      expert_axis=expert_axis,
                                      num_experts=num_experts,
                                      capacity_factor=capacity_factor,
                                      moe_num_groups=moe_num_groups,
                                      moe_router_top_k=moe_router_top_k,
                                      moe_stats_axes=moe_stats_axes)
            return out, (aux_l if moe else None)
        out, aux_layers = lax.scan(layer, act, slot_params)
        return (out, jnp.sum(aux_layers)) if moe else out

    labels_mb = labels.reshape(M, mb, s_loc)
    head_params = {"embed": p["embed"], "final_norm": p["final_norm"]}

    if seq_axis is None:
        def head_fn(hp, y, m):
            x = _rms_norm(y, hp["final_norm"])
            logits = (x @ hp["embed"].T).astype(jnp.float32)
            lab = lax.dynamic_index_in_dim(labels_mb, m, 0, keepdims=False)
            return loss_fn(logits, lab), accuracy(logits, lab)
    else:
        # the SP partial loss (same math as parallel.api.make_sp_loss):
        # shard j's last-token target lives on shard j+1. The fetching
        # ppermute must run OUT HERE, unconditionally on every device —
        # collective-permute rendezvouses globally and would deadlock
        # inside the engine's stage-varying branches (docstring above).
        s_global = s_loc * n_seq
        seq_perm = [((j + 1) % n_seq, j) for j in range(n_seq)]
        nxt = lax.ppermute(labels[:, :1], seq_axis, seq_perm)
        tgt_mb = jnp.concatenate([labels[:, 1:], nxt],
                                 axis=1).astype(jnp.int32).reshape(M, mb,
                                                                   s_loc)

        def head_fn(hp, y, m):
            x = _rms_norm(y, hp["final_norm"])
            logits = (x @ hp["embed"].T).astype(jnp.float32)
            tgt = lax.dynamic_index_in_dim(tgt_mb, m, 0, keepdims=False)
            # this microbatch's global valid-token count normalizes the
            # partials (shared kernel with the GPipe/DP SP loss path)
            return sp_partial_token_loss(logits, tgt, positions, s_global,
                                         mb * (s_global - 1))

    # The backward aux seed is the FULL weight: the aux primal is the
    # pmean over (expert, seq) of per-shard contributions, and the
    # pmean's transpose (cotangent/n per shard) composed with the
    # caller's psum-over-seq of grads already yields exactly
    # aux_weight·d(aux)/dθ — pre-dividing the SEED (as the loss VALUE
    # must be, below) would undercount aux gradients by n_seq.
    if moe:
        losses, accs, dinputs, dchunk, dhead, aux_sum = pipeline_1f1b_grads(
            chunk_fn, head_fn, chunk_params, head_params, micro,
            stage_axis, num_chunks, with_aux=True,
            aux_cotangent=aux_weight)
    else:
        losses, accs, dinputs, dchunk, dhead = pipeline_1f1b_grads(
            chunk_fn, head_fn, chunk_params, head_params, micro,
            stage_axis, num_chunks)
    # the engine seeds every microbatch's loss with cotangent 1.0 (sum
    # convention); the step's loss is the MEAN over microbatches
    scale = 1.0 / M
    dinputs = dinputs * jnp.asarray(scale, dinputs.dtype)
    dchunk = jax.tree.map(lambda a: a * jnp.asarray(scale, a.dtype), dchunk)
    dhead = jax.tree.map(lambda a: a * jnp.asarray(scale, a.dtype), dhead)

    demb_lookup, dpos = emb_vjp(dinputs.astype(micro.dtype))
    grads = {
        "embed": demb_lookup + dhead["embed"],  # lookup + tied head
        "pos": dpos,
        "blocks": jax.tree.map(
            lambda a: a.reshape((L_local,) + a.shape[2:]), dchunk),
        "final_norm": dhead["final_norm"],
    }
    # the engine differentiates the compute-dtype cast of the params;
    # apply the cast's transpose so grads match the master param dtypes
    grads = jax.tree.map(lambda g, p0: g.astype(p0.dtype), grads, params)
    loss = jnp.mean(losses)
    if moe:
        # the VALUE term pre-divides by n_seq (the aux is already the
        # full pmean'd value on every shard; the caller's psum over the
        # seq axis reassembles exactly one copy — make_sp_loss's
        # aux/n_seq convention)
        loss = loss + (aux_weight / n_seq) * aux_sum * scale
    return loss, jnp.mean(accs), grads


def apply_pp_1f1b(params: Params, tokens: jax.Array, *, num_heads: int,
                  stage_axis: str, num_microbatches: int, num_chunks: int,
                  attention_fn: Callable | None = None,
                  model_axis: str | None = None,
                  expert_axis: str | None = None, num_experts: int = 0,
                  capacity_factor: float = 1.25,
                  moe_num_groups: int = 0, moe_router_top_k: int = 1,
                  compute_dtype=jnp.bfloat16) -> jax.Array:
    """Forward-only apply for the chunk-interleaved layout (eval under
    schedule="1f1b"): the chunked ring (ops/pipeline.py:
    pipeline_chunked_forward) with embedding/head outside, same
    contract as :func:`apply_pp`. ``model_axis`` composes Megatron TP
    and ``expert_axis`` MoE expert sharding inside each chunk — the
    forward ring computes every chunk unconditionally (``jnp.where``
    select, not a branch), so the TP psums / EP all-to-alls run
    lockstep on every device every tick."""
    from ..ops.pipeline import pipeline_chunked_forward

    attn = attention_fn or local_self_attention
    b, s = tokens.shape
    if b % num_microbatches != 0:
        raise ValueError(f"batch {b} not divisible by "
                         f"num_microbatches={num_microbatches}")
    m_tp = lax.axis_size(model_axis) if model_axis else 1
    if num_heads % m_tp != 0:
        raise ValueError(f"num_heads={num_heads} not divisible by "
                         f"model-parallel size {m_tp}")
    p = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    d = p["embed"].shape[-1]
    hd = d // num_heads
    x = p["embed"][tokens] + p["pos"][jnp.arange(s)]
    mb = b // num_microbatches
    micro = x.reshape(num_microbatches, mb, s, d)

    L_local = jax.tree.leaves(p["blocks"])[0].shape[0]
    per = L_local // num_chunks
    chunk_params = jax.tree.map(
        lambda a: a.reshape((num_chunks, per) + a.shape[1:]), p["blocks"])

    def chunk_fn(act, slot):
        from ..ops.pipeline import _index_pytree
        slot_params = _index_pytree(chunk_params, slot)

        def layer(carry, blk):
            out, _aux = _apply_block(carry, blk, h_local=num_heads // m_tp,
                                     hd=hd, attn=attn,
                                     model_axis=model_axis,
                                     expert_axis=expert_axis,
                                     num_experts=num_experts,
                                     capacity_factor=capacity_factor,
                                     moe_num_groups=moe_num_groups,
                                     moe_router_top_k=moe_router_top_k)
            return out, None
        out, _ = lax.scan(layer, act, slot_params)
        return out

    out = pipeline_chunked_forward(chunk_fn, micro, stage_axis, num_chunks)
    x = out.reshape(b, s, d)
    x = _rms_norm(x, p["final_norm"])
    logits = x @ p["embed"].T
    return logits.astype(jnp.float32)


def sp_partial_token_loss(logits: jax.Array, tgt: jax.Array,
                          positions: jax.Array, s_global: int,
                          total: int) -> tuple[jax.Array, jax.Array]:
    """The sequence-parallel partial next-token (loss, accuracy) kernel
    — the ONE implementation both SP consumers share (the train step's
    ``make_sp_loss`` in parallel/api.py and the 1F1B engine's seed-tick
    head above), so the masking/normalization conventions cannot drift
    between schedules.

    Args: ``logits`` [b, s_loc, V] this shard's logits; ``tgt``
    [b, s_loc] the already-shifted global targets (the caller fetches
    the cross-shard column); ``positions`` this shard's global
    positions; ``total`` the GLOBAL valid-token count the partial sums
    normalize by — psum over the seq axis of the returned pair equals
    the dense ``loss_fn``/``accuracy`` exactly.
    """
    w = (positions < s_global - 1).astype(jnp.float32)[None, :]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    correct = (jnp.argmax(logp, axis=-1) == tgt).astype(jnp.float32)
    return jnp.sum(nll * w) / total, jnp.sum(correct * w) / total


def loss_fn(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Next-token mean xent. ``labels`` are the input tokens; targets
    are labels shifted left (last position dropped)."""
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    tgt = labels[:, 1:].astype(jnp.int32)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    pred = jnp.argmax(logits[:, :-1], axis=-1)
    return jnp.mean((pred == labels[:, 1:]).astype(jnp.float32))
