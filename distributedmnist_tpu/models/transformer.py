"""A compact causal-LM transformer — the long-context model family.

Not a reference-parity model (the reference has no attention anywhere,
SURVEY §5.7); this exists so the framework's sequence-parallel path —
ring attention over the mesh's ``seq`` axis (ops/ring_attention.py) —
has a first-class consumer, and so the aggregation disciplines can be
exercised on a transformer-shaped allreduce payload.

Pure init/apply over a param pytree, pre-norm blocks, learned
positional embeddings, weight-tied LM head. ``attention_fn`` is
injectable: ``local_self_attention`` single-device, or a closure over
``ring_self_attention(axis_name=...)`` under a seq-sharded shard_map.

Tensor parallelism (Megatron-style) is built in: pass ``model_axis``
when params are sharded per :func:`param_partition_specs` — qkv/w1
column-parallel, wo/w2 row-parallel with one psum per residual add,
attention heads split across the axis.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from .cnn import truncated_normal_init
from ..ops.ring_attention import local_self_attention

Params = dict[str, Any]


def init(key: jax.Array, vocab_size: int = 256, model_dim: int = 128,
         num_heads: int = 4, num_layers: int = 2,
         max_seq_len: int = 512) -> Params:
    assert model_dim % num_heads == 0
    keys = iter(jax.random.split(key, 4 + 4 * num_layers))
    scale = 0.02
    params: Params = {
        "embed": truncated_normal_init(next(keys), (vocab_size, model_dim), scale),
        "pos": truncated_normal_init(next(keys), (max_seq_len, model_dim), scale),
        "blocks": [],
        "final_norm": {"scale": jnp.ones((model_dim,), jnp.float32)},
    }
    for _ in range(num_layers):
        params["blocks"].append({
            "ln1": {"scale": jnp.ones((model_dim,), jnp.float32)},
            # [d, 3, d] (not [d, 3d]): the last dim is the shardable
            # per-head output dim, so a model-axis column shard keeps
            # whole q/k/v head groups together
            "wqkv": truncated_normal_init(next(keys), (model_dim, 3, model_dim), scale),
            "wo": truncated_normal_init(next(keys), (model_dim, model_dim), scale),
            "ln2": {"scale": jnp.ones((model_dim,), jnp.float32)},
            "w1": truncated_normal_init(next(keys), (model_dim, 4 * model_dim), scale),
            "w2": truncated_normal_init(next(keys), (4 * model_dim, model_dim), scale),
        })
    return params


def param_partition_specs(num_layers: int, model_axis: str) -> Params:
    """Megatron TP layout: qkv & MLP-in column-parallel (output dim
    sharded), their consumers wo & MLP-out row-parallel (input dim
    sharded → one psum each per block); embeddings and norms replicated."""
    P = PartitionSpec
    blocks = [{
        "ln1": {"scale": P()},
        "wqkv": P(None, None, model_axis),
        "wo": P(model_axis, None),
        "ln2": {"scale": P()},
        "w1": P(None, model_axis),
        "w2": P(model_axis, None),
    } for _ in range(num_layers)]
    return {"embed": P(), "pos": P(), "blocks": blocks,
            "final_norm": {"scale": P()}}


def _rms_norm(x: jax.Array, p: Params) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6) * p["scale"]).astype(x.dtype)


def apply(params: Params, tokens: jax.Array, *, num_heads: int = 4,
          attention_fn: Callable | None = None,
          positions: jax.Array | None = None,
          compute_dtype=jnp.bfloat16,
          model_axis: str | None = None) -> jax.Array:
    """tokens [batch, seq] int32 → logits [batch, seq, vocab] float32.

    ``positions`` (global positions of this shard's tokens) must be
    passed when the sequence is sharded; defaults to arange(seq).

    ``model_axis``: when set (inside shard_map, params sharded per
    :func:`param_partition_specs`), runs tensor-parallel — this rank
    computes its ``num_heads / axis_size`` heads and its MLP column
    slice; row-parallel projections psum partial sums back to the full
    residual. Activations stay replicated over the axis, so the logits
    (and any loss) are identical on every TP rank.
    """
    attn = attention_fn or local_self_attention
    b, s = tokens.shape
    if positions is None:
        positions = jnp.arange(s)
    p = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    x = p["embed"][tokens] + p["pos"][positions]
    d = x.shape[-1]
    hd = d // num_heads
    m = lax.axis_size(model_axis) if model_axis else 1
    if num_heads % m != 0:
        raise ValueError(f"num_heads={num_heads} not divisible by "
                         f"model-parallel size {m}")
    h_local = num_heads // m
    for blk in p["blocks"]:
        h = _rms_norm(x, blk["ln1"])
        qkv = jnp.einsum("bsd,dte->bste", h, blk["wqkv"])  # e = d/m
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

        def heads(t):
            return t.reshape(b, -1, h_local, hd).transpose(0, 2, 1, 3)

        o = attn(heads(q), heads(k), heads(v))
        o = o.transpose(0, 2, 1, 3).reshape(b, -1, d // m)
        proj = o @ blk["wo"]  # row-parallel: partial sum of the full d
        if model_axis:
            proj = lax.psum(proj, model_axis)
        x = x + proj
        h = _rms_norm(x, blk["ln2"])
        mlp = jax.nn.relu(h @ blk["w1"]) @ blk["w2"]
        if model_axis:
            mlp = lax.psum(mlp, model_axis)
        x = x + mlp
    x = _rms_norm(x, p["final_norm"])
    logits = x @ p["embed"].T  # tied head
    return logits.astype(jnp.float32)


def loss_fn(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Next-token mean xent. ``labels`` are the input tokens; targets
    are labels shifted left (last position dropped)."""
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    tgt = labels[:, 1:].astype(jnp.int32)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    pred = jnp.argmax(logits[:, :-1], axis=-1)
    return jnp.mean((pred == labels[:, 1:]).astype(jnp.float32))
