"""ResNet-20 (CIFAR-10) — the stress-config model (BASELINE.json
configs[4]: "CIFAR-10 ResNet-20 sync-replicas allreduce payload").

The reference has no second model family (src/mnist.py is its only
model); this exists to exercise the aggregation path with a ~0.27M-
param allreduce payload and real residual/normalization structure.

TPU-first choices:
* NHWC convs → MXU-tiled XLA HLO; compute in bfloat16, params float32.
* GroupNorm instead of BatchNorm: no running-stats state to
  synchronize across replicas, so the model stays a pure function and
  the train step needs no side state — and accuracy parity for CIFAR
  at this scale is well established.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .cnn import truncated_normal_init

Params = dict[str, Any]

WIDTHS = (16, 32, 64)
BLOCKS_PER_STAGE = 3  # 3 stages × 3 blocks × 2 convs + stem + head = 20 layers


def _conv_init(key: jax.Array, shape: tuple[int, ...]) -> jax.Array:
    # He-style fan-out scaling, truncated. The stddev is computed on
    # host (float32, same IEEE sqrt the old jnp scalar produced
    # bit-for-bit) so init is traceable under jax.eval_shape — the
    # partition-rule engine maps rules over abstract param shapes.
    fan_out = shape[0] * shape[1] * shape[3]
    stddev = float(np.sqrt(np.float32(2.0 / fan_out)))
    return truncated_normal_init(key, shape, stddev=stddev)


def init(key: jax.Array, num_classes: int = 10, num_channels: int = 3) -> Params:
    keys = iter(jax.random.split(key, 64))
    params: Params = {
        "stem": {"w": _conv_init(next(keys), (3, 3, num_channels, WIDTHS[0]))},
        "stem_norm": _norm_init(WIDTHS[0]),
        "stages": [],
    }
    in_ch = WIDTHS[0]
    for width in WIDTHS:
        stage = []
        for b in range(BLOCKS_PER_STAGE):
            block = {
                "conv1": {"w": _conv_init(next(keys), (3, 3, in_ch, width))},
                "norm1": _norm_init(width),
                "conv2": {"w": _conv_init(next(keys), (3, 3, width, width))},
                "norm2": _norm_init(width),
            }
            if in_ch != width:
                block["proj"] = {"w": _conv_init(next(keys), (1, 1, in_ch, width))}
            stage.append(block)
            in_ch = width
        params["stages"].append(stage)
    params["head"] = {
        "w": truncated_normal_init(next(keys), (WIDTHS[-1], num_classes), stddev=0.1),
        "b": jnp.zeros((num_classes,), jnp.float32),
    }
    return params


def _norm_init(ch: int) -> Params:
    return {"scale": jnp.ones((ch,), jnp.float32),
            "bias": jnp.zeros((ch,), jnp.float32)}


def _group_norm(x: jax.Array, p: Params, groups: int = 8) -> jax.Array:
    n, h, w, c = x.shape
    g = min(groups, c)
    xg = x.reshape(n, h, w, g, c // g).astype(jnp.float32)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mean) * lax.rsqrt(var + 1e-5)
    out = xg.reshape(n, h, w, c) * p["scale"] + p["bias"]
    return out.astype(x.dtype)


def _conv(x: jax.Array, w: jax.Array, stride: int = 1) -> jax.Array:
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def apply(params: Params, images: jax.Array, *, train: bool = False,
          compute_dtype=jnp.bfloat16) -> jax.Array:
    del train  # no dropout / batch stats
    x = images.astype(compute_dtype)
    p = jax.tree.map(lambda a: a.astype(compute_dtype), params)

    x = _conv(x, p["stem"]["w"])
    x = jax.nn.relu(_group_norm(x, p["stem_norm"]))
    for si, stage in enumerate(p["stages"]):
        for bi, block in enumerate(stage):
            stride = 2 if (si > 0 and bi == 0) else 1
            h = _conv(x, block["conv1"]["w"], stride)
            h = jax.nn.relu(_group_norm(h, block["norm1"]))
            h = _conv(h, block["conv2"]["w"])
            h = _group_norm(h, block["norm2"])
            if "proj" in block:
                x = _conv(x, block["proj"]["w"], stride)
            x = jax.nn.relu(x + h)
    x = x.mean(axis=(1, 2))  # global average pool
    logits = x @ p["head"]["w"] + p["head"]["b"]
    return logits.astype(jnp.float32)
