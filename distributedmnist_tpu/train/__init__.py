from .checkpoint import (latest_checkpoint_step, restore_checkpoint,
                         save_checkpoint)
from .loop import Trainer
from .lr_schedule import constant, decay_steps_for, exponential_decay

__all__ = ["latest_checkpoint_step", "restore_checkpoint", "save_checkpoint",
           "Trainer", "constant", "decay_steps_for", "exponential_decay"]
