"""Full-split evaluation — the single implementation behind both the
in-loop ``Trainer.evaluate`` and the continuous evaluator service
(≙ do_eval, src/nn_eval.py:49-115).

Batches are static-shaped and weight-padded (pad examples carry weight
0) so the jitted eval step compiles once; multi-host runs stripe the
split across processes and psum the (correct, loss, weight) sums so
every example is counted exactly once.
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import numpy as np

from ..core.mesh import Topology
from ..data.device_prefetch import DevicePrefetcher
from ..data.pipeline import device_prefetch_pays, eval_batches


def run_full_eval(eval_fn: Callable, params: Any, topo: Topology, data,
                  batch_size: int = 0, prefetch_depth: int = 2) -> dict[str, float]:
    """Evaluate ``params`` on the whole split; returns accuracy / loss /
    num_examples / seconds. ``batch_size`` 0 picks a throughput-friendly
    default (≤4096, ≥1 row per replica).

    Batches ride the same dispatch-ahead staging as the train loop:
    padding/assembly + H2D for batch *k+1* overlap the eval step on
    batch *k* (``prefetch_depth`` staged ahead; 0 feeds inline — also
    the automatic fallback where a producer thread can't pay)."""
    n = topo.num_replicas
    hosts = jax.process_count()
    bs = batch_size or max(n, min(4096, data.num_examples))
    t0 = time.time()
    correct = loss_sum = weight = 0.0
    num_examples = 0.0  # counted from batch weights: for LM models the
    # eval_fn weight sum is a TOKEN count (lm_eval_metrics), which is
    # the right normalizer for loss/accuracy but not an example count.

    def _stage(batch: dict):
        # host-side weight sum rides along: the consumer must never
        # touch the (asynchronously staged) device array for it
        return float(batch["weight"].sum()), topo.device_put_batch(batch)

    raw = eval_batches(data, bs, pad_multiple=max(1, n // hosts),
                       host_id=jax.process_index(), num_hosts=hosts)
    use_prefetch = prefetch_depth > 0 and device_prefetch_pays()
    feed = (DevicePrefetcher(raw, put=_stage, depth=prefetch_depth)
            if use_prefetch else map(_stage, raw))
    try:
        for wsum, gbatch in feed:
            num_examples += wsum
            c, l, w = eval_fn(params, gbatch)
            correct += float(c)
            loss_sum += float(l)
            weight += float(w)
    finally:
        if use_prefetch:
            feed.close()
    if hosts > 1:
        # each host only iterated its stripe of the split
        from jax.experimental import multihost_utils
        num_examples = float(multihost_utils.process_allgather(
            np.asarray(num_examples)).sum())
    return {
        "accuracy": correct / max(weight, 1.0),
        "loss": loss_sum / max(weight, 1.0),
        "num_examples": int(num_examples),
        "seconds": time.time() - t0,
    }
