"""Injectable storage shim — every durable write goes through here.

Nineteen PRs faulted processes (kill/hang/stall), checkpoints-at-rest
(``corrupt_latest_checkpoint_at_step`` truncation), and the network
wire (the chaos proxy), but the storage substrate every recovery path
stands on was still assumed perfect: ``_write_atomic`` believed
renames are durable, writes never hit ENOSPC/EIO, and a crash can only
land between steps.  This module is the single seam that drops both
assumptions:

* **Durability policy** (``train.durability``): ``none`` keeps the
  historical behavior (buffered writes, rename-only atomicity),
  ``data`` fsyncs checkpoint/manifest payload bytes before the rename
  publishes them, ``full`` additionally fsyncs digest sidecars, the
  latest-pointer, JSONL journal appends (:class:`core.log.JsonlSink`
  calls :func:`fsync_journal` when this module says so), and the
  parent directory after every rename — the power-cut-proof upper
  bound the ``checkpoint_durability`` bench case prices.

* **Deterministic disk-fault injection** (``FaultPlan.disk_faults``):
  per-worker fault scripts — :data:`DISK_FAULT_KINDS` — armed in the
  worker process from the ``DMT_DISK_FAULTS`` env var (the cluster
  backend threads each worker's script list through its environment)
  or programmatically via :func:`arm_faults` (tests).  Every firing is
  journaled as a schema-declared ``fault`` record
  (``action: disk_*``) into the worker's ``storage_faults.jsonl`` so
  the replay invariants can LICENSE the degradation they caused: a
  ``save_failed`` or ``fallback_restore`` with no matching injected
  fault is a violation (obsv/invariants.py ``storage_faults``).

Fault kinds and their script fields (every script also takes
``at_step`` — armed once the trainer has reached that step, default 0
— ``times`` — firings before the fault disarms, default 1 — and
``match`` — substring filter on the target file name, default all):

* ``enospc_after_bytes`` (``bytes``): matching writes pass through
  until the cumulative byte budget is exceeded, then writes fail with
  ``ENOSPC`` writing nothing, ``times`` firings long (the disk fills,
  then space frees).
* ``eio`` (``op`` = ``read``/``write``, ``nth``): the ``nth``
  matching op (and the next ``times - 1``) fails with ``EIO``.
* ``slow_io_ms`` (``ms``): each matching op sleeps first — a
  degraded-disk stall, not an error.
* ``torn_write_at_byte`` (``at_byte``): the write lands only its
  first ``at_byte`` bytes, then fails with ``EIO`` — the mid-write
  crash model; the torn ``.tmp`` stays on disk exactly as a power cut
  would leave it.
* ``crash_rename`` (``keep_bytes``, default 0): the rename IS applied
  but the renamed file's data is lost down to ``keep_bytes`` — the
  power-cut-after-rename model (metadata journaled, data never hit
  the platter).  No error is raised: the writer believes the save
  succeeded, and only the digest sidecar can catch it later.

Faults apply ONLY to shim-routed durable artifacts (checkpoints,
manifests, digest sidecars, the pointer, quant sidecars) — never to
the journals that record them, which would be circular evidence.
"""

from __future__ import annotations

import dataclasses
import errno as _errno
import json
import os
import threading
import time
from pathlib import Path
from typing import Any

from ..core.log import JsonlSink, get_logger

logger = get_logger("storage")

DISK_FAULT_KINDS = ("enospc_after_bytes", "eio", "slow_io_ms",
                    "torn_write_at_byte", "crash_rename")

_VALID_DURABILITY = ("none", "data", "full")

# Roles fsynced per policy: "data" syncs payload bytes only; "full"
# syncs everything (payloads, sidecars, pointer, journals, dirs).
_DATA_ROLES = ("data",)

_DURABILITY = "none"


def set_durability(policy: str) -> None:
    """Install the process-wide fsync policy (``train.durability``)."""
    if policy not in _VALID_DURABILITY:
        from ..core.config import ConfigError
        raise ConfigError(
            f"train.durability={policy!r} is not a known durability "
            f"policy; valid policies: {', '.join(_VALID_DURABILITY)}")
    global _DURABILITY
    _DURABILITY = policy


def durability() -> str:
    return _DURABILITY


def _role_synced(role: str) -> bool:
    if _DURABILITY == "full":
        return True
    if _DURABILITY == "data":
        return role in _DATA_ROLES
    return False


def journal_sync_enabled() -> bool:
    """True when JSONL journal appends must fsync (policy ``full``) —
    :class:`core.log.JsonlSink` consults this per write (via a
    ``sys.modules`` lookup, so processes that never import the trainer
    pay nothing)."""
    return _DURABILITY == "full"


def fsync_journal(fh: Any) -> None:
    try:
        fh.flush()
        os.fsync(fh.fileno())
    except (OSError, ValueError):  # closed fh / exotic sink: best effort
        pass


def _fsync_fd(fd: int) -> None:
    os.fsync(fd)


def _fsync_dir(dirpath: Path) -> None:
    try:
        fd = os.open(dirpath, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# ---------------------------------------------------------------------------
# deterministic disk-fault injection
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Script:
    """One armed fault from a ``FaultPlan.disk_faults`` script dict."""

    kind: str
    at_step: int = 0
    times: int = 1
    match: str = ""
    op: str = "write"       # eio: which op class faults
    nth: int = 1            # eio: fire on the nth matching op
    bytes: int = 0          # enospc_after_bytes: byte budget
    ms: float = 0.0         # slow_io_ms: per-op stall
    at_byte: int = 0        # torn_write_at_byte: truncation point
    keep_bytes: int = 0     # crash_rename: surviving prefix
    # runtime counters
    fired: int = 0
    seen_ops: int = 0
    written: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "_Script":
        d = dict(d)
        kind = d.get("kind")
        if kind not in DISK_FAULT_KINDS:
            raise ValueError(
                f"unknown disk fault kind {kind!r}; valid kinds: "
                f"{', '.join(DISK_FAULT_KINDS)}")
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(
                f"disk fault {kind!r} has unknown field(s) "
                f"{sorted(unknown)}")
        return cls(**d)

    def spent(self) -> bool:
        return self.fired >= self.times

    def applies(self, step: int, name: str) -> bool:
        if self.spent() or step < self.at_step:
            return False
        return (not self.match) or (self.match in name)


class DiskFaultInjector:
    """Per-process fault engine consulted by every shim op.

    Scripts fire deterministically (list order, op counters, byte
    budgets — no randomness here; the chaos generator owns the seeded
    draw) and every firing lands in ``storage_faults.jsonl`` as a
    schema-declared ``fault`` record carrying the worker ordinal, so
    the trial-level invariant replay can collect licenses without the
    worker ever touching the supervisor's command journal."""

    def __init__(self, worker: int, scripts: list[dict],
                 journal_path: str | Path | None = None):
        self.worker = int(worker)
        self._scripts = [_Script.from_dict(s) for s in scripts]
        self._journal_path = Path(journal_path) if journal_path else None
        self._sink: JsonlSink | None = None
        self._lock = threading.Lock()
        self._step = 0

    def note_step(self, step: int) -> None:
        with self._lock:
            self._step = max(self._step, int(step))

    def _journal(self, action: str, path: Path, **fields: Any) -> None:
        rec = {"event": "fault", "action": action, "worker": self.worker,
               "path": path.name, "at_step": self._step, **fields}
        logger.warning("injected disk fault %s on %s", action, path.name)
        if self._journal_path is None:
            return
        try:
            if self._sink is None:
                self._sink = JsonlSink(self._journal_path)
            self._sink.write(rec)
        except OSError as e:
            logger.warning("storage fault journal write failed: %s", e)

    def on_write(self, path: Path, nbytes: int) -> int | None:
        """Consulted before a durable write of ``nbytes`` to ``path``.

        Raises ``OSError`` (ENOSPC/EIO), sleeps (slow_io), or returns
        a torn-write truncation point the shim must honor (write that
        prefix, then raise).  ``None`` → proceed normally."""
        name = path.name
        sleep_ms = 0.0
        torn_at: int | None = None
        with self._lock:
            for s in self._scripts:
                if not s.applies(self._step, name):
                    continue
                if s.kind == "slow_io_ms":
                    s.fired += 1
                    self._journal("disk_slow_io", path, op="write",
                                  ms=s.ms, planned_step=s.at_step)
                    sleep_ms += s.ms
                elif s.kind == "torn_write_at_byte":
                    s.fired += 1
                    self._journal("disk_torn_write", path, op="write",
                                  at_byte=s.at_byte,
                                  planned_step=s.at_step)
                    k = min(s.at_byte, nbytes)
                    torn_at = k if torn_at is None else min(torn_at, k)
                elif s.kind == "enospc_after_bytes":
                    if s.written + nbytes > s.bytes:
                        s.fired += 1
                        self._journal("disk_enospc", path, op="write",
                                      budget_bytes=s.bytes,
                                      planned_step=s.at_step)
                        raise OSError(
                            _errno.ENOSPC,
                            f"injected ENOSPC (budget {s.bytes}B)", name)
                    s.written += nbytes
                elif s.kind == "eio" and s.op == "write":
                    s.seen_ops += 1
                    if s.seen_ops >= s.nth:
                        s.fired += 1
                        self._journal("disk_eio", path, op="write",
                                      nth=s.seen_ops,
                                      planned_step=s.at_step)
                        raise OSError(_errno.EIO,
                                      "injected EIO on write", name)
        if sleep_ms:
            time.sleep(sleep_ms / 1000.0)
        return torn_at

    def on_read(self, path: Path) -> None:
        name = path.name
        sleep_ms = 0.0
        with self._lock:
            for s in self._scripts:
                if not s.applies(self._step, name):
                    continue
                if s.kind == "slow_io_ms":
                    s.fired += 1
                    self._journal("disk_slow_io", path, op="read",
                                  ms=s.ms, planned_step=s.at_step)
                    sleep_ms += s.ms
                elif s.kind == "eio" and s.op == "read":
                    s.seen_ops += 1
                    if s.seen_ops >= s.nth:
                        s.fired += 1
                        self._journal("disk_eio", path, op="read",
                                      nth=s.seen_ops,
                                      planned_step=s.at_step)
                        raise OSError(_errno.EIO,
                                      "injected EIO on read", name)
        if sleep_ms:
            time.sleep(sleep_ms / 1000.0)

    def on_replace(self, dst: Path) -> int | None:
        """Consulted before a publishing rename onto ``dst``.  Returns
        the surviving byte count when a ``crash_rename`` fires (the
        shim applies the rename, then loses the data) or ``None``."""
        with self._lock:
            for s in self._scripts:
                if s.kind != "crash_rename":
                    continue
                if not s.applies(self._step, dst.name):
                    continue
                s.fired += 1
                self._journal("disk_crash_rename", dst,
                              kept_bytes=s.keep_bytes,
                              planned_step=s.at_step)
                return s.keep_bytes
        return None

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()
            self._sink = None


_INJECTOR: DiskFaultInjector | None = None
_ENV_CHECKED = False

DISK_FAULTS_ENV = "DMT_DISK_FAULTS"


def arm_faults(worker: int, scripts: list[dict],
               journal_path: str | Path | None = None) -> DiskFaultInjector:
    """Programmatic arming (tests / in-process harnesses)."""
    global _INJECTOR, _ENV_CHECKED
    if _INJECTOR is not None:
        _INJECTOR.close()
    _INJECTOR = DiskFaultInjector(worker, scripts, journal_path)
    _ENV_CHECKED = True
    return _INJECTOR


def clear_faults() -> None:
    """Disarm (tests).  Also stops the env var from re-arming."""
    global _INJECTOR, _ENV_CHECKED
    if _INJECTOR is not None:
        _INJECTOR.close()
    _INJECTOR = None
    _ENV_CHECKED = True


def _injector() -> DiskFaultInjector | None:
    global _INJECTOR, _ENV_CHECKED
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        spec = os.environ.get(DISK_FAULTS_ENV, "")
        if spec:
            try:
                d = json.loads(spec)
                _INJECTOR = DiskFaultInjector(
                    int(d.get("worker", 0)), list(d.get("faults", [])),
                    d.get("journal"))
            except (ValueError, TypeError, KeyError) as e:
                logger.warning("ignoring malformed %s (%s)",
                               DISK_FAULTS_ENV, e)
    return _INJECTOR


def note_step(step: int) -> None:
    """Trainer progress hook — lets ``at_step``-gated scripts arm."""
    inj = _injector()
    if inj is not None:
        inj.note_step(step)


# ---------------------------------------------------------------------------
# the shim ops — what checkpoint.py / quant publish route through
# ---------------------------------------------------------------------------

def write_bytes(path: str | Path, data: bytes, role: str = "data") -> None:
    """Durable-write ``data`` to ``path`` (no rename — callers own the
    tmp+rename protocol), applying faults and the fsync policy."""
    path = Path(path)
    inj = _injector()
    torn_at = inj.on_write(path, len(data)) if inj is not None else None
    if torn_at is not None:
        with open(path, "wb") as fh:
            fh.write(data[:torn_at])
        raise OSError(_errno.EIO,
                      f"injected torn write at byte {torn_at}", path.name)
    with open(path, "wb") as fh:
        fh.write(data)
        if _role_synced(role):
            fh.flush()
            _fsync_fd(fh.fileno())


def write_text(path: str | Path, text: str, role: str = "sidecar") -> None:
    write_bytes(path, text.encode("utf-8"), role=role)


def read_bytes(path: str | Path) -> bytes:
    path = Path(path)
    inj = _injector()
    if inj is not None:
        inj.on_read(path)
    return path.read_bytes()


def read_text(path: str | Path) -> str:
    return read_bytes(path).decode("utf-8")


def replace(src: str | Path, dst: str | Path, role: str = "data") -> None:
    """The publishing rename (``os.replace``) — crash_rename faults
    land here, and policy ``full`` makes the rename itself durable by
    fsyncing the parent directory."""
    src, dst = Path(src), Path(dst)
    inj = _injector()
    keep = inj.on_replace(dst) if inj is not None else None
    os.replace(src, dst)
    if keep is not None:
        # power-cut model: the rename's metadata is journaled but the
        # file's data never hit the platter — only bytes the kernel
        # already flushed survive
        with open(dst, "r+b") as fh:
            fh.truncate(keep)
    if _DURABILITY == "full":
        _fsync_dir(dst.parent)
