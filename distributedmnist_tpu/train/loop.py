"""The training loop (≙ src/distributed_train.py:109-408, redesigned).

What the reference's 300-line ``train()`` does with a Supervisor,
queue-runner threads, a Twisted startup barrier, and per-step
``sess.run``s, this does with: build step → jit once → feed sharded
batches → log/checkpoint on cadence. There is no chief (every process
is identical; process 0 merely owns file writes), no second forward
pass per step (reference quirk at :332-335), and metric fetches are
batched at log points so the device pipeline stays async between them.
"""

from __future__ import annotations

import math
import signal
import threading
import time
from pathlib import Path
from typing import Any, Callable

import jax
import numpy as np

from ..core.config import ExperimentConfig
from ..core.log import JsonlSink, get_logger, step_line
from ..core.mesh import Topology, make_topology
from ..data.datasets import Datasets, load_datasets
from ..data.device_prefetch import DevicePrefetcher
from ..data.pipeline import device_prefetch_pays, make_train_iterator
from .evaluation import run_full_eval
from ..models.registry import Model, get_model
from ..obsv.timing import StepTimeCollector
from ..parallel.api import (TrainState, build_eval_step, build_train_step,
                            canonical_save_state, init_train_state,
                            logical_params, restore_for_topology,
                            state_partition_specs, world_signature,
                            zero1_plan_for)
from . import checkpoint as ckpt
from . import storage
from .lr_schedule import (constant, decay_steps_for, exponential_decay,
                          warmup_polynomial_decay)

logger = get_logger("train")


class _NonFiniteLoss(Exception):
    """Raised inside the flush path when the NaN/Inf guard trips;
    carries the step the poison was first observed at."""

    def __init__(self, step: int, loss: float):
        super().__init__(f"nonfinite loss {loss!r} at step {step}")
        self.step = step
        self.loss = loss


def _params_finite(state) -> bool:
    """True when every floating-point param leaf is finite — the
    is-this-checkpoint-poisoned test the NaN-guard rollback applies."""
    for leaf in jax.tree.leaves(state.params):
        a = np.asarray(jax.device_get(leaf))
        if np.issubdtype(a.dtype, np.floating) and not np.isfinite(a).all():
            return False
    return True


class Trainer:
    """Builds the whole training stack from one ExperimentConfig."""

    def __init__(self, cfg: ExperimentConfig, topo: Topology | None = None,
                 datasets: Datasets | None = None):
        self.cfg = cfg
        self.topo = topo or make_topology(cfg.mesh)
        # precision.compute_dtype overrides the model section's knob
        # when set — one shared resolution (core.config) so the
        # evaluator/serving tiers build the identical model
        from ..core.config import effective_model_config
        self.model: Model = get_model(effective_model_config(cfg))
        self.datasets = datasets if datasets is not None else load_datasets(
            cfg.data, cfg.model.image_size, cfg.model.num_channels,
            cfg.model.num_classes, cfg.model.seq_len, cfg.model.vocab_size)

        n = self.topo.num_replicas
        if cfg.data.batch_size % n != 0:
            raise ValueError(f"global batch {cfg.data.batch_size} not divisible "
                             f"by {n} replicas")
        if cfg.train.grad_accum_steps < 1:
            raise ValueError(f"train.grad_accum_steps must be >= 1, got "
                             f"{cfg.train.grad_accum_steps}")
        self.grad_accum = int(cfg.train.grad_accum_steps)
        # images/sec accounting and the epoch-based decay pacing both
        # key off the EFFECTIVE batch — one optimizer application
        # consumes batch_size × accum examples
        self.effective_batch = cfg.data.batch_size * self.grad_accum
        # DP×SP: tokens sharded over the seq axis too (transformer only)
        n_seq = self.topo.mesh.shape[self.topo.seq_axis]
        self.seq_sharded = n_seq > 1
        if self.seq_sharded and cfg.model.seq_len % n_seq != 0:
            raise ValueError(f"seq_len {cfg.model.seq_len} not divisible by "
                             f"seq_parallelism {n_seq}")
        n_stage = self.topo.mesh.shape[self.topo.stage_axis]
        if n_stage > 1:
            mb = cfg.mesh.pipeline_microbatches
            if (cfg.data.batch_size // n) % mb != 0:
                raise ValueError(
                    f"per-replica batch {cfg.data.batch_size // n} not "
                    f"divisible by pipeline_microbatches {mb}")
            if cfg.model.num_layers % n_stage != 0:
                raise ValueError(
                    f"num_layers {cfg.model.num_layers} not divisible by "
                    f"pipeline_parallelism {n_stage}")
        from ..parallel.policies import resolve_aggregate_k
        k = resolve_aggregate_k(cfg.sync, n)
        # LR schedule keyed to applied updates.
        if cfg.optim.schedule == "polynomial":
            # linear warmup + polynomial decay — the LARS/LAMB
            # large-batch pacing (train/lr_schedule.py);
            # decay_total_steps=0 resolves to the run's step budget
            total = cfg.optim.decay_total_steps or cfg.train.max_steps
            self.schedule = warmup_polynomial_decay(
                cfg.optim.initial_learning_rate, cfg.optim.warmup_steps,
                total, cfg.optim.end_learning_rate, cfg.optim.poly_power)
        elif cfg.optim.learning_rate_decay_factor == 1.0:
            self.schedule = constant(cfg.optim.initial_learning_rate)
        else:
            # exponential staircase; decay_steps ÷ k
            # (src/distributed_train.py:143-156)
            steps = decay_steps_for(self.datasets.train.num_examples,
                                    self.effective_batch,
                                    cfg.optim.num_epochs_per_decay, k)
            self.schedule = exponential_decay(
                cfg.optim.initial_learning_rate, steps,
                cfg.optim.learning_rate_decay_factor, cfg.optim.staircase)

        self.step_fn = build_train_step(self.model, cfg, self.topo, self.schedule)
        self.eval_fn = build_eval_step(self.model, cfg, self.topo)
        self.state_specs = state_partition_specs(self.model, cfg, self.topo)
        # ZeRO-1 shard plan (parallel.shard_weight_update): governs the
        # momentum layout in self.state AND the checkpoint conversion —
        # artifacts always carry the canonical logical layout
        # (parallel/api.py canonical_save_state), so a sharded run's
        # checkpoint restores onto any discipline and the path digests
        # stay stable across the knob.
        self._zero1_plan = zero1_plan_for(self.model, cfg, self.topo)
        self.state: TrainState = init_train_state(self.model, cfg, self.topo)
        self.state = self.topo.device_put_state(self.state, self.state_specs)

        self.train_iter = make_train_iterator(
            self.datasets.train, cfg.data, seed=cfg.train.seed,
            host_id=jax.process_index(), num_hosts=jax.process_count())
        if self.grad_accum > 1:
            # accum consecutive batches concatenated per step; the
            # inner cursor just advances accum batches per step
            # (data/pipeline.py GradAccumFeed)
            from ..data.pipeline import GradAccumFeed
            self.train_iter = GradAccumFeed(self.train_iter,
                                            self.grad_accum)

        # Dispatch-ahead feed: batches staged through device_put_batch
        # on a producer thread, device_prefetch_depth ahead, so host
        # assembly + H2D overlap device compute instead of sitting on
        # its critical path (data/device_prefetch.py). One shared
        # policy for when the producer thread pays: data.pipeline.
        # device_prefetch_pays (spare core, or an accelerator backend
        # whose drains park the host GIL-free).
        self._device_prefetch = (cfg.data.device_prefetch
                                 and cfg.data.device_prefetch_depth > 0
                                 and device_prefetch_pays())
        self._train_feed: DevicePrefetcher | None = None

        # Measured-timing vector staging: validate once, reuse the
        # sharding + host assembly buffer every step (core/mesh.py
        # MeasuredStage) instead of rebuilding both per step.
        self._measured_stage = (self.topo.measured_stage()
                                if self.topo.measured_timing_supported
                                else None)

        self.collector = StepTimeCollector(num_replicas=n)
        # Adaptive straggler discipline (sync.adaptive): the controller
        # watches the collector's rolling CDF and swaps the traced
        # [k, timeout_ms, interval_ms] step input at flush cadence
        # (train/discipline.py). Every process runs the SAME controller
        # on the SAME replicated [n] timing metrics, so all processes
        # swap identically; only the writer journals the begin/complete
        # pair (_sink_write gates).
        self._discipline = None
        if cfg.sync.adaptive:
            from ..parallel.api import make_discipline_vector
            from .discipline import DisciplineController
            self._discipline = DisciplineController(
                cfg.sync, n, self._sink_write, make_discipline_vector)
            self.collector.enable_rolling_cdf(cfg.sync.adaptive_window_steps)
        # comm-overlap gauges (parallel.comm_buckets > 1): the bucket
        # structure is known at build; the per-bucket comm calibration
        # joins in precompile() (obsv/timing.py set_overlap_info)
        self._comm_buckets = None
        self._bucket_pad_elems = None
        if (self._zero1_plan is not None
                and self._zero1_plan.comm_buckets > 1):
            from ..parallel.partition_rules import comm_bucket_assignment
            buckets = comm_bucket_assignment(self._zero1_plan)
            # empty when no leaf actually shards (e.g. a high
            # shard_min_leaf_size) — then bucketing is NOT active and
            # the overlap report key must not appear
            if buckets:
                lps = jax.tree.leaves(
                    self._zero1_plan.leaf_plans,
                    is_leaf=lambda x: hasattr(x, "sharded"))
                self._comm_buckets = buckets
                # derived once; precompile() re-reports with the
                # calibrated per-bucket comm ms added
                self._bucket_pad_elems = [sum(lps[i].pad for i in b)
                                          for b in buckets]
                self.collector.set_overlap_info(len(buckets),
                                                self._bucket_pad_elems)
        # Test/fault-injection seam: extra per-LOCAL-replica delay (ms)
        # added onto the measured vector — lets tests (and chaos runs)
        # make a specific replica the straggler deterministically.
        self.delay_injection_ms: np.ndarray | None = None
        # Per-replica DEVICE-side timing (sync.measure_device_skew):
        # the probe measures each local replica device's queue-drain
        # skew each step; it joins the measured [n] vector so the
        # policies rank on genuinely per-DEVICE time, not one host dt
        # per process (obsv/timing.py:ReplicaDeviceProbe).
        self._device_probe = None
        self._last_device_skew: np.ndarray | None = None
        if (cfg.sync.measure_device_skew
                and self.topo.measured_timing_supported):
            from ..obsv.timing import ReplicaDeviceProbe
            self._device_probe = ReplicaDeviceProbe(self.topo)
        # Chaos seam for REAL device-side delay (not a config constant):
        # {local_replica_index: (jitted_fn, device_resident_arg)} —
        # dispatched async right after each step so the named replica's
        # device genuinely drains later; the probe observes it.
        self.device_work_injection: dict[int, tuple] | None = None
        self.is_writer = jax.process_index() == 0
        self.train_dir = Path(cfg.train.train_dir)
        # Install the fsync policy process-wide BEFORE any durable
        # write (including the resume below) — an unknown value is a
        # typed ConfigError at trainer build, not a downstream surprise
        storage.set_durability(cfg.train.durability)
        self._sharded_ckpt = ckpt.state_needs_sharded_save(self.state)
        self._use_async_ckpt = cfg.train.async_checkpoint and (
            self.is_writer or self._sharded_ckpt)
        if (self._sharded_ckpt and cfg.train.save_interval_secs > 0
                and jax.process_count() > 1):
            # every process must write its shard for the SAME steps;
            # per-process wall clocks cannot agree on a seconds-based
            # trigger, so each periodic checkpoint would be torn
            # (shard files at different steps, no complete set)
            raise ValueError(
                "a cross-process sharded layout needs a deterministic "
                "checkpoint cadence every process agrees on: set "
                "train.save_interval_steps (and save_interval_secs=0)")
        self._checkpointer: ckpt.AsyncCheckpointer | None = None
        # Donation-safe async snapshot (train.async_snapshot): cadence
        # saves dispatch an async device copy into fresh un-donated
        # buffers — enqueued before the next step's program, so the
        # copy reads the state before donation reuses it — and the D2H
        # fetch + canonical conversion run on the checkpointer's worker
        # thread. Single-file layouts only: the per-host sharded format
        # needs every process's synchronized snapshot semantics as-is.
        self._async_snapshot = (self._use_async_ckpt
                                and cfg.train.async_snapshot
                                and not self._sharded_ckpt)
        self._snapshot_fn = None  # jitted un-donated copy, built lazily
        # Post-training quantization at publish time
        # (quant.publish_tiers): int8/bf16 serving tiers written as a
        # digest-verified sidecar next to every cadence save. Built
        # here so a bad tier name is a typed ConfigError at Trainer
        # build; the pass itself runs after each save — on the
        # AsyncCheckpointer worker for async saves, inline otherwise —
        # and never fails a checkpoint (sidecars are additive).
        self._quant_publisher = None
        if cfg.quant.resolved_publish_tiers():
            from ..parallel.api import abstract_train_params
            from ..quant.ptq import QuantPublisher
            self._quant_publisher = QuantPublisher(
                self.model, cfg,
                abstract_train_params(self.model, cfg, self.topo),
                calib_inputs=self.datasets.test.images,
                calib_labels=self.datasets.test.labels)
        self._sink: JsonlSink | None = None
        # Structured recovery events (NaN rollbacks, corrupt-checkpoint
        # fallbacks, preemption flushes) — the trainer-side half of the
        # journal obsv.journal.summarize_recovery aggregates.
        self._recovery_sink: JsonlSink | None = None
        self._preempt_requested: str | None = None
        # TB scalars on the summary cadence (≙ chief summary writes,
        # src/distributed_train.py:382-390)
        self._tb = None
        if self.is_writer and cfg.train.summary_every_steps > 0:
            from ..obsv.tb import SummaryWriter
            self._tb = SummaryWriter(self.train_dir / "tb")
        self._series: list[tuple[float, int, float, float]] = []  # (t, step, loss, acc)
        self._last_save_time = time.time()
        self._start_step = 0
        # AOT precompile bookkeeping (cfg.compile): the compile record
        # is journaled into train_log.jsonl separately from step time,
        # and re-journaled after a standby adoption re-roots the log.
        self._compile_info: dict[str, Any] | None = None
        self._compile_logged = False

        if cfg.train.resume:
            self._maybe_resume()

    # ------------------------------------------------------------------

    @property
    def train_feed(self):
        """The dispatch-ahead feed over the CURRENT ``train_iter`` —
        the DevicePrefetcher when enabled, the raw iterator otherwise.
        Resolved lazily so the established seam of swapping
        ``trainer.train_iter`` after construction (tests, chaos
        harnesses injecting a slow ingest) keeps working: a swap makes
        the previous wrapper stale and a fresh one is built around the
        new iterator.

        One documented limit: a swapped-in iterator with NO
        state()/restore() supports a single run() — the end-of-run
        stop() cannot push its read-ahead back into such an iterator,
        so the wrapper closes (loudly, at the next next()) rather than
        resume with a silent hole in the batch stream."""
        if not self._device_prefetch:
            return self.train_iter
        if (self._train_feed is None
                or self._train_feed.inner is not self.train_iter):
            if self._train_feed is not None:
                # join the stale wrapper's producer now — left to GC it
                # would keep consuming the old iterator (and hold its
                # cursor at the read-ahead position) indefinitely
                self._train_feed.stop()
            self._train_feed = DevicePrefetcher(
                self.train_iter,
                put=lambda b: self.topo.device_put_batch(
                    b, seq_sharded=self.seq_sharded),
                depth=self.cfg.data.device_prefetch_depth)
        return self._train_feed

    def _recovery_event(self, record: dict) -> None:
        """Append one structured recovery event to
        ``train_dir/recovery_journal.jsonl`` (writer process only)."""
        if not self.is_writer:
            return
        if self._recovery_sink is None:
            self._recovery_sink = JsonlSink(
                self.train_dir / "recovery_journal.jsonl")
        self._recovery_sink.write(
            {"event": "recovery", "time": time.time(), **record})

    def _maybe_resume(self) -> None:
        # mesh-portable restore: an artifact saved under ANY world size
        # reshards onto this run's mesh — the ZeRO-1 plan (padding,
        # chunk ownership) is re-derived from the CURRENT replica
        # count, and a world change is journaled as
        # action:"cross_world_restore" (parallel/api.py)
        restored = restore_for_topology(self.model, self.cfg, self.topo,
                                        self.train_dir, self.state,
                                        on_event=self._recovery_event)
        if restored is None:
            return
        state, extra, step = restored
        # The gpipe layer-stacked and 1f1b chunk-interleaved layouts
        # have identical tree structure and leaf shapes but DIFFERENT
        # layer order — a shape-matched restore across schedules would
        # silently permute the model. Refuse instead.
        saved_mesh = (extra.get("config") or {}).get("mesh", {})
        if self.topo.mesh.shape[self.topo.stage_axis] > 1:
            saved = (saved_mesh.get("pipeline_schedule", "gpipe"),
                     saved_mesh.get("pipeline_chunks", 1))
            want = (self.cfg.mesh.pipeline_schedule,
                    self.cfg.mesh.pipeline_chunks)
            if saved != want:
                raise ValueError(
                    f"checkpoint was written with pipeline layout "
                    f"(schedule, chunks)={saved} but this run uses "
                    f"{want}; the stacked layer orders differ — "
                    "restoring would silently permute the model")
        self.state = self.topo.device_put_state(state, self.state_specs)
        if "data_iter" in extra:
            try:
                # through the feed: a prefetching feed must also drop
                # anything it staged ahead of the restored cursor
                # (RuntimeError: DevicePrefetcher over a non-restorable
                # inner — same degrade-to-fresh-stream semantics)
                self.train_feed.restore(extra["data_iter"])
            except (AttributeError, KeyError, ValueError, RuntimeError):
                logger.warning("could not restore data-iterator state; "
                               "restarting stream")
        self._start_step = int(jax.device_get(self.state.step))
        logger.info("resumed from checkpoint step=%d (loop step %d)",
                    step, self._start_step)

    def _save(self, step: int) -> None:
        # Sharded layouts (a model/seq/stage/expert axis crossing
        # process boundaries): EVERY process writes its shard file;
        # process 0 additionally writes the manifest + pointer
        # (train/checkpoint.py per-host format). Otherwise process 0
        # writes the classic single file alone.
        if not self.is_writer and not ckpt.state_needs_sharded_save(self.state):
            return
        t0 = time.perf_counter()
        # the world the artifact is saved under: what lets a restore
        # tell "same world" from "resized world, reshard" and the
        # supervisor name both sides of an elastic reconfigure
        extra = {"config": self.cfg.to_dict(),
                 "world": world_signature(self.topo)}
        # through the feed: a prefetching feed reports the cursor of
        # the last CONSUMED batch, not the producer's read-ahead
        # position — a resume must replay batches the step never saw
        iter_state = getattr(self.train_feed, "state", None)
        if callable(iter_state) and getattr(self.train_feed, "has_state", True):
            extra["data_iter"] = self.train_feed.state()
        at_step = int(jax.device_get(self.state.step))
        # quant sidecar publish rides the save — BEFORE the
        # artifact/pointer write (on the worker thread for async
        # paths): a follower that sees the pointer name a new step
        # must find its sidecar already on disk, else a fast poll
        # falls back to fp32 and never revisits that step's tier
        publish = None
        if self._quant_publisher is not None and self.is_writer:
            pub, tdir = self._quant_publisher, self.train_dir
            publish = lambda st, s: pub.publish(tdir, st, s)  # noqa: E731
        # arm any at_step-gated disk fault scripts for this save
        storage.note_step(at_step)
        try:
            self._save_inner(at_step, extra, publish)
        except OSError as e:
            # Graceful ENOSPC/EIO degradation: a cadence save that
            # still fails after the bounded I/O retries is journaled
            # and SKIPPED — the run keeps training and the next
            # cadence tries again (async writes report here through
            # the checkpointer's on_error hook instead; a persistently
            # dead disk still stops the run via its consecutive-
            # failure bound).
            logger.error("checkpoint save for step=%d failed (%s) — "
                         "skipping this cadence", at_step, e)
            self._recovery_event({"layer": "train",
                                  "action": "save_failed",
                                  "step": at_step,
                                  "error": f"{type(e).__name__}: {e}",
                                  "errno": getattr(e, "errno", None),
                                  "where": "sync"})
            self._last_save_time = time.time()
            return
        # what the step loop actually paid for this save — the quantity
        # the save_stall bench gates (async-snapshot dispatch vs the
        # sync host fetch + canonical conversion)
        stall_ms = (time.perf_counter() - t0) * 1e3
        self.collector.add_snapshot_stall_ms(stall_ms)
        # "at_step", deliberately NOT "step": the log-tail parsers
        # (launch/cluster.py parse_poll_output and the resume watch)
        # treat any intact record carrying "step" as training progress
        self._sink_write({"event": "save", "time": time.time(),
                          "at_step": at_step,
                          "save_stall_ms": round(stall_ms, 3),
                          "async_snapshot": self._async_snapshot,
                          **({"quant_tiers":
                              list(self._quant_publisher.tiers)}
                             if publish is not None else {})})
        self._last_save_time = time.time()

    def _ckpt_save_failed(self, step: int, e: Exception) -> None:
        """AsyncCheckpointer on_error hook (worker thread): journal the
        failed background write as a ``save_failed`` recovery event."""
        self._recovery_event({"layer": "train", "action": "save_failed",
                              "step": step,
                              "error": f"{type(e).__name__}: {e}",
                              "errno": getattr(e, "errno", None),
                              "where": "async"})

    def _save_inner(self, at_step: int, extra: dict, publish) -> None:
        if self._async_snapshot:
            # donation-safe snapshot, backend-matched (both variants
            # leave the canonical-layout conversion + the state-dict
            # walk + serialization to the worker thread):
            #   * CPU client — host VIEWS via device_get: PJRT
            #     copy-on-donate protects buffers with live external
            #     references, so the views keep their pre-donation
            #     values (verified on jaxlib 0.4.37), and the grab is
            #     ~free where a device-side copy would execute a
            #     SYNCHRONOUS memcpy at dispatch (measured ~10 ms for
            #     the flagship CNN state).
            #   * accelerators — an async on-device copy into fresh
            #     un-donated buffers, enqueued ahead of the next
            #     step's donating program (so the copy reads the
            #     buffers first); device_get here would be the
            #     blocking D2H stall this knob exists to remove.
            if self._checkpointer is None or self._checkpointer.closed:
                self._checkpointer = ckpt.AsyncCheckpointer(
                    on_error=self._ckpt_save_failed)
            plan = self._zero1_plan
            if jax.default_backend() == "cpu":
                snap = ckpt.host_view_snapshot(self.state)
                prepare = (lambda s: ckpt.snapshot_for_save(
                    canonical_save_state(ckpt.materialize_snapshot(s),
                                         plan)))
            else:
                if self._snapshot_fn is None:
                    import jax.numpy as jnp
                    self._snapshot_fn = jax.jit(
                        lambda s: jax.tree.map(jnp.copy, s))
                snap = self._snapshot_fn(self.state)
                prepare = (lambda s: ckpt.snapshot_for_save(
                    canonical_save_state(s, plan)))
            self._checkpointer.save(
                self.train_dir, snap, at_step, extra=extra,
                keep=self.cfg.train.keep_checkpoints, prepare=prepare,
                publish=publish)
        else:
            # canonical layout on disk: replica-sharded (ZeRO-1)
            # momentum — and resident-sharded params — unpack to their
            # logical shapes so the artifact (and its canonical path
            # digest) is identical to a replicated run's. Only when
            # this process can materialize the buffers (always true
            # single-process); a cross-process sharded layout saves
            # its live layout via the per-host shard format instead.
            state_to_save = self.state
            if (self._zero1_plan is not None
                    and not ckpt.state_needs_sharded_save(self.state)):
                state_to_save = canonical_save_state(self.state,
                                                     self._zero1_plan)
            if self._use_async_ckpt:
                if self._checkpointer is None or self._checkpointer.closed:
                    self._checkpointer = ckpt.AsyncCheckpointer(
                        on_error=self._ckpt_save_failed)
                self._checkpointer.save(self.train_dir, state_to_save,
                                        at_step, extra=extra,
                                        keep=self.cfg.train.keep_checkpoints,
                                        no_skip=self._sharded_ckpt,
                                        publish=publish)
            else:
                if publish is not None:
                    publish(state_to_save, at_step)
                ckpt.save_checkpoint(self.train_dir, state_to_save, at_step,
                                     extra=extra,
                                     keep=self.cfg.train.keep_checkpoints)

    def _rollback_to_last_good(self, err: _NonFiniteLoss) -> int:
        """NaN-guard rollback: restore the newest checkpoint whose
        params are finite (a cadence save may already have captured the
        poison) and return the loop step to continue from. The guard
        exists for transient corruption — a flipped bit, a bad host —
        not for genuinely divergent optimization, which will reproduce
        the NaN and exhaust ``nan_guard_max_rollbacks``."""
        for s in sorted(ckpt.loadable_steps(self.train_dir), reverse=True):
            try:
                # the mesh-portable path (rollback candidates may
                # predate an elastic resize of this very run)
                state, extra, got = restore_for_topology(
                    self.model, self.cfg, self.topo, self.train_dir,
                    self.state, step=s)
            except Exception as e:
                self._recovery_event({"layer": "train",
                                      "action": "rollback_candidate_unusable",
                                      "step": s, "error": str(e)})
                continue
            if not _params_finite(state):
                self._recovery_event({"layer": "train",
                                      "action": "rollback_candidate_poisoned",
                                      "step": s})
                continue
            self.state = self.topo.device_put_state(state, self.state_specs)
            if "data_iter" in extra:
                try:
                    self.train_feed.restore(extra["data_iter"])
                except (AttributeError, KeyError, ValueError, RuntimeError):
                    logger.warning("could not restore data-iterator state "
                                   "on rollback; restarting stream")
            loop_step = int(jax.device_get(self.state.step))
            logger.warning("nonfinite loss at step %d — rolled back to "
                           "checkpoint step=%d", err.step, loop_step)
            self._recovery_event({"layer": "train", "action": "nan_rollback",
                                  "from_step": err.step,
                                  "to_step": loop_step,
                                  "loss": repr(err.loss)})
            return loop_step
        raise RuntimeError(
            f"nonfinite loss at step {err.step} and no finite checkpoint "
            "to roll back to") from err

    def _install_preempt_handlers(self) -> dict | None:
        """SIGTERM/SIGINT → finish the current step, flush a
        checkpoint, stop cleanly (the CLI exits with
        train.resumable_exit_code). Main thread only — elsewhere the
        signal API refuses, and the process owner is handling signals
        itself."""
        if not self.cfg.train.handle_preemption:
            return None
        if threading.current_thread() is not threading.main_thread():
            return None

        def handler(signum, frame):
            self._preempt_requested = signal.Signals(signum).name
            logger.warning("received %s — will flush a checkpoint and "
                           "stop (resumable)", self._preempt_requested)

        saved: dict = {}
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                saved[sig] = signal.signal(sig, handler)
        except (ValueError, OSError):
            for sig, old in saved.items():
                signal.signal(sig, old)
            return None
        return saved

    def _sink_write(self, record: dict) -> None:
        if self.is_writer:
            if self._sink is None:
                log_path = self.train_dir / "train_log.jsonl"
                if self._start_step == 0 and log_path.exists():
                    # fresh run (not a resume) into a reused train_dir:
                    # starting over must not concatenate onto an older
                    # run's step series — every report/figure consumer
                    # reads this file as ONE monotone series
                    log_path.unlink()
                self._sink = JsonlSink(log_path)
            self._sink.write(record)

    def _dump_series(self) -> None:
        """≙ worker%d_time_acc.npy dumps (src/distributed_train.py:373-379),
        plus the [steps, n_replicas] compute-time matrix the CDF report
        plots (≙ the RPC-gossiped ELAPSED TIMES tables,
        src/timeout_manager.py:31-70)."""
        if self.is_writer and self._series:
            np.save(self.train_dir / "time_acc.npy", np.asarray(self._series))
            m = self.collector.matrix()
            if m.size:
                np.save(self.train_dir / "step_times.npy", m)

    def precompile(self) -> dict[str, Any]:
        """AOT-compile the train step BEFORE the first batch (ROADMAP
        item 5): compile time is measured here — and journaled as its
        own ``event: "compile"`` record — instead of hiding inside the
        first step's wall time, and a warm standby can park fully
        compiled. Routed through the executable disk cache when a
        persistent cache dir is configured (parallel/aot.py); idempotent
        per Trainer."""
        if self._compile_info is not None:
            return self._compile_info
        img = self.datasets.train.images
        lbl = self.datasets.train.labels
        B = self.effective_batch  # accum batches arrive concatenated
        batch = {"image": np.zeros((B, *img.shape[1:]), img.dtype),
                 "label": np.zeros((B, *lbl.shape[1:]), lbl.dtype)}
        gbatch = self.topo.device_put_batch(batch,
                                            seq_sharded=self.seq_sharded)
        from ..core.compile_cache import cache_stats, resolve_cache_dir
        # Deliberately NOT enable_persistent_cache here: flipping jax's
        # global cache is an entry-point action (launch CLI,
        # __graft_entry__ — one Trainer per process). Enabling it from
        # inside the Trainer corrupts jaxlib 0.4.37 when a process
        # builds several Trainers (measured: ~2/3 of two-Trainer runs
        # segfault); library callers who want it call
        # core.compile_cache.enable_persistent_cache once at startup.
        cache_dir = (resolve_cache_dir(self.cfg.compile)
                     if self.cfg.compile.aot_executable_cache else None)
        cache_key = None
        if cache_dir is not None:
            from ..parallel.aot import aot_cache_key
            cache_key = aot_cache_key(self.model, self.cfg, self.topo)
        before = cache_stats(cache_dir) if cache_dir is not None else None
        info = self.step_fn.precompile(
            self.state, gbatch, cache_dir=cache_dir, cache_key=cache_key,
            trust_cross_process=self.cfg.compile.trust_cache_cross_process)
        if before is not None:
            after = cache_stats(cache_dir)
            # zero new entries across a compile = every program came
            # out of the persistent cache — the warm-restart evidence
            # the bench/CI artifacts surface
            info["persistent_cache"] = {
                "dir": str(cache_dir),
                "entries": after["entries"],
                "new_entries": after["entries"] - before["entries"],
                "hits": after["hits"] - before["hits"],
                "misses": after["misses"] - before["misses"]}
        logger.info("precompiled train step in %.2fs (source=%s)",
                    info["compile_s"], info["source"])
        if self._comm_buckets:
            # per-bucket comm calibration (small probe compiles — only
            # when overlap is on, and never fatal to the fast path)
            try:
                from ..parallel.api import measure_bucket_comm_ms
                self.collector.set_overlap_info(
                    len(self._comm_buckets), self._bucket_pad_elems,
                    measure_bucket_comm_ms(self.topo, self._zero1_plan))
            except Exception as e:
                logger.warning("bucket comm calibration failed (%s: %s)",
                               type(e).__name__, e)
        self._compile_info = info
        return info

    def adopt_train_dir(self, train_dir: str | Path) -> None:
        """Re-root this trainer onto a different ``train_dir`` and
        resume from whatever checkpoints live there — the warm-standby
        promotion hook: a parked, precompiled process adopts a dead
        worker's logdir and continues its run without paying boot or
        compile again. Sinks and the TB writer are rebuilt against the
        new dir; the compile record is re-journaled there so the
        adopted log still carries the episode's compile evidence."""
        for attr in ("_sink", "_recovery_sink"):
            sink = getattr(self, attr)
            if sink is not None:
                sink.close()
                setattr(self, attr, None)
        self.train_dir = Path(train_dir)
        self.train_dir.mkdir(parents=True, exist_ok=True)
        if self._tb is not None:
            from ..obsv.tb import SummaryWriter
            self._tb.flush()
            self._tb = SummaryWriter(self.train_dir / "tb")
        self._compile_logged = False
        self._series.clear()
        self._start_step = 0
        if self.cfg.train.resume:
            self._maybe_resume()

    # ------------------------------------------------------------------

    def evaluate(self, split: str = "test") -> dict[str, float]:
        """One full-split eval pass (in-loop convenience; the
        continuous evaluator lives in ``evalsvc``). Resident-sharded
        params are gathered to the logical replicated layout the eval
        step places (parallel.api.logical_params — a passthrough
        otherwise)."""
        return run_full_eval(
            self.eval_fn,
            logical_params(self.state.params, self._zero1_plan, self.topo),
            self.topo,
            getattr(self.datasets, split), self.cfg.eval.eval_batch_size,
            prefetch_depth=self.cfg.data.effective_device_prefetch_depth())

    def run(self, max_steps: int | None = None,
            step_callback: Callable[[int, dict], None] | None = None) -> dict[str, Any]:
        """Run the loop; returns a summary dict."""
        cfg = self.cfg.train
        total = max_steps if max_steps is not None else cfg.max_steps
        profile_start, profile_stop = cfg.profile_steps
        profiling = False
        log_every = max(1, cfg.log_every_steps)
        last_log_t = time.time()
        last_log_step = self._start_step
        pending: list[tuple[int, dict, float]] = []
        final_metrics: dict[str, float] = {}
        # With no synthetic straggler model, per-replica step times are
        # driven by the real measured host step time: each process feeds
        # its own measurement into its replicas' rows of the [n] vector
        # (this is what paces interval windows / timeout deadlines and
        # ranks quorum contributors on real hardware). Granularity is
        # per PROCESS by construction: replicas of one process execute
        # inside a single lockstep SPMD program, so a within-process
        # per-replica clock cannot differ — real divergence enters at
        # process boundaries (slow host, ingest, contention), which is
        # exactly what this measures (proven live by
        # tests/test_multihost.py::test_slow_process_loses_quorum_by_
        # measured_time; ≙ per-worker times, src/timeout_manager.py:48-61).
        inject_measured = (self.cfg.sync.straggler_profile == "none"
                           and self.cfg.sync.mode in ("interval", "timeout",
                                                      "quorum", "cdf"))
        host_dt = 0.0

        can_measure = self.topo.measured_timing_supported
        if (inject_measured or self.delay_injection_ms is not None) and not can_measure:
            logger.warning(
                "replicas don't split evenly over processes — per-host "
                "measured timing disabled, policies run on the synthetic "
                "model only")

        def measured_vector() -> jax.Array | None:
            stage = self._measured_stage
            if stage is None or not (inject_measured
                                     or self.delay_injection_ms is not None):
                return None
            # assemble into the stage's reusable buffer; put() reuses
            # the cached sharding (and the staged all-zeros device
            # buffer outright when nothing was injected or measured)
            buf = stage.buffer
            buf[:] = host_dt * 1000.0 if inject_measured else 0.0
            if self.delay_injection_ms is not None:
                buf += np.asarray(self.delay_injection_ms, np.float32)
            if self._last_device_skew is not None:
                # per-device drain skew measured LAST step — the
                # within-host divergence the uniform host dt misses
                buf += self._last_device_skew
            return stage.put()

        def flush(now: float) -> None:
            nonlocal final_metrics, last_log_t, last_log_step
            if not pending:
                return
            upto = pending[-1][0]
            rate = ((upto - last_log_step) * self.effective_batch
                    / max(now - last_log_t, 1e-9))
            # NaN/Inf guard scans the WHOLE window before anything is
            # written: a mid-window raise would have already emitted the
            # earlier records (log lines, TB scalars, step_callbacks)
            # that the post-rollback re-run then emits again
            if self.cfg.train.nan_guard:
                for s, m, t in pending:
                    loss = float(m["loss"])
                    if not (math.isfinite(loss)
                            and math.isfinite(float(m["train_acc"]))):
                        self._recovery_event(
                            {"layer": "train",
                             "action": "nonfinite_loss_detected",
                             "step": s, "loss": repr(loss)})
                        raise _NonFiniteLoss(s, loss)
            for s, m, t in pending:
                loss = float(m["loss"])
                acc = float(m["train_acc"])
                self._series.append((t, s, loss, acc))
                record = {
                    "event": "step", "step": s, "time": t, "loss": loss,
                    "train_acc": acc, "lr": float(m["lr"]),
                    "updates_applied": int(m["updates_applied"]),
                    "num_contributors": float(m["num_contributors"]),
                    "examples_per_sec": rate,
                    # per-replica contribution mask — which replicas'
                    # gradients entered this step's masked mean
                    "flags": np.asarray(m["flags"]).astype(int).tolist(),
                    # adaptive mode: the [k, timeout_ms] in force for
                    # this step — params only change at flush end, so
                    # every pending step ran under the current pair
                    **({"discipline": self._discipline.params_list()}
                       if self._discipline is not None else {}),
                }
                self._sink_write(record)
                final_metrics = record
                if (self._tb is not None
                        and s % self.cfg.train.summary_every_steps == 0):
                    self._tb.add_scalars(
                        {"train/loss": loss, "train/accuracy": acc,
                         "train/learning_rate": record["lr"],
                         "train/examples_per_sec": rate,
                         "train/num_contributors":
                             record["num_contributors"]},
                        step=s, wall_time=t)
                    # on-cadence flush: live `tensorboard --logdir`
                    # sees the run, and a crash loses at most one window
                    self._tb.flush()
                if step_callback:
                    step_callback(s, record)
            # canonical line for the last flushed step
            logger.info(step_line(jax.process_index(), upto,
                                  final_metrics["loss"],
                                  final_metrics["train_acc"], rate,
                                  (now - last_log_t) / max(upto - last_log_step, 1)))
            pending.clear()
            last_log_t, last_log_step = now, upto
            # adaptive discipline: evaluate AFTER the window's records
            # are written — a change licensed here governs from the
            # NEXT step (effective_step = upto + 1), so the records
            # above correctly carry the pre-change pair
            if self._discipline is not None:
                rolling = self.collector.rolling_cdf()
                if rolling is not None:
                    from .discipline import WindowStats
                    self._discipline.maybe_adapt(upto, WindowStats(
                        p50_ms=rolling["p50_ms"],
                        p90_ms=rolling["p90_ms"],
                        p99_ms=rolling["p99_ms"],
                        n_samples=rolling["window_steps"],
                        fast_p50_ms=rolling["fast_p50_ms"]))

        # Recurring per-window trace dumps (cfg.trace_every_steps): a
        # one-step trace each cadence window, each under its own
        # step_<k> directory — ≙ the reference's --timeline_logging
        # per-iteration Chrome traces (src/distributed_train.py:354-358)
        # at a bounded cadence instead of every step. Mutually
        # exclusive with the one-shot profile_steps window (two
        # concurrent jax.profiler traces cannot nest).
        trace_every = max(0, cfg.trace_every_steps)
        if trace_every and profile_stop > profile_start:
            raise ValueError("set either train.profile_steps or "
                             "train.trace_every_steps, not both "
                             "(profiler traces cannot nest)")
        tracing_step = None

        # Dispatch-ahead: the feed (train_feed property) either hands
        # back pre-staged sharded global arrays (DevicePrefetcher —
        # host assembly and H2D ran on the producer thread while the
        # previous step executed) or the raw host batch to stage
        # inline. Re-resolved each iteration (two attribute compares)
        # so the train_iter swap seam works mid-run too — the property
        # joins a stale wrapper before handing back the fresh one.
        prefetching = self._device_prefetch

        self.train_dir.mkdir(parents=True, exist_ok=True)
        if self.cfg.compile.precompile and self._compile_info is None:
            try:
                self.precompile()
            except Exception as e:
                # the fast path must never cost a run: fall back to the
                # classic first-step inline compile
                logger.warning("precompile failed (%s: %s) — first step "
                               "will compile inline", type(e).__name__, e)
                self._compile_info = {"compile_s": None, "source": "inline",
                                      "error": f"{type(e).__name__}: {e}"}
        if self._compile_info is not None and not self._compile_logged:
            self._sink_write({"event": "compile", "time": time.time(),
                              **self._compile_info})
            self._compile_logged = True
        step = self._start_step
        rollbacks = 0
        self._preempt_requested = None
        saved_handlers = self._install_preempt_handlers()
        try:
          # outer loop: one iteration per NaN-guard rollback episode —
          # the inner loop re-enters from the restored step
          while True:
            try:
              while step < total and self._preempt_requested is None:
                feed = self.train_feed
                in_window = profile_stop > profile_start and profile_start <= step < profile_stop
                if in_window and not profiling and self.is_writer:
                    jax.profiler.start_trace(str(self.train_dir / "profile"))
                    profiling = True
                if (trace_every and self.is_writer and tracing_step is None
                        and step % trace_every == 0):
                    jax.profiler.start_trace(
                        str(self.train_dir / "profile" / f"step_{step}"))
                    tracing_step = step
                t0 = time.time()
                if prefetching:
                    gbatch = next(feed)
                    # gauge AT dequeue: sampled any later, the producer
                    # has refilled and a producer-bound pipeline (the
                    # "pinned at 0" reading) would look healthy
                    queue_depth = feed.qsize
                else:
                    gbatch = self.topo.device_put_batch(
                        next(feed), seq_sharded=self.seq_sharded)
                self.state, metrics = self.step_fn(
                    self.state, gbatch, measured_vector(),
                    None if self._discipline is None
                    else self._discipline.vector)
                # host_dt is the per-HOST base time and must be captured
                # BEFORE the probe's drain poll — otherwise one slow device
                # would inflate every local replica's base (and the slow
                # one's skew would double-count)
                host_dt = time.time() - t0
                if self._device_probe is not None:
                    if self.device_work_injection:
                        for _r, (fn, arg) in self.device_work_injection.items():
                            # async: queues real work on that device; the
                            # probe polls the output's readiness so the
                            # delay is attributed to the right replica
                            # even on backends without per-device FIFO
                            self._device_probe.note(_r, fn(arg))
                    self._last_device_skew = self._device_probe.measure_skew_ms()
                step += 1
                self.collector.add(
                    metrics["step_times_ms"], host_dt,
                    prefetch_depth=queue_depth if prefetching else None)
                pending.append((step, metrics, time.time()))

                if tracing_step is not None:
                    # one full step per window; fetch a scalar first so the
                    # trace covers the device work, not just the dispatch
                    float(metrics["loss"])
                    jax.profiler.stop_trace()
                    tracing_step = None

                if cfg.step_pace_ms > 0:
                    # deliberate wall throttle (serving-chaos publisher
                    # pacing) — after the step, before any cadence work
                    time.sleep(cfg.step_pace_ms / 1e3)

                if step % log_every == 0:
                    flush(time.time())

                if profiling and step >= profile_stop:
                    jax.profiler.stop_trace()
                    profiling = False

                if cfg.save_interval_secs > 0:
                    if time.time() - self._last_save_time >= cfg.save_interval_secs:
                        self._save(step)
                elif cfg.save_interval_steps > 0 and step % cfg.save_interval_steps == 0:
                    self._save(step)
                if cfg.save_results_period > 0 and step % cfg.save_results_period == 0:
                    self._dump_series()
              flush(time.time())  # records past the last log boundary
              break
            except _NonFiniteLoss as e:
                # NaN/Inf guard: discard the poisoned window, stop any
                # open trace, roll back to the newest finite
                # checkpoint and re-enter the loop from there. (If that
                # checkpoint predates the last flushed window, the
                # re-run appends the overlapping steps again — the same
                # overlap a kill + resume produces; no poisoned window
                # is ever written, per the flush pre-scan.)
                pending.clear()
                if tracing_step is not None:
                    jax.profiler.stop_trace()
                    tracing_step = None
                if profiling:
                    jax.profiler.stop_trace()
                    profiling = False
                rollbacks += 1
                if rollbacks > self.cfg.train.nan_guard_max_rollbacks:
                    raise RuntimeError(
                        f"nonfinite loss recurred after "
                        f"{rollbacks - 1} rollback(s) — deterministic "
                        "divergence, giving up") from e
                step = self._rollback_to_last_good(e)
                last_log_step = step
                last_log_t = time.time()
        finally:
            if saved_handlers is not None:
                for sig, old in saved_handlers.items():
                    signal.signal(sig, old)
            if self._train_feed is not None:
                # normal exit OR an exception escaping the loop: join
                # the producer and re-sync the inner cursor to the
                # consumed position, so nothing holds the process open
                # and a later run()/checkpoint observes no phantom
                # read-ahead progress (the live wrapper directly — the
                # property would construct a fresh one after a swap)
                self._train_feed.stop()

        if profiling:
            jax.profiler.stop_trace()
        if self._preempt_requested:
            self._recovery_event({"layer": "train", "action": "preempt_flush",
                                  "signal": self._preempt_requested,
                                  "step": step})
        # final save (≙ chief final saver.save, src/distributed_train.py:405-408)
        self._save(step)
        if self._checkpointer is not None:
            # drain + join the writer thread (a sweep builds many
            # Trainers in one process); raises if the final write failed
            self._checkpointer.close()
            self._checkpointer = None
        self._dump_series()
        if self._tb is not None:
            self._tb.flush()  # not closed: run() may be called again
        if self._sink:
            self._sink.close()
            self._sink = None
        if self._recovery_sink is not None:
            self._recovery_sink.close()
            self._recovery_sink = None
        summary = {
            "final_step": step,
            "updates_applied": int(jax.device_get(self.state.updates_applied)),
            "last_metrics": final_metrics,
            # bitwise identity of the final params (train/checkpoint.py
            # state_params_digest): the chaos invariant checker compares
            # a faulted-but-recovered run against its fault-free
            # same-seed reference by this — and against the final
            # checkpoint's own digest (the two must agree). None when
            # shards live on other processes (this process cannot
            # materialize the full params to hash them). Canonicalized
            # first: a resident-sharded run must hash the same LOGICAL
            # params a replicated same-seed run does — with momentum
            # dropped before the conversion, since the digest reads
            # params only and unpacking whole moment trees for it
            # would be a wasted D2H fetch.
            "params_digest": (ckpt.state_params_digest(
                                  canonical_save_state(
                                      self.state.replace(momentum=None),
                                      self._zero1_plan))
                              if not self._sharded_ckpt else None),
            "timing": self.collector.report(),
            # self-healing outcome: None/0 on a clean run; the CLI maps
            # "preempted" to train.resumable_exit_code
            "preempted": self._preempt_requested,
            "nan_rollbacks": rollbacks,
            # AOT/compile-cache evidence (None when precompile is off):
            # where the executable came from and what the persistent
            # cache did — journaled in train_log.jsonl too
            "compile": self._compile_info,
        }
        if self._discipline is not None:
            # adaptive-controller roll-up: change count + epoch trace
            summary["discipline"] = self._discipline.summary()
        return summary
