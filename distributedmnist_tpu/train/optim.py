"""Optimizer registry: the large-batch update rules as pure per-leaf
functions.

The reference hardwires ``tf.train.GradientDescentOptimizer``
(src/distributed_train.py:176); this registry opens that into the
MLPerf-on-TPU-pods large-batch recipe (arXiv:1909.09756): plain /
momentum SGD plus the layer-wise adaptive trust-ratio optimizers —
LARS (arXiv:1708.03888) and LAMB (arXiv:1904.00962).

Design constraints, in order:

1. **Per-leaf purity.** Every optimizer is one
   ``update_leaf(p, g, slots, lr, t, norm_reduce, adapt)`` function
   over same-shaped arrays — a FULL logical leaf on the replicated
   update path, or this replica's 1/n ZeRO-1 *chunk* on the sharded
   path (parallel/api.py ``_zero1_update``). The only cross-element
   quantity the trust-ratio math needs is a sum of squares, so the
   caller supplies ``norm_reduce`` — identity for full leaves, a
   ``lax.psum`` over the replica axis for chunks (zero padding
   contributes 0 to a sum of squares, so chunked norms are exact).
   One update rule, both weight-update disciplines.
2. **Float32 math.** Inputs are cast to float32 on entry and the new
   param value is cast back to the leaf's storage dtype on exit, so a
   bf16 param leaf (precision.param_dtype without master weights)
   still takes its update in full precision. Moment slots are always
   float32 (``slot_dtype``).
3. **Layer-wise semantics per the papers.** The trust ratio and weight
   decay apply only to leaves with ``adapt=True`` — the caller passes
   the leaf's logical rank, and 1-D leaves (biases, norm scales) skip
   adaptation, the standard LARS/LAMB exclusion list.

Slot layout: ``None`` (stateless sgd), a params-shaped tree (one-slot
optimizers — byte-identical to the historical momentum layout, so
existing momentum checkpoints and their canonical digests are
untouched), or ``{"m": tree, "v": tree}`` (LAMB). The ``{"m", "v"}``
top-level key set is reserved for the two-slot layout; no registered
model's param tree uses it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from ..core.config import ConfigError, OptimConfig

OPTIMIZER_NAMES = ("sgd", "momentum", "lars", "lamb")

# update_leaf(p, g, slots, lr, t, norm_reduce, adapt) -> (new_p, new_slots)
#   p, g        same-shaped arrays (full leaf or ZeRO-1 chunk)
#   slots       tuple of moment arrays, same shape as p (len == num_slots)
#   lr          scalar learning rate
#   t           float32 applied-update count AFTER this apply (>= 1) —
#               LAMB bias correction; ignored by the others
#   norm_reduce scalar -> scalar: completes a partial sum-of-squares to
#               the full-leaf value (identity, or psum over axes)
#   adapt       static bool: apply weight decay + trust ratio (ndim > 1)
UpdateLeaf = Callable[..., tuple[jax.Array, tuple]]


@dataclasses.dataclass(frozen=True)
class Optimizer:
    """One registry entry: the canonical state kind, how many moment
    slots a leaf carries, and the pure per-leaf update rule."""

    kind: str
    num_slots: int
    update_leaf: UpdateLeaf


def validate(ocfg: OptimConfig) -> None:
    """Typed validation for the optimizer section — raised at build
    time (make_optimizer) so every consumer (Trainer, bench, tests)
    fails loudly before tracing anything."""
    if ocfg.name not in OPTIMIZER_NAMES:
        raise ConfigError(
            f"unknown optimizer {ocfg.name!r}; valid: {list(OPTIMIZER_NAMES)}")
    if ocfg.name in ("lars", "lamb") and ocfg.momentum != 0.0:
        raise ConfigError(
            f"optim.momentum={ocfg.momentum} combined with "
            f"optim.name={ocfg.name!r}: trust-ratio optimizers own their "
            "momentum term (optim.beta1); set optim.momentum=0")
    if ocfg.name == "momentum" and ocfg.momentum <= 0.0:
        raise ConfigError(
            f"optim.name='momentum' with optim.momentum={ocfg.momentum}: "
            "the explicit momentum optimizer needs a positive coefficient "
            "(heavyball at 0 is exactly plain sgd — name that instead)")
    if ocfg.schedule not in ("exponential", "polynomial"):
        raise ConfigError(
            f"unknown optim.schedule {ocfg.schedule!r}; valid: "
            "['exponential', 'polynomial']")


def opt_state_kind(ocfg: OptimConfig) -> str:
    """The canonical optimizer-STATE identity a checkpoint carries:
    ``none`` (stateless), ``momentum``, ``lars`` or ``lamb``. ``sgd``
    with ``momentum > 0`` is heavyball momentum (the knob's historical
    meaning), so its state kind is ``momentum``. This is what the
    cross-optimizer restore guard compares (parallel/api.py
    ``restore_for_topology``): LARS and momentum state share a tree
    shape but not semantics, so kinds differ even when layouts match."""
    validate(ocfg)
    if ocfg.name == "sgd":
        return "momentum" if ocfg.momentum > 0.0 else "none"
    return ocfg.name


def saved_opt_state_kind(optim_dict: dict | None) -> str | None:
    """``opt_state_kind`` over a checkpoint's saved ``config.optim``
    dict — tolerant of foreign/extra keys (an older or newer schema)
    and of invalid saved combinations (the identity is still the name).
    None when the dict carries nothing usable."""
    if not isinstance(optim_dict, dict):
        return None
    name = optim_dict.get("name", "sgd")
    if name == "sgd":
        return "momentum" if optim_dict.get("momentum", 0.0) else "none"
    return str(name)


def slot_dtype(param_dtype) -> Any:
    """Moment-slot dtype for a param leaf: float32 for any float param
    (a bf16 moment would quantize the accumulation the slot exists to
    carry), the param dtype otherwise."""
    return (jnp.float32 if jnp.issubdtype(jnp.dtype(param_dtype), jnp.floating)
            else jnp.dtype(param_dtype))


# ---------------------------------------------------------------------------
# slot-tree plumbing (opt state = None | tree | {"m": tree, "v": tree})
# ---------------------------------------------------------------------------

_SLOT_KEYS = frozenset({"m", "v"})


def is_slot_dict(opt_state: Any) -> bool:
    """True for the two-slot ``{"m": tree, "v": tree}`` layout."""
    return isinstance(opt_state, dict) and set(opt_state) == _SLOT_KEYS


def map_slots(fn: Callable[[Any], Any], opt_state: Any) -> Any:
    """Apply ``fn`` to each params-shaped slot tree of an optimizer
    state, preserving the layout. The structural twin of the per-slot
    ZeRO-1 pack/unpack/spec derivations — callers that cannot see the
    Optimizer (e.g. ``canonical_save_state``) detect the two-slot
    layout by its reserved key set."""
    if opt_state is None:
        return None
    if is_slot_dict(opt_state):
        return {k: fn(tree) for k, tree in opt_state.items()}
    return fn(opt_state)


def slot_trees(opt: Optimizer, opt_state: Any) -> list:
    """The optimizer state as an ordered list of params-shaped trees
    (length ``opt.num_slots``)."""
    if opt.num_slots == 0:
        return []
    if opt.num_slots == 1:
        return [opt_state]
    return [opt_state["m"], opt_state["v"]]


def from_slot_trees(opt: Optimizer, trees: Sequence) -> Any:
    if opt.num_slots == 0:
        return None
    if opt.num_slots == 1:
        return trees[0]
    return {"m": trees[0], "v": trees[1]}


def init_slots(opt: Optimizer, make_tree: Callable[[], Any]) -> Any:
    """Zeros-initialized optimizer state: ``make_tree()`` builds ONE
    params-shaped (or ZeRO-1-packed) float32 tree; called once per
    slot."""
    return from_slot_trees(opt, [make_tree() for _ in range(opt.num_slots)])


# ---------------------------------------------------------------------------
# the update rules
# ---------------------------------------------------------------------------

def _f32(x: jax.Array) -> jax.Array:
    return x.astype(jnp.float32)


def _norm(x32: jax.Array, norm_reduce) -> jax.Array:
    return jnp.sqrt(norm_reduce(jnp.sum(x32 * x32)))


def _sgd_leaf(p, g, slots, lr, t, norm_reduce, adapt):
    del slots, t, norm_reduce, adapt
    new_p = _f32(p) - lr * _f32(g)
    return new_p.astype(p.dtype), ()


def _make_momentum_leaf(mu: float) -> UpdateLeaf:
    def update(p, g, slots, lr, t, norm_reduce, adapt):
        del t, norm_reduce, adapt
        (b,) = slots
        nb = mu * _f32(b) + _f32(g)
        new_p = _f32(p) - lr * nb
        return new_p.astype(p.dtype), (nb.astype(b.dtype),)
    return update


def _make_lars_leaf(ocfg: OptimConfig) -> UpdateLeaf:
    mu, eta, wd = ocfg.beta1, ocfg.trust_coefficient, ocfg.weight_decay

    def update(p, g, slots, lr, t, norm_reduce, adapt):
        del t
        (b,) = slots
        p32, g32 = _f32(p), _f32(g)
        if adapt:
            gw = g32 + wd * p32
            w_norm = _norm(p32, norm_reduce)
            g_norm = _norm(gw, norm_reduce)
            # trust = eta·‖w‖/‖g + wd·w‖; 1 when either norm is 0
            # (fresh zero leaves must still move)
            trust = jnp.where((w_norm > 0.0) & (g_norm > 0.0),
                              eta * w_norm / jnp.maximum(g_norm, 1e-30), 1.0)
            gw = trust * gw
        else:
            gw = g32  # biases/norms: no decay, no adaptation
        nb = mu * _f32(b) + gw
        new_p = p32 - lr * nb
        return new_p.astype(p.dtype), (nb.astype(b.dtype),)
    return update


def _make_lamb_leaf(ocfg: OptimConfig) -> UpdateLeaf:
    b1, b2, eps, wd = ocfg.beta1, ocfg.beta2, ocfg.eps, ocfg.weight_decay

    def update(p, g, slots, lr, t, norm_reduce, adapt):
        m, v = slots
        p32, g32 = _f32(p), _f32(g)
        nm = b1 * _f32(m) + (1.0 - b1) * g32
        nv = b2 * _f32(v) + (1.0 - b2) * g32 * g32
        m_hat = nm / (1.0 - jnp.power(b1, t))
        v_hat = nv / (1.0 - jnp.power(b2, t))
        u = m_hat / (jnp.sqrt(v_hat) + eps)
        if adapt:
            u = u + wd * p32
            w_norm = _norm(p32, norm_reduce)
            u_norm = _norm(u, norm_reduce)
            ratio = jnp.where((w_norm > 0.0) & (u_norm > 0.0),
                              w_norm / jnp.maximum(u_norm, 1e-30), 1.0)
        else:
            ratio = 1.0
        new_p = p32 - lr * ratio * u
        return new_p.astype(p.dtype), (nm.astype(m.dtype), nv.astype(v.dtype))
    return update


def make_optimizer(ocfg: OptimConfig) -> Optimizer:
    """Resolve the config into a registry entry (validating it)."""
    kind = opt_state_kind(ocfg)
    if kind == "none":
        return Optimizer(kind="none", num_slots=0, update_leaf=_sgd_leaf)
    if kind == "momentum":
        return Optimizer(kind="momentum", num_slots=1,
                         update_leaf=_make_momentum_leaf(ocfg.momentum))
    if kind == "lars":
        return Optimizer(kind="lars", num_slots=1,
                         update_leaf=_make_lars_leaf(ocfg))
    return Optimizer(kind="lamb", num_slots=2,
                     update_leaf=_make_lamb_leaf(ocfg))
