"""Learning-rate schedule.

Exponential staircase decay with the reference's exact semantics
(src/distributed_train.py:143-156): decay is keyed to the number of
*applied updates* (the reference's global_step — which counts PS
applies, not worker iterations), and

    decay_steps = (num_examples / batch_size) * num_epochs_per_decay / k

where ``k = num_replicas_to_aggregate`` (src/distributed_train.py:147)
— so convergence curves stay comparable across quorum settings.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

Schedule = Callable[[jax.Array], jax.Array]


def decay_steps_for(num_examples: int, batch_size: int,
                    num_epochs_per_decay: float, aggregate_k: int) -> int:
    num_batches_per_epoch = num_examples / batch_size
    return max(1, int(num_batches_per_epoch * num_epochs_per_decay / aggregate_k))


def exponential_decay(initial_lr: float, decay_steps: int,
                      decay_factor: float, staircase: bool = True) -> Schedule:
    """≙ tf.train.exponential_decay(staircase=True) at
    src/distributed_train.py:152-156."""

    def schedule(updates_applied: jax.Array) -> jax.Array:
        p = jnp.asarray(updates_applied, jnp.float32) / float(decay_steps)
        if staircase:
            p = jnp.floor(p)
        return jnp.asarray(initial_lr, jnp.float32) * jnp.power(decay_factor, p)

    return schedule


def constant(lr: float) -> Schedule:
    """No decay — the reference's 50-worker sweeps set decay_factor=1.0
    (cfg/50_workers/*_aggregate_sync:63-65)."""
    def schedule(updates_applied: jax.Array) -> jax.Array:
        del updates_applied
        return jnp.asarray(lr, jnp.float32)
    return schedule


def warmup_polynomial_decay(base_lr: float, warmup_steps: int,
                            total_steps: int, end_lr: float = 0.0,
                            power: float = 2.0) -> Schedule:
    """Linear warmup to ``base_lr`` over ``warmup_steps`` applied
    updates, then polynomial decay to ``end_lr`` at ``total_steps`` —
    the MLPerf large-batch recipe (arXiv:1909.09756 §3: LARS/LAMB pair
    with warmup + polynomial decay; power=2 is the MLPerf-0.6 setting).
    Keyed, like every schedule here, to *applied updates* so pacing is
    invariant to masked no-op steps. Past ``total_steps`` the rate
    holds at ``end_lr``."""
    if total_steps <= 0:
        raise ValueError(f"total_steps must be > 0, got {total_steps}")
    if warmup_steps >= total_steps:
        raise ValueError(f"warmup_steps ({warmup_steps}) must be < "
                         f"total_steps ({total_steps})")

    def schedule(updates_applied: jax.Array) -> jax.Array:
        t = jnp.asarray(updates_applied, jnp.float32)
        base = jnp.asarray(base_lr, jnp.float32)
        # warmup ramps 1/w, 2/w, … so update 0 never applies a zero lr
        warm = base * (t + 1.0) / max(warmup_steps, 1)
        frac = jnp.clip((t - warmup_steps)
                        / max(total_steps - warmup_steps, 1), 0.0, 1.0)
        decayed = (base - end_lr) * jnp.power(1.0 - frac, power) + end_lr
        return jnp.where(t < warmup_steps, warm, decayed)
    return schedule
