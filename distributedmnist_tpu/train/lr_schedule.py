"""Learning-rate schedule.

Exponential staircase decay with the reference's exact semantics
(src/distributed_train.py:143-156): decay is keyed to the number of
*applied updates* (the reference's global_step — which counts PS
applies, not worker iterations), and

    decay_steps = (num_examples / batch_size) * num_epochs_per_decay / k

where ``k = num_replicas_to_aggregate`` (src/distributed_train.py:147)
— so convergence curves stay comparable across quorum settings.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

Schedule = Callable[[jax.Array], jax.Array]


def decay_steps_for(num_examples: int, batch_size: int,
                    num_epochs_per_decay: float, aggregate_k: int) -> int:
    num_batches_per_epoch = num_examples / batch_size
    return max(1, int(num_batches_per_epoch * num_epochs_per_decay / aggregate_k))


def exponential_decay(initial_lr: float, decay_steps: int,
                      decay_factor: float, staircase: bool = True) -> Schedule:
    """≙ tf.train.exponential_decay(staircase=True) at
    src/distributed_train.py:152-156."""

    def schedule(updates_applied: jax.Array) -> jax.Array:
        p = jnp.asarray(updates_applied, jnp.float32) / float(decay_steps)
        if staircase:
            p = jnp.floor(p)
        return jnp.asarray(initial_lr, jnp.float32) * jnp.power(decay_factor, p)

    return schedule


def constant(lr: float) -> Schedule:
    """No decay — the reference's 50-worker sweeps set decay_factor=1.0
    (cfg/50_workers/*_aggregate_sync:63-65)."""
    def schedule(updates_applied: jax.Array) -> jax.Array:
        del updates_applied
        return jnp.asarray(lr, jnp.float32)
    return schedule
