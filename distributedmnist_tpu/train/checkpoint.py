"""Checkpoint save/restore.

≙ the reference's ``tf.train.Saver`` + Supervisor autosave +
restore-if-present (src/distributed_train.py:222,244-252,262,405-408)
and the evaluator's read side (src/nn_eval.py:70-88). Differences:

* msgpack-serialized pytrees (flax.serialization) written atomically
  (tmp + rename) so a reader never sees a torn file — the reference
  relies on Saver's own atomicity over NFS.
* The data-iterator position and config are checkpointed too, so
  *resume is exact* (the reference resumes params but restarts its
  time-seeded data stream from scratch).
* A ``checkpoint.json`` pointer names the latest step — the moral
  equivalent of TF's ``checkpoint`` proto file.
* **Quantized sidecar tiers** (``quant/`` — the serving-precision
  pass): a publish may additionally write
  ``ckpt-{step}.quant.msgpack`` next to the artifact, holding
  ``{"tiers": {tier: state-dict-shaped param tree}, "meta": json}``
  for the configured tiers — ``int8`` leaves are
  ``{"q": int8[..., C], "scale": float32[1, ..., C]}`` per-channel
  pairs (1-D leaves stay float32), ``bf16`` leaves a straight bf16
  cast; ``meta`` records the source params' sha256, the calibration
  stats, and the tier list. The sidecar gets its OWN ``.sha256``
  digest sidecar through the same atomic-write machinery, so a torn
  sidecar is refused exactly like a torn checkpoint (the serving
  replica then falls back to the full-precision artifact). Sidecars
  are ADDITIVE: the full-precision artifact's bytes and digest are
  untouched by publishing them, they never make a step "loadable" on
  their own, and they garbage-collect with their step.
* **Per-host sharded format** (SURVEY §2.3 "per-host array
  serialization", ≙ the Saver-over-NFS multi-worker layout): when the
  state holds arrays whose shards this process cannot fully
  materialize (a model/seq/stage/expert axis crossing process
  boundaries), EVERY process writes
  ``ckpt-{step}.shard{p}-of-{P}.msgpack`` with its addressable shard
  data keyed by global index, and process 0 writes a
  ``ckpt-{step}.manifest.json`` (global shapes/dtypes + the extra
  payload) plus the pointer. Restore reads every shard file and
  reassembles full global arrays — so any layout-compatible consumer
  (a resumed cluster of any process count, the evaluator on its own
  mesh, a single device) can load the checkpoint.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from pathlib import Path
from typing import Any, Callable

import jax
import numpy as np
from flax import serialization

from ..core.log import get_logger
from . import storage

logger = get_logger("checkpoint")

_POINTER = "checkpoint.json"
_DIGEST_SUFFIX = ".sha256"


class CheckpointCorruptError(ValueError):
    """A checkpoint artifact exists but cannot be trusted: torn write
    (truncated msgpack / unparseable manifest) or checksum mismatch.
    Distinct from FileNotFoundError (an incomplete publish) so callers
    can tell "never finished writing" from "finished then damaged" —
    both fall back to the previous loadable step on restore.

    Subclasses ValueError because that is what the raw failures
    (msgpack unpack errors, json.JSONDecodeError) raised before this
    wrapper existed — long-running consumers like the eval service
    catch ValueError around checkpoint reads and skip-and-retry; this
    type must keep flowing into those handlers, not crash them."""


class WorldSizeMismatchError(ValueError):
    """A checkpoint was written under a different world
    ``(replica count, process count, mesh shape)`` than the consumer
    requires. Deliberately NOT a :class:`CheckpointCorruptError`: a
    world mismatch affects EVERY step of the run equally, so the
    restore must not "fall back" past all of them and silently discard
    the run — it must surface so the caller can branch: the
    supervisor's reconfigure path (and the mesh-portable
    ``parallel.api.restore_for_topology``) reshards the artifact for
    the new world; a strict consumer aborts with both worlds named
    instead of a raw flax structure error."""

    def __init__(self, msg: str, saved_world: dict | None = None,
                 requested_world: dict | None = None):
        super().__init__(msg)
        self.saved_world = saved_world
        self.requested_world = requested_world


class OptimizerStateMismatchError(ValueError):
    """A checkpoint carries a different optimizer-STATE kind
    (none/momentum/lars/lamb — train/optim.opt_state_kind) than the
    restoring run's config. Like :class:`WorldSizeMismatchError`, this
    is deliberately NOT a :class:`CheckpointCorruptError`: the mismatch
    affects every step of the run equally, so the restore must surface
    it — naming both kinds — rather than fall back past the whole run
    or silently graft one optimizer's moments into another's slots
    (momentum and LARS state even share a tree shape, so the structural
    graft would SUCCEED and quietly corrupt the trust-ratio math)."""

    def __init__(self, msg: str, saved_kind: str | None = None,
                 requested_kind: str | None = None):
        super().__init__(msg)
        self.saved_kind = saved_kind
        self.requested_kind = requested_kind


# -- I/O retry wrapper ------------------------------------------------------
#
# Checkpoint reads/writes hit network filesystems in production; a
# transient EIO/ESTALE must not look like corruption (which would
# discard a perfectly good step). FileNotFoundError stays immediate:
# a missing file is a publish-ordering fact, not a flake.

_IO_ATTEMPTS = 3
_IO_BACKOFF_S = 0.05


def _io_retries(fn: Callable[[], Any], what: str) -> Any:
    for attempt in range(1, _IO_ATTEMPTS + 1):
        try:
            return fn()
        except FileNotFoundError:
            raise
        except OSError as e:
            if attempt == _IO_ATTEMPTS:
                raise
            delay = _IO_BACKOFF_S * 2 ** (attempt - 1)
            logger.warning("I/O error on %s (%s) — attempt %d/%d, "
                           "retrying in %.2fs", what, e, attempt,
                           _IO_ATTEMPTS, delay)
            time.sleep(delay)


def _ckpt_path(train_dir: Path, step: int) -> Path:
    return train_dir / f"ckpt-{step:08d}.msgpack"


def _manifest_path(train_dir: Path, step: int) -> Path:
    return train_dir / f"ckpt-{step:08d}.manifest.json"


def _shard_path(train_dir: Path, step: int, p: int, count: int) -> Path:
    return train_dir / f"ckpt-{step:08d}.shard{p:03d}-of-{count:03d}.msgpack"


def _leaf_locally_complete(leaf: Any) -> bool:
    """True when this process can materialize the WHOLE array."""
    if not isinstance(leaf, jax.Array):
        return True
    return bool(leaf.is_fully_addressable or leaf.is_fully_replicated)


def state_needs_sharded_save(state: Any) -> bool:
    """True when some array's shards live only on other processes —
    the single-file writer (a process-0 ``device_get``) cannot
    materialize it and the per-host sharded format must be used."""
    return not all(_leaf_locally_complete(l) for l in jax.tree.leaves(state))


def _flat_state_items(state: Any):
    """state → [("a/b/c", leaf)] over the flax state-dict view."""
    sd = serialization.to_state_dict(state)
    flat, _ = jax.tree_util.tree_flatten_with_path(sd)
    out = []
    for path, leaf in flat:
        key = "/".join(str(getattr(k, "key", k)) for k in path)
        out.append((key, leaf))
    return out


def snapshot_for_save(state: Any):
    """Synchronously pull this process's view of ``state`` to host.

    Returns ``("full", host_state_dict)`` when every leaf is locally
    complete (the classic single-file layout, written by process 0), or
    ``("sharded", local_leaves, meta)`` where ``local_leaves`` maps
    leaf keys to either a full ndarray (locally-complete leaves, kept
    by process 0 only) or ``{"indices": [...], "datas": [...]}`` shard
    slabs, and ``meta`` records global shape/dtype per leaf.
    """
    if not state_needs_sharded_save(state):
        return ("full", serialization.to_state_dict(jax.device_get(state)))
    pidx = jax.process_index()
    local: dict = {}
    meta: dict = {}
    for key, leaf in _flat_state_items(state):
        if leaf is None:
            continue
        if _leaf_locally_complete(leaf):
            meta[key] = {"full": True}
            if pidx == 0:
                local[key] = np.asarray(jax.device_get(leaf))
            continue
        meta[key] = {"shape": list(leaf.shape), "dtype": str(leaf.dtype)}
        slabs: dict = {}
        for sh in leaf.addressable_shards:
            idx = tuple(sl.indices(dim)[:2]
                        for sl, dim in zip(sh.index, leaf.shape))
            if idx not in slabs:  # replicas of the same slab: keep one
                slabs[idx] = np.asarray(sh.data)
        local[key] = {
            "indices": [[list(ab) for ab in idx] for idx in slabs],
            "datas": list(slabs.values()),
        }
    return ("sharded", local, meta)


class _LeafViews:
    """Zero-copy per-shard host views of ONE device array — the CPU
    client's donation-safe snapshot primitive. ``np.asarray`` of a
    single-device shard is a host view (no copy), and PJRT's
    copy-on-donate protects any buffer with a live external reference,
    so the views keep their pre-donation values after the next step
    donates the state (verified on jaxlib 0.4.37). Crucially the
    CROSS-SHARD ASSEMBLY of replica-split (ZeRO-1) leaves — the real
    per-save cost — is deferred to :meth:`materialize` on the
    checkpoint worker thread instead of the train loop."""

    __slots__ = ("shape", "dtype", "slabs")

    def __init__(self, x: "jax.Array"):
        self.shape, self.dtype = tuple(x.shape), x.dtype
        self.slabs: list = []
        seen = set()
        for sh in x.addressable_shards:
            idx = tuple(sl.indices(dim)[:2]
                        for sl, dim in zip(sh.index, x.shape))
            if idx in seen:  # replicas of the same slab: keep one
                continue
            seen.add(idx)
            self.slabs.append((idx, np.asarray(sh.data)))

    def materialize(self) -> np.ndarray:
        if len(self.slabs) == 1 and self.slabs[0][1].shape == self.shape:
            return self.slabs[0][1]
        buf = np.empty(self.shape, self.dtype)
        for idx, data in self.slabs:
            buf[tuple(slice(a, b) for a, b in idx)] = data
        return buf


def host_view_snapshot(state: Any) -> Any:
    """Snapshot ``state`` as per-shard host views (:class:`_LeafViews`
    per jax leaf; other leaves pass through) — near-zero cost on the
    train loop. Pair with :func:`materialize_snapshot` on the worker.
    CPU-client only: on accelerators ``np.asarray(shard.data)`` is a
    blocking D2H transfer, exactly the stall this exists to avoid —
    those backends snapshot via an async on-device copy instead
    (train/loop.py)."""
    return jax.tree.map(
        lambda x: _LeafViews(x) if isinstance(x, jax.Array) else x, state)


def materialize_snapshot(tree: Any) -> Any:
    """Assemble a :func:`host_view_snapshot` back into plain numpy
    leaves (the worker-thread half)."""
    return jax.tree.map(
        lambda x: x.materialize() if isinstance(x, _LeafViews) else x,
        tree, is_leaf=lambda x: isinstance(x, _LeafViews))


def _digest_path(path: Path) -> Path:
    return path.with_suffix(path.suffix + _DIGEST_SUFFIX)


def _write_atomic(path: Path, data: bytes, digest: bool = True) -> None:
    def write() -> None:
        tmp = path.with_suffix(path.suffix + ".tmp")
        storage.write_bytes(tmp, data, role="data")
        dpath = _digest_path(path)
        if digest:
            # drop any PREVIOUS sidecar before the data lands: this
            # path can be overwritten (a NaN rollback or kill+resume
            # re-saves the same step), and a crash after the new data
            # but before the new digest must leave a digest-LESS
            # (legacy-accepted) file — never old-digest-over-new-bytes,
            # which would reject a perfectly good checkpoint
            dpath.unlink(missing_ok=True)
        storage.replace(tmp, path, role="data")
        if digest:
            dtmp = dpath.with_name(dpath.name + ".tmp")
            storage.write_text(dtmp, hashlib.sha256(data).hexdigest(),
                               role="sidecar")
            storage.replace(dtmp, dpath, role="sidecar")
    _io_retries(write, path.name)


def _verified_read(path: Path) -> bytes:
    """Read ``path`` (with I/O retries) and verify it against its
    digest sidecar when one exists — a file without a sidecar is
    accepted as-is (pre-checksum layout, or a crash between data and
    digest writes)."""
    data = _io_retries(lambda: storage.read_bytes(path), path.name)
    dpath = _digest_path(path)
    if dpath.exists():
        want = _io_retries(lambda: storage.read_text(dpath),
                           dpath.name).strip()
        got = hashlib.sha256(data).hexdigest()
        if want and got != want:
            raise CheckpointCorruptError(
                f"{path.name}: sha256 mismatch (file {got[:12]}… != "
                f"recorded {want[:12]}…)")
    return data


def verify_artifact(path: str | Path) -> None:
    """Public digest check for one checkpoint artifact: raises
    :class:`CheckpointCorruptError` when ``path`` fails its sha256
    sidecar (a file WITHOUT a sidecar is accepted — pre-checksum
    layout, or a crash between the data and digest writes). The
    invariant checker (obsv/invariants.py) audits checkpoint dirs
    through this so the sidecar contract lives in exactly one place."""
    _verified_read(Path(path))


def _msgpack_restore_checked(data: bytes, path: Path) -> Any:
    try:
        return serialization.msgpack_restore(data)
    except Exception as e:  # msgpack raises several unpack error types
        raise CheckpointCorruptError(
            f"{path.name}: torn or corrupt msgpack ({type(e).__name__}: "
            f"{e})") from e


def _manifest_checksum(manifest: dict) -> str:
    body = {k: v for k, v in manifest.items() if k != "checksum"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()).hexdigest()


def _read_manifest(train_dir: Path, step: int) -> dict:
    mpath = _manifest_path(train_dir, step)
    text = _io_retries(lambda: storage.read_text(mpath), mpath.name)
    try:
        manifest = json.loads(text)
    except json.JSONDecodeError as e:
        raise CheckpointCorruptError(
            f"{mpath.name}: torn or corrupt manifest ({e})") from e
    want = manifest.get("checksum")
    if want and _manifest_checksum(manifest) != want:
        raise CheckpointCorruptError(f"{mpath.name}: checksum mismatch")
    return manifest


def _write_pointer(train_dir: Path, step: int, latest_name: str) -> None:
    pointer = {"latest_step": step, "latest_path": latest_name,
               "written_at": time.time()}
    ptmp = train_dir / (_POINTER + ".tmp")
    storage.write_text(ptmp, json.dumps(pointer), role="pointer")
    storage.replace(ptmp, train_dir / _POINTER, role="pointer")


def save_checkpoint(train_dir: str | Path, state: Any, step: int,
                    extra: dict | None = None, keep: int = 5) -> Path:
    """Atomically write state (+ JSON-serializable ``extra``) at
    ``step``. ``state`` may be a live (possibly device-sharded) pytree
    or a snapshot from :func:`snapshot_for_save` (the async writer's
    path). Single-file when this process can materialize everything;
    per-host sharded otherwise (module docstring) — in the sharded case
    EVERY process must call this (each writes its own shard file)."""
    train_dir = Path(train_dir)
    train_dir.mkdir(parents=True, exist_ok=True)
    snap = (state if isinstance(state, tuple)
            and state and state[0] in ("full", "sharded")
            else snapshot_for_save(state))

    if snap[0] == "full":
        # extra goes through JSON (tuples etc. are not msgpack-clean)
        payload = {"state": snap[1], "extra": json.dumps(extra or {})}
        data = serialization.msgpack_serialize(payload)
        path = _ckpt_path(train_dir, step)
        _write_atomic(path, data)
        _write_pointer(train_dir, step, path.name)
        _garbage_collect(train_dir, keep)
        logger.info("saved checkpoint step=%d → %s", step, path.name)
        return path

    _, local, meta = snap
    pidx, pcount = jax.process_index(), jax.process_count()
    path = _shard_path(train_dir, step, pidx, pcount)
    _write_atomic(path, serialization.msgpack_serialize({"leaves": local}))
    if pidx == 0:
        manifest = {"step": step, "num_shards": pcount, "leaves": meta,
                    "extra": extra or {}}
        manifest["checksum"] = _manifest_checksum(manifest)
        mpath = _manifest_path(train_dir, step)
        _write_atomic(mpath, json.dumps(manifest).encode(), digest=False)
        _write_pointer(train_dir, step, mpath.name)
        logger.info("saved sharded checkpoint step=%d → %s (+%d shard files)",
                    step, mpath.name, pcount)
    _garbage_collect(train_dir, keep)
    return path


class AsyncCheckpointer:
    """Background-thread checkpoint writer.

    The reference's Supervisor saves synchronously from its own timer
    thread (src/distributed_train.py:244-252); here the *train loop*
    triggers saves, so serialization + file IO must not stall the step
    cadence. By default ``save`` fetches state to host synchronously
    (the step function donates its input buffers, so a background
    device read of the LIVE state would race with donation) and hands
    the numpy pytree to a worker that msgpacks and writes it.

    **Donation-safe device snapshots** (``prepare=``): a caller that
    has already copied the state into fresh un-donated device buffers
    (train/loop.py dispatches that copy right after the step, BEFORE
    the next step's program is enqueued — so the copy reads the
    donated buffers first) passes the device-array pytree plus a
    ``prepare`` callable; the WORKER thread runs ``prepare`` (D2H
    fetch + canonical-layout conversion) before writing, and the train
    loop's stall shrinks to the copy dispatch. A ``prepare`` failure
    counts as a failed write (logged + surfaced on ``wait``), same as
    an IO error.

    Latest-wins: if a save is still in flight when the next one
    arrives, the pending one is replaced — checkpoints are snapshots,
    not a journal. Worker errors surface on the next ``save``/``wait``.
    """

    def __init__(self, max_consecutive_failures: int = 3,
                 on_error: Callable[[int, Exception], None] | None = None):
        self._lock = threading.Lock()
        self._pending: tuple | None = None
        self._busy = False
        self._error: Exception | None = None  # last write's outcome
        self._last_failure: Exception | None = None  # never cleared by wait()
        self._consecutive_failures = 0
        self.max_consecutive_failures = max_consecutive_failures
        # Journal hook ``(step, exception)`` for a failed write — the
        # trainer records a schema-declared ``save_failed`` recovery
        # event so a skipped cadence save is auditable evidence, not
        # just a log line (obsv/invariants.py storage_faults licenses
        # it against the injected disk fault that caused it).
        self._on_error = on_error
        self._wake = threading.Condition(self._lock)
        self._stop = False
        self.closed = False
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="ckpt-writer")
        self._thread.start()

    def _worker(self) -> None:
        while True:
            with self._wake:
                while self._pending is None and not self._stop:
                    self._wake.wait()
                if self._stop and self._pending is None:
                    return
                job = self._pending
                self._pending = None
                self._busy = True
            try:
                *args, prepare, publish = job
                if prepare is not None:
                    # device snapshot → host + canonical layout, off
                    # the train loop's critical path
                    args[1] = prepare(args[1])
                if publish is not None:
                    # sidecar hook (the quant tiers): runs BEFORE the
                    # artifact/pointer write — a follower that sees
                    # the pointer name a new step must find its
                    # sidecar already on disk, or a fast poll lands in
                    # the gap, falls back to fp32, and (cursor
                    # advanced) never revisits that step's tier. A
                    # sidecar with no artifact yet is harmless: it
                    # never makes a step loadable and GCs with it. A
                    # sidecar failure must never read as a failed
                    # CHECKPOINT.
                    try:
                        publish(args[1], args[2])
                    except Exception as e:
                        logger.warning("pre-save publish hook for "
                                       "step=%d failed: %s", args[2], e)
                save_checkpoint(*args)
            except Exception as e:
                # Log NOW (the failure may otherwise go unnoticed for
                # hours of training); also kept for wait() to raise.
                logger.error("async checkpoint write for step=%d failed: %s",
                             job[2], e)
                with self._lock:
                    self._error = e
                    self._last_failure = e
                    self._consecutive_failures += 1
                if self._on_error is not None:
                    try:
                        self._on_error(job[2], e)
                    except Exception:
                        logger.exception("checkpoint on_error hook failed")
            else:
                with self._lock:
                    self._error = None  # a later success supersedes
                    self._consecutive_failures = 0
            finally:
                with self._wake:
                    self._busy = False
                    self._wake.notify_all()

    def _raise_pending_error(self) -> None:
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError("async checkpoint write failed") from err

    def save(self, train_dir: str | Path, state: Any, step: int,
             extra: dict | None = None, keep: int = 5,
             no_skip: bool = False,
             prepare: Callable[[Any], Any] | None = None,
             publish: Callable[[Any, int], Any] | None = None) -> None:
        """Queue a write. A single failed write never raises here —
        that already went to the log and a later save may well succeed
        (transient disk pressure); ``wait`` raises if the LAST write
        failed, so a broken final checkpoint is never silent. A
        persistently broken disk does stop training: after
        ``max_consecutive_failures`` failed writes in a row, ``save``
        raises instead of letting checkpoints go silently stale.

        ``no_skip``: drain a lagging queued write instead of replacing
        it — the per-host sharded layout needs EVERY process to write
        EVERY triggered step, or a process that skipped a different
        step than its siblings would leave that checkpoint torn.

        ``prepare``: defer the host snapshot to the worker thread (the
        donation-safe device-snapshot path, class docstring) — the
        caller must pass buffers the step will NOT donate (a fresh
        device copy).

        ``publish``: sidecar hook ``(prepared_state, step)`` run by
        the worker BEFORE the artifact/pointer write (the quantized-
        tier pass rides here so it stays off the step loop AND so a
        follower that sees the new pointer always finds the sidecar
        already published); its failures are logged, never surfaced
        as checkpoint failures (the sidecar is additive)."""
        with self._lock:
            if self._consecutive_failures >= self.max_consecutive_failures:
                raise RuntimeError(
                    f"{self._consecutive_failures} consecutive async "
                    "checkpoint writes failed; giving up"
                ) from self._last_failure
        if prepare is None:
            # sync snapshot: buffers get donated next step (sharded
            # states snapshot their addressable shards the same way)
            host_state = snapshot_for_save(state)
        else:
            host_state = state  # un-donated device copy; worker fetches
        if no_skip:
            with self._wake:
                while self._pending is not None and not self.closed:
                    self._wake.wait()
        with self._wake:
            if self.closed:
                raise RuntimeError("AsyncCheckpointer is closed")
            if self._pending is not None:
                logger.warning("checkpoint writer lagging; replacing queued "
                               "step=%d with step=%d", self._pending[2], step)
            self._pending = (train_dir, host_state, step, extra, keep,
                             prepare, publish)
            self._wake.notify_all()

    def wait(self) -> None:
        """Drain in-flight writes (call before exit / final save)."""
        with self._wake:
            while self._pending is not None or self._busy:
                self._wake.wait()
        self._raise_pending_error()

    def close(self) -> None:
        self.wait()
        with self._wake:
            self._stop = True
            self.closed = True
            self._wake.notify_all()
        self._thread.join(timeout=60)


_STEP_RE = re.compile(r"^ckpt-(\d+)")


def _ckpt_step_of(name: str) -> int | None:
    m = _STEP_RE.match(name)
    return int(m.group(1)) if m else None


def _garbage_collect(train_dir: Path, keep: int) -> None:
    """Keep the last ``keep`` STEPS — single files, shard files, and
    manifests all group by their step prefix. Every process of a
    sharded run GCs (concurrent unlinks race benignly)."""
    if keep <= 0:
        return
    by_step: dict[int, list[Path]] = {}
    for f in train_dir.glob("ckpt-*"):
        s = _ckpt_step_of(f.name)
        if s is not None and not f.name.endswith(".tmp"):
            by_step.setdefault(s, []).append(f)
    for s in sorted(by_step)[:-keep]:
        for old in by_step[s]:
            try:
                old.unlink()
            except OSError:
                pass


def latest_checkpoint_step(train_dir: str | Path) -> int | None:
    """Read the pointer (≙ tf.train.get_checkpoint_state,
    src/nn_eval.py:70); falls back to a directory scan if the pointer
    is missing/torn."""
    train_dir = Path(train_dir)
    ptr = train_dir / _POINTER
    if ptr.exists():
        try:
            d = json.loads(storage.read_text(ptr))
            if (train_dir / d["latest_path"]).exists():
                return int(d["latest_step"])
        except (json.JSONDecodeError, KeyError, ValueError, OSError):
            pass
    steps = _loadable_steps(train_dir)
    if not steps:
        return None
    return max(steps)


def loadable_steps(train_dir: str | Path) -> list[int]:
    """Public view of the restorable steps in ``train_dir`` (ascending)
    — what the NaN-guard rollback and the supervisor iterate over."""
    return _loadable_steps(Path(train_dir))


def _loadable_steps(train_dir: Path) -> list[int]:
    """Steps that can actually be restored: a single-file .msgpack or a
    manifest (shard files alone — a crash mid-publish — don't count)."""
    steps = set()
    for f in train_dir.glob("ckpt-*"):
        s = _ckpt_step_of(f.name)
        if s is None or f.name.endswith(".tmp"):
            continue
        if f.name.endswith(".manifest.json") or f.name == _ckpt_path(
                train_dir, s).name:
            steps.add(s)
    return sorted(steps)


def _digest_tree(tree: Any, h) -> None:
    """Fold a nested state-dict of arrays into ``h`` canonically:
    sorted key paths, then dtype/shape/bytes per leaf, every component
    NUL-delimited so adjacent fields can never be re-split into a
    colliding byte stream — two trees hash equal iff their structure
    and arrays are identical."""
    if isinstance(tree, dict):
        h.update(b"{\x00")
        for key in sorted(tree):
            h.update(str(key).encode() + b"\x00")
            _digest_tree(tree[key], h)
        h.update(b"}\x00")
        return
    if tree is None:
        h.update(b"<none>\x00")
        return
    a = np.ascontiguousarray(np.asarray(jax.device_get(tree)))
    h.update(str(a.dtype).encode() + b"\x00")
    h.update(str(a.shape).encode() + b"\x00")
    h.update(a.tobytes())
    h.update(b"\x00")


def state_params_digest(state: Any) -> str:
    """sha256 over the live state's param leaves — the model's bitwise
    identity, independent of where/when it was saved. The determinism
    seam the chaos invariant checker compares runs by: a faulted but
    fully-recovered run must reproduce the fault-free run's digest."""
    h = hashlib.sha256()
    _digest_tree(serialization.to_state_dict(state.params), h)
    return h.hexdigest()


def _checkpoint_state_dict(train_dir: Path, step: int | None
                           ) -> tuple[dict, int] | None:
    """The raw saved state dict of a checkpoint artifact (no model
    template) — the shared read behind the artifact digests. None when
    nothing is loadable. Single-file layout only (the local chaos
    workers are single-process); a sharded checkpoint raises so a
    silent cross-layout miscompare cannot happen."""
    if step is None:
        step = latest_checkpoint_step(train_dir)
        if step is None:
            return None
    if _manifest_path(train_dir, step).exists():
        raise NotImplementedError(
            "artifact digests over the sharded layout are not supported — "
            "restore through a template and use state_params_digest")
    path = _ckpt_path(train_dir, step)
    payload = _msgpack_restore_checked(_verified_read(path), path)
    state = payload.get("state")
    if not isinstance(state, dict) or state.get("params") is None:
        raise CheckpointCorruptError(
            f"{path.name}: payload has no state/params entry")
    return state, step


def checkpoint_params_digest(train_dir: str | Path,
                             step: int | None = None
                             ) -> tuple[str, int] | None:
    """(sha256-of-params, step) for a saved checkpoint — computed from
    the ARTIFACT alone (raw state dict, no model template), so the
    invariant checker can compare two runs' checkpoints without
    building either model. None when nothing is loadable."""
    got = _checkpoint_state_dict(Path(train_dir), step)
    if got is None:
        return None
    state, step = got
    h = hashlib.sha256()
    _digest_tree(state["params"], h)
    return h.hexdigest(), step


def checkpoint_state_digests(train_dir: str | Path,
                             step: int | None = None
                             ) -> tuple[str, str, int] | None:
    """(params_digest, opt_state_digest, step) from ONE artifact read —
    what the determinism invariant compares per worker; the split
    functions below each re-read the file, so batch consumers use
    this."""
    got = _checkpoint_state_dict(Path(train_dir), step)
    if got is None:
        return None
    state, step = got
    hp, ho = hashlib.sha256(), hashlib.sha256()
    _digest_tree(state["params"], hp)
    _digest_tree(state.get("momentum"), ho)
    return hp.hexdigest(), ho.hexdigest(), step


def checkpoint_opt_state_digest(train_dir: str | Path,
                                step: int | None = None
                                ) -> tuple[str, int] | None:
    """(sha256-of-optimizer-state, step) over the artifact's
    ``momentum`` subtree — the optimizer-state half of the chaos
    determinism invariant (obsv/invariants.py #3). Checkpoints store
    momentum in the CANONICAL logical layout regardless of
    ``parallel.shard_weight_update`` (train/loop.py ``_save`` via
    parallel.api.canonical_save_state), so this digest is comparable
    across runs — and meaningful, not skipped, for replica-sharded
    optimizer state. A momentum-less run (momentum=0) digests the
    canonical ``<none>`` marker, which still compares equal between a
    trial and its reference."""
    got = _checkpoint_state_dict(Path(train_dir), step)
    if got is None:
        return None
    state, step = got
    h = hashlib.sha256()
    _digest_tree(state.get("momentum"), h)
    return h.hexdigest(), step


def read_checkpoint_extra(train_dir: str | Path,
                          step: int | None = None) -> tuple[dict, int] | None:
    """Read only the JSON ``extra`` payload (saved config, data-iter
    position) — needs NO state template, so the evaluator can bootstrap
    its config from a checkpoint of *any* model/optimizer shape before
    it knows what to build."""
    train_dir = Path(train_dir)
    if step is None:
        step = latest_checkpoint_step(train_dir)
        if step is None:
            return None
    mpath = _manifest_path(train_dir, step)
    if mpath.exists():
        return _read_manifest(train_dir, step).get("extra", {}), step
    path = _ckpt_path(train_dir, step)
    payload = _msgpack_restore_checked(_verified_read(path), path)
    extra = payload.get("extra", {})
    if isinstance(extra, (str, bytes)):
        extra = json.loads(extra)
    return extra, step


def read_checkpoint_world(train_dir: str | Path,
                          step: int | None = None
                          ) -> tuple[dict | None, int] | None:
    """The ``world`` record a checkpoint was saved under (the Trainer
    stamps ``parallel.api.world_signature`` into ``extra``) — what the
    supervisor's reconfigure path reads to name old vs new world, and
    None for pre-elastic artifacts. Returns ``(world | None, step)``,
    or None when nothing is loadable."""
    got = read_checkpoint_extra(train_dir, step)
    if got is None:
        return None
    extra, step = got
    world = (extra or {}).get("world")
    return (world if isinstance(world, dict) else None), step


class CheckpointFollower:
    """The newest-checkpoint hot-follow loop shared by the long-running
    checkpoint consumers (``evalsvc`` evaluator, ``servesvc`` serving
    replica): atomic pointer read, step-advanced check, and
    skip-and-retry on an unreadable/torn/corrupt artifact.

    One poll: :meth:`poll(read)` reads the pointer; when the newest
    step has advanced past the last one successfully consumed, it calls
    ``read(step)`` and returns its result. ``read`` raising
    ``OSError`` / ``ValueError`` (which covers
    :class:`CheckpointCorruptError`) / ``KeyError`` — the trainer's GC
    unlinking the step between the pointer read and the restore, a
    shared fs serving a torn file, a failed digest — is a SKIP, not a
    crash: the failure is remembered per step (``last_error``), None is
    returned, and the next poll retries (or moves on to a newer
    publish). ``read`` returning None (e.g. nothing restorable) leaves
    the cursor unmoved the same way. A long-running service built on
    this never dies to a torn publish."""

    def __init__(self, train_dir: str | Path,
                 on_event: Callable[[dict], None] | None = None):
        self.train_dir = Path(train_dir)
        self.last_step = -1          # last step successfully consumed
        self.last_error: tuple[int, str] | None = None  # (step, error)
        self.skips = 0               # torn/corrupt publishes survived
        self._on_event = on_event

    def newest_step(self) -> int | None:
        """The pointer's current step (None before the first publish)
        — exposed so callers can log 'nothing yet' distinctly."""
        return latest_checkpoint_step(self.train_dir)

    def poll(self, read: Callable[[int], Any]) -> Any | None:
        """One follow tick; returns ``read(step)``'s result for a newly
        advanced step, else None (nothing new, or the read failed and
        will be retried)."""
        step = self.newest_step()
        if step is None or step == self.last_step:
            return None
        try:
            out = read(step)
        except (OSError, ValueError, KeyError) as e:
            self.skips += 1
            self.last_error = (step, f"{type(e).__name__}: {e}")
            logger.warning("checkpoint step=%s unreadable (%s); "
                           "skip-and-retry", step, e)
            if self._on_event is not None:
                self._on_event({"layer": "checkpoint",
                                "action": "follow_skip", "step": step,
                                "error": self.last_error[1]})
            return None
        if out is None:
            return None
        self.last_step = step
        return out


def wait_for_run_config(train_dir: str | Path,
                        timeout_s: float = 600.0):
    """Block until the first checkpoint publishes, then adopt its
    saved config — the bootstrap both long-running checkpoint
    consumers (the evaluator and the serving replica) start from, so
    there is no trainer/consumer graph skew. Reads only the JSON
    ``extra`` payload (no state template), so any model/optimizer
    shape works. Returns an ``ExperimentConfig``."""
    from ..core.config import ExperimentConfig
    train_dir = Path(train_dir)
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            out = read_checkpoint_extra(train_dir)
        except (OSError, ValueError, KeyError) as e:
            # mid-replace read on a shared fs / torn file — the caller
            # is a long-running service, retry on the next poll
            logger.warning("checkpoint read failed (%s); retrying", e)
            out = None
        if out is not None:
            extra, _ = out
            if "config" in extra:
                return ExperimentConfig.from_dict(extra["config"])
            logger.warning("checkpoint has no saved config; using defaults")
            return ExperimentConfig()
        time.sleep(1.0)
    raise TimeoutError(
        f"no checkpoint appeared in {train_dir} within {timeout_s:.0f}s")


def artifact_digest(train_dir: str | Path, step: int) -> str | None:
    """The recorded sha256 of a step's single-file artifact (its digest
    sidecar) — what a serving replica journals as the identity of the
    weights it swapped in. None when no sidecar exists (pre-checksum
    layout) or the artifact is sharded (manifest layout)."""
    train_dir = Path(train_dir)
    dpath = _digest_path(_ckpt_path(train_dir, step))
    try:
        return storage.read_text(dpath).strip() or None
    except OSError:
        return None


def quant_sidecar_path(train_dir: str | Path, step: int) -> Path:
    """Where a step's quantized-tier sidecar lives (module docstring:
    the ``.quant.msgpack`` next to the artifact). The ``ckpt-`` prefix
    keeps it inside the step-grouped GC and the invariant checker's
    digest sweep; the distinct suffix keeps it OUT of
    ``_loadable_steps`` — a sidecar alone never makes a step
    restorable."""
    return Path(train_dir) / f"ckpt-{step:08d}.quant.msgpack"


def write_quant_sidecar(train_dir: str | Path, step: int,
                        tiers: dict, meta: dict) -> Path:
    """Atomically publish the quantized tiers for ``step`` (tmp +
    rename + sha256 digest sidecar — the exact torn-write contract the
    checkpoint artifact has). ``tiers`` maps tier name → state-dict-
    shaped param tree; ``meta`` is JSON-serializable provenance (source
    params digest, calibration record)."""
    path = quant_sidecar_path(train_dir, step)
    payload = {"tiers": tiers, "meta": json.dumps(meta)}
    _write_atomic(path, serialization.msgpack_serialize(payload))
    return path


def read_quant_sidecar(train_dir: str | Path, step: int) -> dict:
    """Digest-verified read of a step's quant sidecar →
    ``{"tiers": {...}, "meta": dict}``. Raises ``FileNotFoundError``
    when no sidecar was published, :class:`CheckpointCorruptError` on
    a torn payload or sha256 mismatch — both flow into the
    :class:`CheckpointFollower` skip path, so a serving replica treats
    a bad sidecar as "fall back to the full-precision artifact", never
    as a crash and never as something to serve."""
    path = quant_sidecar_path(train_dir, step)
    payload = _msgpack_restore_checked(_verified_read(path), path)
    if not isinstance(payload, dict) or not isinstance(
            payload.get("tiers"), dict):
        raise CheckpointCorruptError(
            f"{path.name}: payload has no 'tiers' entry")
    meta = payload.get("meta", {})
    if isinstance(meta, (str, bytes)):
        try:
            meta = json.loads(meta)
        except json.JSONDecodeError as e:
            raise CheckpointCorruptError(
                f"{path.name}: torn meta payload ({e})") from e
    return {"tiers": payload["tiers"], "meta": meta}


def quant_sidecar_digest(train_dir: str | Path, step: int) -> str | None:
    """The recorded sha256 of a step's quant sidecar (its digest
    sidecar) — what a serving replica journals as the identity of a
    quantized tier it swapped in. None when no sidecar (or no digest)
    exists."""
    dpath = _digest_path(quant_sidecar_path(train_dir, step))
    try:
        return storage.read_text(dpath).strip() or None
    except OSError:
        return None


def _check_world(extra: Any, step: int, expect_world: dict | None) -> None:
    """Strict-world gate: callers that CANNOT reshard (no
    restore_for_topology in their path) pass the world they require;
    an artifact recorded under a different world raises the typed
    mismatch instead of whatever downstream structure error the
    foreign layout would eventually produce."""
    if expect_world is None:
        return
    saved = (extra or {}).get("world") if isinstance(extra, dict) else None
    if isinstance(saved, dict) and saved != expect_world:
        raise WorldSizeMismatchError(
            f"checkpoint step={step} was saved under world {saved} but "
            f"this consumer requires world {expect_world}; reshard it "
            "through parallel.api.restore_for_topology (mesh-portable "
            "restore) instead of a same-world restore",
            saved_world=saved, requested_world=expect_world)


def _from_state_dict_checked(template_state: Any, saved: Any, extra: Any,
                             step: int, where: str,
                             expect_world: dict | None) -> Any:
    """``from_state_dict`` with the raw structure error upgraded: when
    the artifact records the world it was saved under, a graft failure
    names saved vs requested world (the typed error the supervisor's
    reconfigure path branches on) instead of a bare flax KeyError."""
    try:
        return serialization.from_state_dict(template_state, saved)
    except WorldSizeMismatchError:
        raise
    except Exception as e:
        saved_world = ((extra or {}).get("world")
                       if isinstance(extra, dict) else None)
        if isinstance(saved_world, dict) and (
                expect_world is None or saved_world != expect_world):
            raise WorldSizeMismatchError(
                f"{where}: checkpoint step={step} does not fit this "
                f"run's state template ({type(e).__name__}: {e}); the "
                f"artifact was saved under world {saved_world}"
                + (f" but this run is world {expect_world}"
                   if expect_world is not None else "")
                + " — reshard it through parallel.api."
                "restore_for_topology",
                saved_world=saved_world,
                requested_world=expect_world) from e
        raise


def _restore_sharded(train_dir: Path, template_state: Any, step: int,
                     expect_world: dict | None = None
                     ) -> tuple[Any, dict, int]:
    """Reassemble full global arrays from every process's shard file
    (readable by ANY process count — the evaluator or a resumed
    cluster of a different size reads the same files)."""
    manifest = _read_manifest(train_dir, step)
    _check_world(manifest.get("extra"), step, expect_world)
    try:
        pcount = int(manifest["num_shards"])
        meta = manifest["leaves"]
    except (KeyError, TypeError, ValueError) as e:
        raise CheckpointCorruptError(
            f"{_manifest_path(train_dir, step).name}: manifest missing "
            f"required fields ({type(e).__name__}: {e})") from e
    leaves: dict[str, np.ndarray] = {}
    for p in range(pcount):
        spath = _shard_path(train_dir, step, p, pcount)
        payload = _msgpack_restore_checked(_verified_read(spath), spath)
        try:
            for key, val in payload["leaves"].items():
                if isinstance(val, dict) and "indices" in val:
                    m = meta[key]
                    buf = leaves.setdefault(
                        key,
                        np.empty(tuple(m["shape"]), np.dtype(m["dtype"])))
                    for idx, data in zip(val["indices"], val["datas"]):
                        buf[tuple(slice(a, b) for a, b in idx)] = data
                elif key not in leaves:  # locally-complete leaf (first wins)
                    leaves[key] = np.asarray(val)
        except (KeyError, ValueError, TypeError, IndexError) as e:
            # structure that contradicts the manifest (missing meta,
            # slab shapes that don't fit) is damage to THIS step —
            # distinct from a template mismatch, which surfaces later
            # in from_state_dict and must stay loud
            raise CheckpointCorruptError(
                f"{spath.name}: shard/manifest structure mismatch "
                f"({type(e).__name__}: {e})") from e
    nested: dict = {}
    for key, arr in leaves.items():
        node = nested
        parts = key.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr

    # None fields (momentum off, non-interval mode) have no leaves, so
    # the flattened files carry no entry — graft them back from the
    # template so from_state_dict sees every field. A missing non-None
    # leaf means the shard set doesn't actually hold this step's state:
    # damage to THIS step, so it surfaces as CheckpointCorruptError and
    # the restore falls back to an older one.
    def graft_nones(sub: Any, tmpl: Any) -> Any:
        if tmpl is None:
            return None
        if isinstance(tmpl, dict):
            got = sub if isinstance(sub, dict) else {}
            return {k: (None if tv is None
                        else graft_nones(got.get(k, {}), tv)
                        if isinstance(tv, dict) else got[k])
                    for k, tv in tmpl.items()}
        return sub

    try:
        nested = graft_nones(nested,
                             serialization.to_state_dict(template_state))
    except KeyError as e:
        raise CheckpointCorruptError(
            f"sharded checkpoint step={step} is missing leaf {e} that "
            "the state requires") from e
    state = _from_state_dict_checked(
        template_state, nested, manifest.get("extra"), step,
        _manifest_path(train_dir, step).name, expect_world)
    return state, manifest.get("extra", {}), step


# Exceptions that mean "THIS step is unusable, an older one may not
# be": incomplete publish (FileNotFoundError), torn/garbled/lying
# artifacts (CheckpointCorruptError — parse failures, checksum
# mismatches, and shard/manifest structure contradictions are all
# wrapped into it at the read sites), or I/O that stayed broken through
# the retry budget (OSError). Deliberately NOT broader: a
# template/model mismatch (from_state_dict errors) affects EVERY step
# equally and must surface loudly, not silently discard the run by
# "falling back" past all of it.
_FALLBACK_ERRORS = (FileNotFoundError, CheckpointCorruptError, OSError)


def restore_checkpoint(train_dir: str | Path, template_state: Any,
                       step: int | None = None,
                       on_event: Callable[[dict], None] | None = None,
                       expect_world: dict | None = None,
                       ) -> tuple[Any, dict, int] | None:
    """Restore (state, extra, step); None when nothing exists
    (≙ Supervisor's restore-if-present, src/distributed_train.py:262).
    Handles both the single-file and the per-host sharded layouts.

    When no explicit ``step`` is given, an unusable latest checkpoint —
    a torn sharded publish (interrupted between process 0's manifest
    and a sibling's shard file; there is no cross-process barrier in
    the async writer), a truncated file, or a checksum mismatch — falls
    back to the next older loadable step instead of wedging the resume
    forever. Each skipped step is reported through ``on_event`` (a
    recovery-journal hook; receives one dict per fallback and one for
    the step finally restored when any fallback happened).

    ``expect_world``: a strict same-world gate for consumers that
    cannot reshard — an artifact recorded under a different world
    raises :class:`WorldSizeMismatchError` (which, like any template
    mismatch, is NOT fallen back past: it affects every step equally).
    Mesh-portable consumers leave it None and restore through
    ``parallel.api.restore_for_topology``."""
    train_dir = Path(train_dir)
    if step is not None:
        return _restore_step(train_dir, template_state, step, expect_world)
    candidates = _loadable_steps(train_dir)
    latest = latest_checkpoint_step(train_dir)
    if latest is not None and latest not in candidates:
        candidates.append(latest)
    fell_back = False
    for s in sorted(set(candidates), reverse=True):
        try:
            got = _restore_step(train_dir, template_state, s, expect_world)
        except _FALLBACK_ERRORS as e:
            fell_back = True
            logger.warning("checkpoint step=%d is unusable (%s: %s); "
                           "falling back to an older step",
                           s, type(e).__name__, e)
            if on_event is not None:
                on_event({"layer": "checkpoint",
                          "action": "corrupt_checkpoint_fallback",
                          "bad_step": s, "error": f"{type(e).__name__}: {e}"})
            continue
        if fell_back and on_event is not None:
            on_event({"layer": "checkpoint", "action": "fallback_restore",
                      "step": got[2]})
        return got
    return None


def _restore_step(train_dir: Path, template_state: Any, step: int,
                  expect_world: dict | None = None) -> tuple[Any, dict, int]:
    if _manifest_path(train_dir, step).exists():
        return _restore_sharded(train_dir, template_state, step,
                                expect_world)
    path = _ckpt_path(train_dir, step)
    payload = _msgpack_restore_checked(_verified_read(path), path)
    if not isinstance(payload, dict) or "state" not in payload:
        raise CheckpointCorruptError(
            f"{path.name}: payload has no 'state' entry")
    saved = payload["state"]
    extra = payload.get("extra", {})
    if isinstance(extra, (str, bytes)):
        extra = json.loads(extra)
    _check_world(extra, step, expect_world)
    # Migration: drop top-level fields the current TrainState no longer
    # has (e.g. pre-round-3 checkpoints carried a measured_ms scalar) —
    # from_state_dict hard-fails on unknown keys, which would make every
    # old checkpoint unresumable instead of forward-compatible.
    template_dict = serialization.to_state_dict(template_state)
    if isinstance(saved, dict) and isinstance(template_dict, dict):
        stale = set(saved) - set(template_dict)
        if stale:
            logger.warning("dropping stale checkpoint fields %s", sorted(stale))
            saved = {k: v for k, v in saved.items() if k not in stale}
    state = _from_state_dict_checked(template_state, saved, extra, step,
                                     path.name, expect_world)
    return state, extra, step
