"""Checkpoint save/restore.

≙ the reference's ``tf.train.Saver`` + Supervisor autosave +
restore-if-present (src/distributed_train.py:222,244-252,262,405-408)
and the evaluator's read side (src/nn_eval.py:70-88). Differences:

* msgpack-serialized pytrees (flax.serialization) written atomically
  (tmp + rename) so a reader never sees a torn file — the reference
  relies on Saver's own atomicity over NFS.
* The data-iterator position and config are checkpointed too, so
  *resume is exact* (the reference resumes params but restarts its
  time-seeded data stream from scratch).
* A ``checkpoint.json`` pointer names the latest step — the moral
  equivalent of TF's ``checkpoint`` proto file.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Any

import jax
from flax import serialization

from ..core.log import get_logger

logger = get_logger("checkpoint")

_POINTER = "checkpoint.json"


def _ckpt_path(train_dir: Path, step: int) -> Path:
    return train_dir / f"ckpt-{step:08d}.msgpack"


def save_checkpoint(train_dir: str | Path, state: Any, step: int,
                    extra: dict | None = None, keep: int = 5) -> Path:
    """Atomically write state (+ JSON-serializable ``extra``) at ``step``."""
    train_dir = Path(train_dir)
    train_dir.mkdir(parents=True, exist_ok=True)
    state = jax.device_get(state)
    # extra goes through JSON (tuples etc. are not msgpack-clean)
    payload = {"state": serialization.to_state_dict(state),
               "extra": json.dumps(extra or {})}
    data = serialization.msgpack_serialize(payload)
    path = _ckpt_path(train_dir, step)
    tmp = path.with_suffix(".tmp")
    tmp.write_bytes(data)
    os.replace(tmp, path)

    pointer = {"latest_step": step, "latest_path": path.name,
               "written_at": time.time()}
    ptmp = train_dir / (_POINTER + ".tmp")
    ptmp.write_text(json.dumps(pointer))
    os.replace(ptmp, train_dir / _POINTER)

    _garbage_collect(train_dir, keep)
    logger.info("saved checkpoint step=%d → %s", step, path.name)
    return path


def _garbage_collect(train_dir: Path, keep: int) -> None:
    if keep <= 0:
        return
    ckpts = sorted(train_dir.glob("ckpt-*.msgpack"))
    for old in ckpts[:-keep]:
        try:
            old.unlink()
        except OSError:
            pass


def latest_checkpoint_step(train_dir: str | Path) -> int | None:
    """Read the pointer (≙ tf.train.get_checkpoint_state,
    src/nn_eval.py:70); falls back to a directory scan if the pointer
    is missing/torn."""
    train_dir = Path(train_dir)
    ptr = train_dir / _POINTER
    if ptr.exists():
        try:
            d = json.loads(ptr.read_text())
            if (train_dir / d["latest_path"]).exists():
                return int(d["latest_step"])
        except (json.JSONDecodeError, KeyError, ValueError):
            pass
    ckpts = sorted(train_dir.glob("ckpt-*.msgpack"))
    if not ckpts:
        return None
    return int(ckpts[-1].stem.split("-")[1])


def read_checkpoint_extra(train_dir: str | Path,
                          step: int | None = None) -> tuple[dict, int] | None:
    """Read only the JSON ``extra`` payload (saved config, data-iter
    position) — needs NO state template, so the evaluator can bootstrap
    its config from a checkpoint of *any* model/optimizer shape before
    it knows what to build."""
    train_dir = Path(train_dir)
    if step is None:
        step = latest_checkpoint_step(train_dir)
        if step is None:
            return None
    payload = serialization.msgpack_restore(_ckpt_path(train_dir, step).read_bytes())
    extra = payload.get("extra", {})
    if isinstance(extra, (str, bytes)):
        extra = json.loads(extra)
    return extra, step


def restore_checkpoint(train_dir: str | Path, template_state: Any,
                       step: int | None = None) -> tuple[Any, dict, int] | None:
    """Restore (state, extra, step); None when nothing exists
    (≙ Supervisor's restore-if-present, src/distributed_train.py:262)."""
    train_dir = Path(train_dir)
    if step is None:
        step = latest_checkpoint_step(train_dir)
        if step is None:
            return None
    path = _ckpt_path(train_dir, step)
    payload = serialization.msgpack_restore(path.read_bytes())
    state = serialization.from_state_dict(template_state, payload["state"])
    extra = payload.get("extra", {})
    if isinstance(extra, (str, bytes)):
        extra = json.loads(extra)
    return state, extra, step
