"""Checkpoint save/restore.

≙ the reference's ``tf.train.Saver`` + Supervisor autosave +
restore-if-present (src/distributed_train.py:222,244-252,262,405-408)
and the evaluator's read side (src/nn_eval.py:70-88). Differences:

* msgpack-serialized pytrees (flax.serialization) written atomically
  (tmp + rename) so a reader never sees a torn file — the reference
  relies on Saver's own atomicity over NFS.
* The data-iterator position and config are checkpointed too, so
  *resume is exact* (the reference resumes params but restarts its
  time-seeded data stream from scratch).
* A ``checkpoint.json`` pointer names the latest step — the moral
  equivalent of TF's ``checkpoint`` proto file.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Any

import jax
from flax import serialization

from ..core.log import get_logger

logger = get_logger("checkpoint")

_POINTER = "checkpoint.json"


def _ckpt_path(train_dir: Path, step: int) -> Path:
    return train_dir / f"ckpt-{step:08d}.msgpack"


def save_checkpoint(train_dir: str | Path, state: Any, step: int,
                    extra: dict | None = None, keep: int = 5) -> Path:
    """Atomically write state (+ JSON-serializable ``extra``) at ``step``."""
    train_dir = Path(train_dir)
    train_dir.mkdir(parents=True, exist_ok=True)
    state = jax.device_get(state)
    # extra goes through JSON (tuples etc. are not msgpack-clean)
    payload = {"state": serialization.to_state_dict(state),
               "extra": json.dumps(extra or {})}
    data = serialization.msgpack_serialize(payload)
    path = _ckpt_path(train_dir, step)
    tmp = path.with_suffix(".tmp")
    tmp.write_bytes(data)
    os.replace(tmp, path)

    pointer = {"latest_step": step, "latest_path": path.name,
               "written_at": time.time()}
    ptmp = train_dir / (_POINTER + ".tmp")
    ptmp.write_text(json.dumps(pointer))
    os.replace(ptmp, train_dir / _POINTER)

    _garbage_collect(train_dir, keep)
    logger.info("saved checkpoint step=%d → %s", step, path.name)
    return path


class AsyncCheckpointer:
    """Background-thread checkpoint writer.

    The reference's Supervisor saves synchronously from its own timer
    thread (src/distributed_train.py:244-252); here the *train loop*
    triggers saves, so serialization + file IO must not stall the step
    cadence. ``save`` fetches state to host synchronously (the step
    function donates its input buffers, so a background device read
    would race with donation) and hands the numpy pytree to a worker
    that msgpacks and writes it. Latest-wins: if a save is still in
    flight when the next one arrives, the pending one is replaced —
    checkpoints are snapshots, not a journal. Worker errors surface on
    the next ``save``/``wait``.
    """

    def __init__(self, max_consecutive_failures: int = 3):
        self._lock = threading.Lock()
        self._pending: tuple | None = None
        self._busy = False
        self._error: Exception | None = None  # last write's outcome
        self._last_failure: Exception | None = None  # never cleared by wait()
        self._consecutive_failures = 0
        self.max_consecutive_failures = max_consecutive_failures
        self._wake = threading.Condition(self._lock)
        self._stop = False
        self.closed = False
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="ckpt-writer")
        self._thread.start()

    def _worker(self) -> None:
        while True:
            with self._wake:
                while self._pending is None and not self._stop:
                    self._wake.wait()
                if self._stop and self._pending is None:
                    return
                job = self._pending
                self._pending = None
                self._busy = True
            try:
                save_checkpoint(*job)
            except Exception as e:
                # Log NOW (the failure may otherwise go unnoticed for
                # hours of training); also kept for wait() to raise.
                logger.error("async checkpoint write for step=%d failed: %s",
                             job[2], e)
                with self._lock:
                    self._error = e
                    self._last_failure = e
                    self._consecutive_failures += 1
            else:
                with self._lock:
                    self._error = None  # a later success supersedes
                    self._consecutive_failures = 0
            finally:
                with self._wake:
                    self._busy = False
                    self._wake.notify_all()

    def _raise_pending_error(self) -> None:
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError("async checkpoint write failed") from err

    def save(self, train_dir: str | Path, state: Any, step: int,
             extra: dict | None = None, keep: int = 5) -> None:
        """Queue a write. A single failed write never raises here —
        that already went to the log and a later save may well succeed
        (transient disk pressure); ``wait`` raises if the LAST write
        failed, so a broken final checkpoint is never silent. A
        persistently broken disk does stop training: after
        ``max_consecutive_failures`` failed writes in a row, ``save``
        raises instead of letting checkpoints go silently stale."""
        with self._lock:
            if self._consecutive_failures >= self.max_consecutive_failures:
                raise RuntimeError(
                    f"{self._consecutive_failures} consecutive async "
                    "checkpoint writes failed; giving up"
                ) from self._last_failure
        host_state = jax.device_get(state)  # sync: buffers get donated next step
        with self._wake:
            if self.closed:
                raise RuntimeError("AsyncCheckpointer is closed")
            if self._pending is not None:
                logger.warning("checkpoint writer lagging; replacing queued "
                               "step=%d with step=%d", self._pending[2], step)
            self._pending = (train_dir, host_state, step, extra, keep)
            self._wake.notify_all()

    def wait(self) -> None:
        """Drain in-flight writes (call before exit / final save)."""
        with self._wake:
            while self._pending is not None or self._busy:
                self._wake.wait()
        self._raise_pending_error()

    def close(self) -> None:
        self.wait()
        with self._wake:
            self._stop = True
            self.closed = True
            self._wake.notify_all()
        self._thread.join(timeout=60)


def _garbage_collect(train_dir: Path, keep: int) -> None:
    if keep <= 0:
        return
    ckpts = sorted(train_dir.glob("ckpt-*.msgpack"))
    for old in ckpts[:-keep]:
        try:
            old.unlink()
        except OSError:
            pass


def latest_checkpoint_step(train_dir: str | Path) -> int | None:
    """Read the pointer (≙ tf.train.get_checkpoint_state,
    src/nn_eval.py:70); falls back to a directory scan if the pointer
    is missing/torn."""
    train_dir = Path(train_dir)
    ptr = train_dir / _POINTER
    if ptr.exists():
        try:
            d = json.loads(ptr.read_text())
            if (train_dir / d["latest_path"]).exists():
                return int(d["latest_step"])
        except (json.JSONDecodeError, KeyError, ValueError):
            pass
    ckpts = sorted(train_dir.glob("ckpt-*.msgpack"))
    if not ckpts:
        return None
    return int(ckpts[-1].stem.split("-")[1])


def read_checkpoint_extra(train_dir: str | Path,
                          step: int | None = None) -> tuple[dict, int] | None:
    """Read only the JSON ``extra`` payload (saved config, data-iter
    position) — needs NO state template, so the evaluator can bootstrap
    its config from a checkpoint of *any* model/optimizer shape before
    it knows what to build."""
    train_dir = Path(train_dir)
    if step is None:
        step = latest_checkpoint_step(train_dir)
        if step is None:
            return None
    payload = serialization.msgpack_restore(_ckpt_path(train_dir, step).read_bytes())
    extra = payload.get("extra", {})
    if isinstance(extra, (str, bytes)):
        extra = json.loads(extra)
    return extra, step


def restore_checkpoint(train_dir: str | Path, template_state: Any,
                       step: int | None = None) -> tuple[Any, dict, int] | None:
    """Restore (state, extra, step); None when nothing exists
    (≙ Supervisor's restore-if-present, src/distributed_train.py:262)."""
    train_dir = Path(train_dir)
    if step is None:
        step = latest_checkpoint_step(train_dir)
        if step is None:
            return None
    path = _ckpt_path(train_dir, step)
    payload = serialization.msgpack_restore(path.read_bytes())
    saved = payload["state"]
    # Migration: drop top-level fields the current TrainState no longer
    # has (e.g. pre-round-3 checkpoints carried a measured_ms scalar) —
    # from_state_dict hard-fails on unknown keys, which would make every
    # old checkpoint unresumable instead of forward-compatible.
    template_dict = serialization.to_state_dict(template_state)
    if isinstance(saved, dict) and isinstance(template_dict, dict):
        stale = set(saved) - set(template_dict)
        if stale:
            logger.warning("dropping stale checkpoint fields %s", sorted(stale))
            saved = {k: v for k, v in saved.items() if k not in stale}
    state = serialization.from_state_dict(template_state, saved)
    extra = payload.get("extra", {})
    if isinstance(extra, (str, bytes)):
        extra = json.loads(extra)
    return state, extra, step
