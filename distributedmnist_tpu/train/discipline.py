"""Online straggler-discipline controller — close the loop on the
source paper.

The paper studies sync-with-backup-workers, quorum, timeout and
interval aggregation as *static* configurations chosen a priori
(src/distributed_train.py:118-121, cfg/time_cdf_cfgs/*);
arXiv:1604.00981 shows the backup-worker tradeoff is empirical and
workload-dependent. We already collect the per-replica step-time CDF
at ~0 overhead (the ``[n]`` measured-timing vector + optional
ReplicaDeviceProbe skew; re-verified in PR 10, the ``cdf`` step lowers
byte-identical to ``sync``). This module feeds that instrumentation
back in at runtime: watch the rolling window CDF and adapt the
discipline parameters — quorum ``k`` and ``timeout_ms`` — on the fly.

Shape (deliberately the resource-broker controller shape,
launch/broker.py):

* :func:`decide` is PURE — no clock, no IO, no jax. Signal is the
  window tail ratio: p99 over the fastest replica's median (the
  cohort pace — robust to straggler fractions the pooled p50 is
  not); dead-band hysteresis between
  ``adaptive_tail_high`` (tighten) and ``adaptive_tail_low`` (relax),
  cooldown in steps from the last completed change. Property-tested
  directly.
* :class:`DisciplineController` executes decisions: journals the
  schema-declared ``event:"discipline"`` begin/complete pair
  (obsv/schema.py), swaps the traced [3] discipline vector
  (parallel/api.py make_discipline_vector — a device_put, never a
  recompile), and tracks the epoch trace.
* :func:`threshold_holds` is the SHARED predicate between the emitter
  and the replay invariant (obsv/invariants.py ``discipline``): the
  begin record's ``value op threshold`` claim is re-checked with the
  same function at replay, so emitter and checker cannot drift.

Determinism contract: params are bitwise within a discipline epoch and
causally journaled across them — every change licensed by a recorded
CDF-percentile crossing that held, with ``effective_step`` marking the
epoch boundary the invariant-3 digest comparison splices at.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Callable, Sequence

from ..core.config import SyncConfig
from ..core.log import get_logger

logger = get_logger("discipline")

# the only trigger v1 emits; the invariant rejects licenses naming
# anything else (the autoscale invariant's malformed-license posture)
TAIL_RATIO = "tail_ratio"


def threshold_holds(value: float, op: str, threshold: float) -> bool:
    """Does ``value op threshold`` hold? Shared between decide() and the
    replay invariant — same contract as launch/broker.py."""
    return value >= threshold if op == ">=" else value <= threshold


@dataclasses.dataclass(frozen=True)
class DisciplineParams:
    """The runtime aggregation-discipline parameters (one epoch)."""

    k: int                 # quorum size (quorum mode)
    timeout_ms: float      # deadline (timeout mode)
    interval_ms: float     # interval window (never adapted — wall-clock
    #                        pacing only; see SyncConfig.validate)
    num_replicas: int


@dataclasses.dataclass(frozen=True)
class WindowStats:
    """Rolling-window CDF summary of the per-replica step times (ms).

    ``fast_p50_ms`` is the fastest replica's window median — the cohort
    pace. The pooled p50 is contaminated once the straggling fraction
    approaches half the replicas (two 8x stragglers of four drag the
    pooled median to the midpoint and the ratio into the dead band,
    exactly when tightening matters most); the fastest median stays the
    healthy cohort's pace at any straggler fraction below n."""

    p50_ms: float
    p90_ms: float
    p99_ms: float
    n_samples: int
    fast_p50_ms: float = 0.0   # 0 = unknown: fall back to pooled p50

    @property
    def base_ms(self) -> float:
        """The tail ratio's denominator: the cohort pace."""
        return self.fast_p50_ms if self.fast_p50_ms > 0.0 else self.p50_ms

    @property
    def tail_ratio(self) -> float:
        """p99 over the cohort pace — the straggler signal. >= 1 by
        construction when the window is non-degenerate; 0 marks an
        unusable window."""
        if self.base_ms <= 0.0:
            return 0.0
        return self.p99_ms / self.base_ms


@dataclasses.dataclass(frozen=True)
class Decision:
    """One licensed discipline change — mirrors the ``begin`` record."""

    decision: str          # "tighten" | "relax"
    trigger: str           # TAIL_RATIO
    value: float           # observed signal (rounded)
    threshold: float       # the mark it crossed
    op: str                # ">=" (tighten) | "<=" (relax)
    old_k: int
    new_k: int
    old_timeout_ms: float
    new_timeout_ms: float


def static_params(cfg: SyncConfig, num_replicas: int) -> DisciplineParams:
    """The configured (pre-adaptation) discipline — also the ceiling
    relax steps back toward."""
    k = (num_replicas if cfg.num_replicas_to_aggregate == -1
         else cfg.num_replicas_to_aggregate)
    return DisciplineParams(k=k, timeout_ms=float(cfg.timeout_ms),
                            interval_ms=float(cfg.interval_ms),
                            num_replicas=num_replicas)


def quorum_floor(cfg: SyncConfig, num_replicas: int) -> int:
    """Lowest k the controller may tighten to: ceil(n · min_frac),
    never below 1 — arXiv:1604.00981's caution that too few
    contributors costs more in gradient quality than it buys in wait."""
    return max(1, math.ceil(num_replicas * cfg.adaptive_min_quorum_frac))


def decide(cfg: SyncConfig, window_stats: WindowStats | None,
           current: DisciplineParams, last_change_t: float | None,
           now: float) -> Decision | None:
    """The pure controller core (the broker decide() shape).

    ``last_change_t``/``now`` are STEP indices (the controller's clock
    is the step counter — wall time would make decisions depend on host
    speed and break the seeded-replay contract). Returns None inside
    the cooldown, inside the dead band, on a short/degenerate window,
    or when the indicated change is a no-op (already at a bound).
    """
    if not cfg.adaptive:
        return None
    if window_stats is None or window_stats.n_samples < cfg.adaptive_window_steps:
        return None
    if (last_change_t is not None
            and (now - last_change_t) < cfg.adaptive_cooldown_steps):
        return None
    ratio = window_stats.tail_ratio
    if ratio <= 0.0:  # degenerate window (p50 == 0)
        return None

    def _mk(decision: str, threshold: float, op: str, new_k: int,
            new_timeout: float) -> Decision | None:
        if new_k == current.k and round(new_timeout, 6) == round(
                current.timeout_ms, 6):
            return None  # at the bound already — not a change
        return Decision(
            decision=decision, trigger=TAIL_RATIO,
            value=round(ratio, 6), threshold=threshold, op=op,
            old_k=current.k, new_k=new_k,
            old_timeout_ms=round(current.timeout_ms, 6),
            new_timeout_ms=round(new_timeout, 6))

    static = static_params(cfg, current.num_replicas)
    if threshold_holds(ratio, ">=", cfg.adaptive_tail_high):
        # tail blown out past the high mark: TIGHTEN — stop waiting for
        # the stragglers the window just measured
        if cfg.mode == "quorum":
            new_k = max(quorum_floor(cfg, current.num_replicas),
                        current.k - 1)
            return _mk("tighten", cfg.adaptive_tail_high, ">=", new_k,
                       current.timeout_ms)
        # timeout mode: pull the deadline to a multiple of the cohort
        # pace — drops exactly the tail that blew the ratio
        target = max(cfg.adaptive_timeout_floor_ms,
                     window_stats.base_ms * cfg.adaptive_timeout_factor)
        target = min(target, static.timeout_ms)
        if current.timeout_ms > 0 and abs(
                target - current.timeout_ms) / current.timeout_ms < 0.01:
            return None  # sub-percent retarget: dead band, not a change
        return _mk("tighten", cfg.adaptive_tail_high, ">=", current.k,
                   target)
    if threshold_holds(ratio, "<=", cfg.adaptive_tail_low):
        # tail back under the low mark: RELAX one notch toward the
        # configured static discipline (never past it)
        if cfg.mode == "quorum":
            new_k = min(static.k, current.k + 1)
            return _mk("relax", cfg.adaptive_tail_low, "<=", new_k,
                       current.timeout_ms)
        if current.timeout_ms >= static.timeout_ms:
            return None
        return _mk("relax", cfg.adaptive_tail_low, "<=", current.k,
                   static.timeout_ms)
    return None  # dead band between the marks


class DisciplineController:
    """Executes :func:`decide` against the live run.

    The trainer calls :meth:`maybe_adapt` at flush cadence with the
    rolling window stats; on a decision the controller journals the
    ``begin`` record, stages the new traced discipline vector via
    ``make_vector`` (parallel/api.py make_discipline_vector — the whole
    point: a 12-byte buffer swap, zero recompiles), then journals
    ``complete`` with the staging reaction time and the first step the
    new epoch governs.

    ``emit`` is the trainer's journal writer (train_log.jsonl) — the
    begin/complete pair lands in the SAME log as the step records the
    replay invariant matches them against.
    """

    def __init__(self, cfg: SyncConfig, num_replicas: int,
                 emit: Callable[[dict], None],
                 make_vector: Callable[[float, float, float], Any],
                 clock: Callable[[], float] = time.time) -> None:
        cfg.validate(num_replicas=num_replicas)
        if not cfg.adaptive:
            raise ValueError("DisciplineController requires "
                             "sync.adaptive=true")
        self.cfg = cfg
        self.num_replicas = num_replicas
        self.current = static_params(cfg, num_replicas)
        self._emit = emit
        self._make_vector = make_vector
        self._clock = clock
        self.vector = make_vector(self.current.k, self.current.timeout_ms,
                                  self.current.interval_ms)
        self.last_change_step: float | None = None
        self.changes = 0
        # epoch trace: (effective_step, k, timeout_ms) per change — the
        # per-window discipline trace benches/summaries report
        self.trace: list[tuple[int, int, float]] = []

    def params_list(self) -> list[float]:
        """The [k, timeout_ms] pair step records observe."""
        return [float(self.current.k), round(self.current.timeout_ms, 6)]

    def maybe_adapt(self, step: int,
                    window_stats: WindowStats | None) -> Decision | None:
        """Evaluate the pure core at ``step``; execute + journal any
        decision. Returns the decision (None = no change)."""
        d = decide(self.cfg, window_stats, self.current,
                   self.last_change_step, float(step))
        if d is None:
            return None
        now = self._clock()
        self._emit({
            "event": "discipline", "action": "begin", "time": now,
            "decision": d.decision, "trigger": d.trigger,
            "value": d.value, "threshold": d.threshold, "op": d.op,
            "old_k": d.old_k, "new_k": d.new_k,
            "old_timeout_ms": d.old_timeout_ms,
            "new_timeout_ms": d.new_timeout_ms, "at_step": int(step),
            "window_steps": self.cfg.adaptive_window_steps,
            "cooldown_steps": self.cfg.adaptive_cooldown_steps,
            "p50_ms": round(window_stats.p50_ms, 6),
            "p99_ms": round(window_stats.p99_ms, 6),
            "num_replicas": self.num_replicas,
        })
        self.current = dataclasses.replace(
            self.current, k=d.new_k, timeout_ms=d.new_timeout_ms)
        # the swap itself: stage a fresh [3] vector — the next step_fn
        # call feeds it to the SAME compiled executable
        self.vector = self._make_vector(
            self.current.k, self.current.timeout_ms,
            self.current.interval_ms)
        effective = int(step) + 1  # first step the new epoch governs
        self._emit({
            "event": "discipline", "action": "complete",
            "time": self._clock(), "decision": d.decision,
            "trigger": d.trigger,
            "reaction_s": round(self._clock() - now, 6),
            "k": d.new_k, "timeout_ms": d.new_timeout_ms,
            "effective_step": effective,
        })
        self.last_change_step = float(step)
        self.changes += 1
        self.trace.append((effective, d.new_k,
                           round(d.new_timeout_ms, 6)))
        logger.info(
            "discipline %s @ step %d: %s=%s %s %s -> k=%d timeout=%.1fms",
            d.decision, step, d.trigger, d.value, d.op, d.threshold,
            d.new_k, d.new_timeout_ms)
        return d

    def summary(self) -> dict:
        """Roll-up for run summaries / chaos outcomes."""
        return {
            "changes": self.changes,
            "current_k": self.current.k,
            "current_timeout_ms": round(self.current.timeout_ms, 6),
            "trace": [list(t) for t in self.trace],
        }


def discipline_trace(records: Sequence[dict]) -> list[tuple[int, float, float]]:
    """The epoch trace a journal records: (effective_step, k,
    timeout_ms) per completed change, in order. Shared by the replay
    invariant's epoch-splice comparison and summaries — both sides read
    the SAME projection of the log."""
    out: list[tuple[int, float, float]] = []
    for rec in records:
        if (rec.get("event") == "discipline"
                and rec.get("action") == "complete"):
            try:
                out.append((int(rec["effective_step"]),
                            float(rec["k"]), float(rec["timeout_ms"])))
            except (KeyError, TypeError, ValueError):
                continue  # malformed completes are the invariant's job
    return out
