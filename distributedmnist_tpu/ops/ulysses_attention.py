"""Ulysses-style sequence parallelism: all-to-all head scatter.

The second sequence-parallel strategy next to ring attention
(ops/ring_attention.py). Where the ring rotates K/V blocks around
``ppermute`` neighbor links, Ulysses re-shards: an ``all_to_all``
trades the sequence sharding for a *head* sharding, every device runs
exact attention over the FULL sequence for its head subset — the
perfect shape for the fused pallas kernel (ops/pallas_attention.py) —
and a second ``all_to_all`` restores the sequence sharding.

Trade-offs (why both exist): Ulysses moves 2× the activations through
all-to-all but runs attention unblocked and needs ``heads %
n_devices == 0``; the ring streams K/V with O(1) extra memory and
works for any head count, but serializes into n ppermute steps. Cf.
DeepSpeed-Ulysses (arXiv:2309.14509) vs Ring Attention
(arXiv:2310.01889). The reference has no sequence dimension at all
(SURVEY §5.7) — this is framework capability beyond parity.
"""

from __future__ import annotations

from typing import Callable

import jax
from jax import lax

from .ring_attention import local_self_attention


def ulysses_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           axis_name: str, *, causal: bool = True,
                           scale: float | None = None,
                           attention_fn: Callable | None = None) -> jax.Array:
    """Exact attention over a sequence sharded on ``axis_name``.

    Args (local blocks inside shard_map):
      q, k, v: [batch, heads, seq_local, head_dim]; ``heads`` must be
        divisible by the axis size.
      attention_fn: full-sequence attention applied per head subset —
        defaults to the dense oracle; pass
        ``pallas_attention.flash_attention`` for the fused kernel.

    Returns [batch, heads, seq_local, head_dim] for this device's block.
    """
    n = lax.axis_size(axis_name)
    h = q.shape[1]
    if h % n != 0:
        raise ValueError(f"heads={h} not divisible by axis {axis_name!r} "
                         f"size {n} (use ring attention instead)")
    inner = attention_fn or local_self_attention

    def gather_seq(x):  # [b, h, s/n, d] → [b, h/n, s, d]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    o = inner(gather_seq(q), gather_seq(k), gather_seq(v), causal=causal,
              scale=scale)
    # [b, h/n, s, d] → [b, h, s/n, d]
    return lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)
