"""Drop-connect gradient masking.

Capability parity with the reference's ``--drop_connect`` path: each
gradient element is multiplied by an independent Bernoulli(p=0.9)
sample before the update (src/distributed_train.py:60,98-99,194-196,
202-203,414-416). As in the reference, there is NO 1/p rescaling —
the expected gradient is deliberately attenuated.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def drop_connect_grads(grads: Any, key: jax.Array, keep_prob: float) -> Any:
    """Apply an elementwise Bernoulli(keep_prob) mask to every gradient
    leaf. Each leaf gets an independent fold of ``key``."""
    leaves, treedef = jax.tree.flatten(grads)
    keys = jax.random.split(key, len(leaves))
    masked = [
        g * jax.random.bernoulli(k, keep_prob, g.shape).astype(g.dtype)
        for g, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, masked)
