"""GPipe-style pipeline parallelism over a mesh ``stage`` axis.

The fourth parallelism family (after data/tensor/sequence — all absent
from the reference, SURVEY §2.2): layers are sharded across stages,
activations flow stage→stage over ``lax.ppermute`` (neighbor ICI
links), and the batch is split into microbatches so stages overlap
work on different microbatches instead of idling.

SPMD formulation: every device runs the same scanned program for
``M + S - 1`` ticks. Each tick, a stage applies ITS layer slice to the
activation in its buffer, the last stage banks finished microbatches,
and a ppermute shifts activations one stage forward while stage 0
injects the next microbatch. Warm-up/drain bubbles process zeros whose
results are never banked (the later, valid write of each slot lands
after any bubble write). Expressed with ``lax.scan`` end to end, so the
whole pipeline — including the bubbles — is reverse-mode
differentiable; the ppermute transposes to the reverse rotation in the
backward pass, giving the classic backward pipeline for free.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable[[jax.Array], jax.Array],
                   microbatches: jax.Array, axis_name: str) -> jax.Array:
    """Run sharded-by-layer ``stage_fn`` as a microbatch pipeline.

    Args:
      stage_fn: applies THIS device's layer slice:
        activations [mb, ...] → activations [mb, ...] (same shape).
      microbatches: [M, mb, ...] — the embedded inputs; only stage 0's
        values are consumed (other stages may hold the same array).
      axis_name: the mesh stage axis (inside shard_map).

    Returns [M, mb, ...] final-stage outputs, REPLICATED over the stage
    axis (a masked psum broadcasts them), so downstream loss/head code
    runs identically on every stage.
    """
    s = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    perm = [(i, (i + 1) % s) for i in range(s)]

    def vary_like(x, ref):
        want = getattr(jax.typeof(ref), "vma", frozenset()) or frozenset()
        have = getattr(jax.typeof(x), "vma", frozenset()) or frozenset()
        missing = tuple(want - have)
        return lax.pcast(x, missing, to="varying") if missing else x

    buf0 = jnp.where(me == 0, microbatches[0], jnp.zeros_like(microbatches[0]))
    outs0 = jnp.zeros_like(microbatches)
    # probe one stage application so carries match the scan body's vma
    ref = stage_fn(buf0)
    buf0 = vary_like(buf0, ref)
    outs0 = vary_like(outs0, ref)

    def tick(carry, t):
        buf, outs = carry
        y = stage_fn(buf)
        # last stage banks microbatch (t - (s-1)) once it's really done;
        # bubble writes clobber slot 0 early but the valid write lands later
        idx = jnp.clip(t - (s - 1), 0, m - 1)
        banked = lax.dynamic_update_index_in_dim(outs, y, idx, 0)
        outs = jnp.where(me == s - 1, banked, outs)
        # shift forward; stage 0 injects the next microbatch
        shifted = lax.ppermute(y, axis_name, perm)
        nxt = jnp.clip(t + 1, 0, m - 1)
        buf = jnp.where(me == 0, microbatches[nxt], shifted)
        return (buf, outs), None

    (_, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(m + s - 1))
    # broadcast the last stage's banked outputs to every stage
    mask = (me == s - 1).astype(outs.dtype)
    return lax.psum(outs * mask, axis_name)
