"""GPipe-style pipeline parallelism over a mesh ``stage`` axis.

The fourth parallelism family (after data/tensor/sequence — all absent
from the reference, SURVEY §2.2): layers are sharded across stages,
activations flow stage→stage over ``lax.ppermute`` (neighbor ICI
links), and the batch is split into microbatches so stages overlap
work on different microbatches instead of idling.

SPMD formulation: every device runs the same scanned program for
``M + S - 1`` ticks. Each tick, a stage applies ITS layer slice to the
activation in its buffer, the last stage banks finished microbatches,
and a ppermute shifts activations one stage forward while stage 0
injects the next microbatch. Warm-up/drain bubbles process zeros whose
results are never banked (the later, valid write of each slot lands
after any bubble write). Expressed with ``lax.scan`` end to end, so the
whole pipeline — including the bubbles — is reverse-mode
differentiable; the ppermute transposes to the reverse rotation in the
backward pass, giving the classic backward pipeline for free.
"""

from __future__ import annotations

import functools
from typing import Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _vma_of(x) -> frozenset:
    return frozenset(getattr(jax.typeof(x), "vma", frozenset())
                     or frozenset())


def _vary_to(full_vma: frozenset) -> Callable:
    """pcast-to-varying normalizer: every value this returns covers
    exactly ``full_vma`` — the single point of truth for keeping scan
    carries / cond branches on one consistent vma type."""
    def vary(x):
        missing = tuple(full_vma - _vma_of(x))
        return lax.pcast(x, missing, to="varying") if missing else x
    return vary


def pipeline_apply(stage_fn: Callable, microbatches: jax.Array,
                   axis_name: str, with_stats: bool = False):
    """Run sharded-by-layer ``stage_fn`` as a microbatch pipeline.

    Args:
      stage_fn: applies THIS device's layer slice:
        activations [mb, ...] → activations [mb, ...] (same shape).
        With ``with_stats``, returns (activations, stats_pytree) — the
        stats (e.g. MoE routing statistics) are accumulated over the
        REAL microbatch ticks only (bubble ticks chew zeros whose
        routing stats are garbage) and returned averaged over the M
        microbatches; with equal-size microbatches that mean equals
        the full-batch statistics exactly.
      microbatches: [M, mb, ...] — the embedded inputs; only stage 0's
        values are consumed (other stages may hold the same array).
      axis_name: the mesh stage axis (inside shard_map).

    Returns [M, mb, ...] final-stage outputs, REPLICATED over the stage
    axis (a masked psum broadcasts them), so downstream loss/head code
    runs identically on every stage; with ``with_stats``, a tuple
    (outputs, mean_stats) where mean_stats stays PER-STAGE (each
    stage's own layers' statistics — the caller reduces across stages).
    """
    s = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    perm = [(i, (i + 1) % s) for i in range(s)]

    buf0 = jnp.where(me == 0, microbatches[0], jnp.zeros_like(microbatches[0]))
    outs0 = jnp.zeros_like(microbatches)
    # probe one stage application so carries match the scan body's vma
    ref = stage_fn(buf0)
    ref_act, ref_stats = ref if with_stats else (ref, None)
    vary = _vary_to(_vma_of(ref_act))
    buf0 = vary(buf0)
    outs0 = vary(outs0)
    stats0 = (jax.tree.map(lambda r: vary(jnp.zeros_like(r)), ref_stats)
              if with_stats else None)

    def tick(carry, t):
        buf, outs, acc = carry
        if with_stats:
            y, stats = stage_fn(buf)
            # device ``me`` chews a REAL microbatch at ticks
            # me <= t < me + m; bubble ticks must not pollute the stats
            valid = ((t >= me) & (t < me + m))
            acc = jax.tree.map(
                lambda a, st: a + jnp.where(valid, st, 0.0).astype(a.dtype),
                acc, stats)
        else:
            y = stage_fn(buf)
        # last stage banks microbatch (t - (s-1)) once it's really done;
        # bubble writes clobber slot 0 early but the valid write lands later
        idx = jnp.clip(t - (s - 1), 0, m - 1)
        banked = lax.dynamic_update_index_in_dim(outs, y, idx, 0)
        outs = jnp.where(me == s - 1, banked, outs)
        # shift forward; stage 0 injects the next microbatch
        shifted = lax.ppermute(y, axis_name, perm)
        nxt = jnp.clip(t + 1, 0, m - 1)
        buf = jnp.where(me == 0, microbatches[nxt], shifted)
        return (buf, outs, acc), None

    (_, outs, acc), _ = lax.scan(tick, (buf0, outs0, stats0),
                                 jnp.arange(m + s - 1))
    # broadcast the last stage's banked outputs to every stage
    mask = (me == s - 1).astype(outs.dtype)
    outs = lax.psum(outs * mask, axis_name)
    if with_stats:
        return outs, jax.tree.map(lambda a: a / m, acc)
    return outs


# ---------------------------------------------------------------------------
# Interleaved 1F1B: static schedule + fused forward/backward engine
# ---------------------------------------------------------------------------
#
# GPipe above runs all forwards, then (via the AD transpose of its
# scan) all backwards: per device the bubble is 2(S-1) STAGE-works —
# 2(S-1)·v chunk-works once a stage is split into v virtual chunks.
# The fused 1F1B engine below schedules one chunk-work per device per
# tick (forward OR backward, chosen by a static per-tick table), so
# backwards start as soon as a microbatch clears the last chunk and
# the bubble shrinks to ~2(S-1) chunk-works — the Megatron-LM
# interleaved-schedule result (arXiv:2104.04473), re-expressed as one
# SPMD scan: every tick runs one lax.switch (device-varying branch:
# idle / forward / forward+loss-seed / backward-via-recompute-vjp) and
# two unconditional neighbor ppermutes, so collectives stay lockstep
# while compute follows each device's own schedule row.
#
# Chunk placement: global chunk c ∈ [0, S·v) lives on device c % S,
# local slot c // S — microbatches travel the ring v times. Backward
# recomputes the chunk forward from the saved chunk INPUT (jax.vjp at
# tick time), i.e. rematerialization is built in; only chunk-boundary
# activations are buffered.


@functools.lru_cache(maxsize=None)
def make_1f1b_schedule(num_stages: int, num_chunks: int,
                       num_microbatches: int,
                       forward_only: bool = False) -> "Mapping":
    """Build the static interleaved-1F1B tables (greedy list scheduler,
    backward-priority — the 1F1B rule — with forwards preferring the
    deepest ready chunk to keep chains moving).

    Single-slot model: per tick a device does ONE chunk-work. A chunk's
    output transfers to the next device on the tick it is produced and
    is usable from the next tick (the engine's end-of-tick ppermute);
    per-(slot, microbatch) buffers mean arrivals never clobber.

    Returns numpy int32 tables, each [T, S] (indexed [tick, device]):
      kind        0 idle · 1 forward · 2 forward of the LAST global
                  chunk (seeds the loss cotangent) · 3 backward
      slot, mb    the local chunk slot / microbatch of this tick's work
      bank        1 when this tick's backward is global chunk 0 on
                  device 0: its input-cotangent is banked, not sent
      frecv_slot, frecv_mb   where the activation arriving THIS tick
                  (sent by device d-1 this tick, readable next tick)
                  lands in the X buffer; -1 = nothing arrives
      brecv_slot, brecv_mb   same for cotangents from device d+1
    plus "ticks" (T) and "idle_slots" (S·T − 2·M·S·v, the measured
    bubble tests compare against GPipe's 2·S·(S−1)·v).

    ``forward_only=True`` builds the inference/eval schedule for the
    same chunk placement: no backward works, kind 2 marks the LAST
    global chunk (its output is banked), idle_slots counts S·T − M·S·v.
    """
    S, v, M = num_stages, num_chunks, num_microbatches
    C = S * v
    f_done: dict = {}
    b_done: dict = {}
    f_arr = {(m, 0): 0 for m in range(M)}
    b_arr: dict = {}
    rows = []
    t = 0
    while (len(f_done) < M * C if forward_only else len(b_done) < M * C):
        if t > 8 * (M * C + S):
            raise RuntimeError("1f1b scheduler stalled (bug)")
        act = {}
        for d in range(S):
            bready = []
            fready = []
            for m in range(M):
                for j in range(v):
                    c = j * S + d
                    if (m, c) not in f_done:
                        if f_arr.get((m, c), 10**9) <= t:
                            fready.append((-c, m))
                        continue
                    if forward_only:
                        continue
                    if (m, c) in b_done or f_done[(m, c)] > t - 1:
                        continue
                    if c == C - 1 or b_arr.get((m, c), 10**9) <= t:
                        bready.append((m, -c))
            if bready:  # backward first — the 1F1B rule
                m, negc = min(bready)
                act[d] = (3, m, -negc)
            elif fready:  # deepest ready chunk first, then earliest mb
                negc, m = min(fready)
                act[d] = (1, m, -negc)
        for d, (kind, m, c) in act.items():
            if kind == 1:
                f_done[(m, c)] = t
                if c < C - 1:
                    f_arr[(m, c + 1)] = t + 1
                else:
                    act[d] = (2, m, c)  # last chunk: seed, nothing sent
            else:
                b_done[(m, c)] = t
                if c > 0:
                    b_arr[(m, c - 1)] = t + 1
        rows.append(act)
        t += 1

    T = len(rows)
    tables = {k: np.zeros((T, S), np.int32)
              for k in ("kind", "slot", "mb", "bank")}
    for k in ("frecv_slot", "frecv_mb", "brecv_slot", "brecv_mb"):
        tables[k] = np.full((T, S), -1, np.int32)
    for t, act in enumerate(rows):
        for d, (kind, m, c) in act.items():
            tables["kind"][t, d] = kind
            tables["slot"][t, d] = c // S
            tables["mb"][t, d] = m
            if kind == 3 and c == 0:
                tables["bank"][t, d] = 1
            if kind == 1:  # c < C-1 by construction: receiver gets it
                rd = (d + 1) % S
                tables["frecv_slot"][t, rd] = (c + 1) // S
                tables["frecv_mb"][t, rd] = m
            if kind == 3 and c > 0:
                rd = (d - 1) % S
                tables["brecv_slot"][t, rd] = (c - 1) // S
                tables["brecv_mb"][t, rd] = m

    # validity: every chunk forwarded (and backwarded) exactly once,
    # deps by construction; belt-and-braces recount
    assert len(f_done) == M * C
    assert forward_only or len(b_done) == M * C
    tables["ticks"] = T
    tables["idle_slots"] = S * T - (1 if forward_only else 2) * M * C
    # the lru_cache hands the SAME object to every caller: freeze it so
    # a mutating caller cannot silently poison later schedule lookups
    import types
    for a in tables.values():
        if isinstance(a, np.ndarray):
            a.flags.writeable = False
    return types.MappingProxyType(tables)


def _index_pytree(tree, idx):
    """tree of [v, ...] leaves → the slot-``idx`` subtree (traced idx)."""
    return jax.tree.map(
        lambda p: lax.dynamic_index_in_dim(p, idx, 0, keepdims=False), tree)


def pipeline_1f1b_grads(chunk_fn: Callable, head_fn: Callable,
                        chunk_params, head_params,
                        microbatches: jax.Array, axis_name: str,
                        num_chunks: int, with_aux: bool = False,
                        aux_cotangent: float = 0.0):
    """Fused interleaved-1F1B training pipeline (inside shard_map).

    Args:
      chunk_fn: (slot_params, x) -> y, one virtual chunk of THIS device
        (shape-preserving). Backward recomputes it via jax.vjp. With
        ``with_aux``, returns (y, aux) where ``aux`` is a scalar
        auxiliary-loss contribution (e.g. the summed per-group MoE
        load-balance aux of the chunk's layers) that enters the total
        loss LINEARLY with weight ``aux_cotangent`` — linearity is what
        lets the engine seed each backward chunk's aux output with the
        constant cotangent instead of a value that depends on other
        chunks.
      head_fn: (head_params, y, mb_index) -> (loss, metric) — the loss
        head applied to a LAST-chunk output microbatch (closes over
        labels; mb_index is a traced scalar). Differentiated w.r.t.
        both arguments at the seed tick.
      chunk_params: pytree with leading dim [num_chunks] — this
        device's chunk slots (slot j = global chunk j·S + d).
      head_params: replicated loss-head params.
      microbatches: [M, mb, ...] pipeline inputs (already embedded).
      axis_name: the mesh stage axis.
      aux_cotangent: the (already axis-normalized) weight the caller
        gives each chunk-aux in the total loss; the backward seeds
        every chunk's aux output with exactly this constant.

    Returns (losses [M], metrics [M], dinputs [M, mb, ...],
    dchunk_params (same layout as chunk_params, THIS device's grads),
    dhead_params (replicated — psum'd over the axis)); losses/metrics/
    dinputs come out replicated over the axis. With ``with_aux``, a
    sixth element: the SUM over all (chunk, microbatch) forward works
    of the chunk aux (psum'd over the axis — stages hold disjoint
    chunks), i.e. Σ_layers aux summed over microbatches; the caller
    scales by aux_cotangent/M for the loss value.
    """
    S = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    v = num_chunks
    M = microbatches.shape[0]
    tbl = make_1f1b_schedule(S, v, M)
    T = tbl["ticks"]
    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype

    # every buffer / branch output is cast varying over the stage axis
    # AND whatever axes the data already varies over (e.g. the replica
    # axis inside the full train step) so the switch branches and scan
    # carries have one consistent vma type
    vary = _vary_to(_vma_of(microbatches) | {axis_name})

    # per-(slot, mb) buffers: chunk inputs (kept for the recompute
    # backward) and arriving cotangents. Device 0's slot 0 holds the
    # pipeline inputs from the start.
    X0 = jnp.zeros((v, M) + mb_shape, dtype)
    X0 = jnp.where(me == 0, X0.at[0].set(microbatches), X0)
    Gin0 = vary(jnp.zeros((v, M) + mb_shape, dtype))
    X0 = vary(X0)
    dparams0 = jax.tree.map(lambda p: vary(jnp.zeros_like(p)), chunk_params)
    dhead0 = jax.tree.map(lambda p: vary(jnp.zeros_like(p)), head_params)
    losses0 = vary(jnp.zeros((M,), jnp.float32))
    metrics0 = vary(jnp.zeros((M,), jnp.float32))
    dinputs0 = vary(jnp.zeros((M,) + mb_shape, dtype))

    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [((i + 1) % S, i) for i in range(S)]

    # zero fillers for non-matching switch branches — pcast varying so
    # every branch returns identical vma types
    zeros_dp = jax.tree.map(lambda p: vary(jnp.zeros_like(p[0])),
                            chunk_params)
    zeros_dh = jax.tree.map(lambda p: vary(jnp.zeros_like(p)), head_params)

    def run_chunk(slot_params, x):
        """chunk_fn normalized to (y, aux): aux 0.0 when not with_aux,
        so the branch structure is identical either way."""
        if with_aux:
            return chunk_fn(slot_params, x)
        return chunk_fn(slot_params, x), jnp.zeros((), jnp.float32)

    def tick(carry, row):
        X, Gin, dparams, dhead, losses, metrics, dinputs, aux_acc = carry
        kind = row["kind"][me]
        j = row["slot"][me]
        m = row["mb"][me]
        bank = row["bank"][me]
        x = X[j, m]
        g = Gin[j, m]
        slot_params = _index_pytree(chunk_params, j)

        # Each branch returns (out_act, dy_seed, dslot_params,
        # dhead_params, loss, metric, aux): out_act is the forward
        # output (F), the input-cotangent (B), or zeros (idle/seed
        # handles dy separately so the seed's forward output never
        # ships); aux is the chunk's auxiliary-loss value on forward
        # works (zero elsewhere — the backward recompute would double-
        # count it).
        zero_act = vary(jnp.zeros(mb_shape, dtype))
        zero_s = vary(jnp.zeros((), jnp.float32))

        def br_idle(_):
            return (zero_act, zero_act, zeros_dp, zeros_dh,
                    zero_s, zero_s, zero_s)

        def br_fwd(_):
            y, aux = run_chunk(slot_params, x)
            return (vary(y.astype(dtype)), zero_act, zeros_dp, zeros_dh,
                    zero_s, zero_s, vary(aux.astype(jnp.float32)))

        def br_seed(_):
            y, aux = run_chunk(slot_params, x)
            # differentiate w.r.t. a VARYING copy of the head params:
            # the transpose of invariant→varying would be a psum over
            # the axis — a collective inside one device's branch, which
            # would deadlock the lockstep siblings. The final masked
            # psum of dhead (below the scan) does that reduction for
            # every device at once instead.
            hp_var = jax.tree.map(vary, head_params)
            loss, vjp, metric = jax.vjp(
                lambda hp, yy: head_fn(hp, yy, m), hp_var, y,
                has_aux=True)
            dhp, dy = vjp(vary(jnp.ones((), jnp.float32)))
            dhp = jax.tree.map(vary, dhp)
            return (zero_act, dy.astype(dtype), zeros_dp, dhp,
                    vary(loss), vary(metric),
                    vary(aux.astype(jnp.float32)))

        def br_bwd(_):
            (y_p, aux_p), vjp = jax.vjp(
                lambda sp, xx: run_chunk(sp, xx), slot_params, x)
            # the aux enters the total loss linearly with weight
            # aux_cotangent, so its cotangent is that CONSTANT — no
            # cross-chunk value needed (arithmetic on aux_p keeps its
            # varying-axes type)
            dsp, dx = vjp((g, aux_p * 0.0 + aux_cotangent))
            dsp = jax.tree.map(vary, dsp)
            return (dx.astype(dtype), zero_act, dsp, zeros_dh,
                    zero_s, zero_s, zero_s)

        out_act, dy_seed, dsp, dhp, loss, metric, aux = lax.switch(
            jnp.clip(kind, 0, 3), (br_idle, br_fwd, br_seed, br_bwd), None)

        is_f = kind == 1
        is_seed = kind == 2
        is_b = kind == 3

        # bookkeeping (zeros from non-matching branches make the adds
        # no-ops; masked writes keep the untouched entries)
        dparams = jax.tree.map(lambda acc, d: acc.at[j].add(d), dparams, dsp)
        dhead = jax.tree.map(lambda acc, d: acc + d, dhead, dhp)
        losses = losses.at[m].add(jnp.where(is_seed, loss, 0.0))
        metrics = metrics.at[m].add(jnp.where(is_seed, metric, 0.0))
        aux_acc = aux_acc + jnp.where(is_f | is_seed, aux, 0.0)
        Gin = Gin.at[j, m].set(jnp.where(is_seed, dy_seed, Gin[j, m]))
        dinputs = dinputs.at[m].set(
            jnp.where(is_b & (bank == 1), out_act, dinputs[m]))

        # unconditional lockstep transfers; payload masked by action
        f_payload = jnp.where(is_f, out_act, zero_act)
        b_payload = jnp.where(is_b & (bank == 0), out_act, zero_act)
        f_in = lax.ppermute(f_payload, axis_name, fwd_perm)
        b_in = lax.ppermute(b_payload, axis_name, bwd_perm)
        frs, frm = row["frecv_slot"][me], row["frecv_mb"][me]
        brs, brm = row["brecv_slot"][me], row["brecv_mb"][me]
        fi, fm = jnp.maximum(frs, 0), jnp.maximum(frm, 0)
        bi, bm = jnp.maximum(brs, 0), jnp.maximum(brm, 0)
        X = X.at[fi, fm].set(jnp.where(frs >= 0, f_in, X[fi, fm]))
        Gin = Gin.at[bi, bm].set(jnp.where(brs >= 0, b_in, Gin[bi, bm]))
        return (X, Gin, dparams, dhead, losses, metrics, dinputs,
                aux_acc), None

    rows = {k: jnp.asarray(tbl[k]) for k in
            ("kind", "slot", "mb", "bank", "frecv_slot", "frecv_mb",
             "brecv_slot", "brecv_mb")}
    aux0 = vary(jnp.zeros((), jnp.float32))
    carry = (X0, Gin0, dparams0, dhead0, losses0, metrics0, dinputs0, aux0)
    (X, Gin, dparams, dhead, losses, metrics, dinputs, aux_acc), _ = lax.scan(
        tick, carry, rows, length=T)

    # losses/metrics live on the last stage, dinputs on stage 0, dhead
    # on the last stage — psum broadcasts each (zeros elsewhere); the
    # aux accumulators cover each stage's own chunks (disjoint), so a
    # plain psum totals the model
    last = (me == S - 1).astype(jnp.float32)
    first = (me == 0).astype(dtype)
    losses = lax.psum(losses * last, axis_name)
    metrics = lax.psum(metrics * last, axis_name)
    dinputs = lax.psum(dinputs * first, axis_name)
    dhead = jax.tree.map(
        lambda ddd: lax.psum(ddd * last.astype(ddd.dtype), axis_name), dhead)
    if with_aux:
        return (losses, metrics, dinputs, dparams, dhead,
                lax.psum(aux_acc, axis_name))
    return losses, metrics, dinputs, dparams, dhead


def pipeline_chunked_forward(chunk_fn: Callable, microbatches: jax.Array,
                             axis_name: str, num_chunks: int) -> jax.Array:
    """Forward-only companion of the 1F1B engine for the chunked param
    layout (device d holds global chunks {d, S+d, …}): microbatches
    ride the ring v times, outputs of the last chunk bank on the last
    device and psum-broadcast — same contract as :func:`pipeline_apply`
    but for chunk-stacked params (eval under schedule="1f1b")."""
    S = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    v = num_chunks
    M = microbatches.shape[0]

    # the SAME scheduler as training, backward works disabled — train
    # and eval cannot drift apart on transfer/readiness rules
    tbl = make_1f1b_schedule(S, v, M, forward_only=True)
    T = tbl["ticks"]
    kind, slot, mbi = tbl["kind"], tbl["slot"], tbl["mb"]
    frs_t, frm_t = tbl["frecv_slot"], tbl["frecv_mb"]

    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype
    vary = _vary_to(_vma_of(microbatches) | {axis_name})

    X0 = jnp.zeros((v, M) + mb_shape, dtype)
    X0 = vary(jnp.where(me == 0, X0.at[0].set(microbatches), X0))
    outs0 = vary(jnp.zeros((M,) + mb_shape, dtype))
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, row):
        X, outs = carry
        k, j, m = row[0][me], row[1][me], row[2][me]
        frs, frm = row[3][me], row[4][me]
        x = X[j, m]
        y = jnp.where(k > 0, chunk_fn(x, j), x).astype(dtype)
        outs = outs.at[m].set(jnp.where(k == 2, y, outs[m]))
        f_in = lax.ppermute(jnp.where(k == 1, y, jnp.zeros(mb_shape, dtype)),
                            axis_name, fwd_perm)
        fi, fm = jnp.maximum(frs, 0), jnp.maximum(frm, 0)
        X = X.at[fi, fm].set(jnp.where(frs >= 0, f_in, X[fi, fm]))
        return (X, outs), None

    rows = tuple(jnp.asarray(a) for a in (kind, slot, mbi, frs_t, frm_t))
    (_, outs), _ = lax.scan(tick, (X0, outs0), rows, length=T)
    mask = (me == S - 1).astype(outs.dtype)
    return lax.psum(outs * mask, axis_name)
