"""Mixture-of-experts FFN with expert parallelism.

The fifth parallelism family (data/tensor/sequence/pipeline/expert —
all absent from the reference, SURVEY §2.2). Switch-Transformer top-1
and GShard top-k routing (cf. arXiv:2101.03961, arXiv:2006.16668) in
the dispatch/combine einsum formulation — static shapes throughout, so
XLA sees dense batched matmuls per expert shard and the MXU stays busy
regardless of routing.

Token groups (the GShard "group" dimension): every sequence row splits
into a fixed number of contiguous chunks, and routing capacity plus the
load-balance auxiliary loss are computed PER CHUNK. Because groups nest
inside rows, the routing math depends only on (config, row contents) —
never on how a batch is split into pipeline microbatches, how many
expert ranks exist, or how the sequence is sharded (given an explicit
``num_groups``). Consequences the tests pin down:

* a pipelined (PP) MoE evaluates/trains identically at ANY microbatch
  count — groups never straddle a microbatch boundary;
* an expert-parallel run equals the dense oracle EXACTLY, including
  with binding capacity (same groups → same drops);
* the aux loss is the MEAN over groups of the per-group Switch loss
  E·Σ_e frac_e·mprob_e — linear in per-group contributions, so
  pipeline ticks / seq shards / expert ranks can average it without
  the round-4 raw-statistics accumulation machinery.

Expert-parallel layout (GShard all-to-all dispatch): each expert rank
owns a contiguous 1/G slice of every row's groups — a free local slice
of the replicated activations. It routes those groups locally and two
``lax.all_to_all``s carry only the dispatched capacity slices
[n_groups, E_local, G·cap, d] to the expert owners and back. The
combined outputs are reassembled replicated via the framework's
scatter+psum idiom (parallel/api.py:_gather_replicated — an
``all_gather`` result stays tracked device-varying and could not feed
the replicated residual stream), fused over the expert and TP axes in
one reduction. all_to_all / psum rendezvous GROUP-locally, which is
what lets this op run inside the 1F1B engine's stage-varying branches
(ops/pipeline.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def _route(xg: jax.Array, router_w: jax.Array, e: int, cap: int,
           top_k: int):
    """Route one token group [t, d] → dispatch/combine [t, e, cap]
    (f32) plus per-expert load statistics [e].

    ``top_k == 1``: Switch routing — the token's combine weight is its
    raw top gate. ``top_k >= 2``: GShard — each round dispatches the
    next-best expert, queue positions offset by ALL earlier rounds'
    claims (kept or dropped, matching GShard's ``locations2 += sum
    (mask1)``), and gates renormalize over the chosen set, so a token
    whose first choice overflowed still flows through its second.
    """
    logits = (xg @ router_w.astype(xg.dtype)).astype(jnp.float32)  # [t, e]
    probs = jax.nn.softmax(logits, axis=-1)
    remaining = probs
    counts = jnp.zeros((e,), jnp.float32)   # queue claims so far
    disps, gates = [], []
    for _ in range(top_k):
        gate_k = jnp.max(remaining, axis=-1)              # [t]
        choice = jnp.argmax(remaining, axis=-1)           # [t]
        oh = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # [t, e]
        # position within the expert's queue: this round's arrival
        # order plus every earlier round's total claims on that expert
        pos = (jnp.sum((jnp.cumsum(oh, axis=0) - 1.0) * oh, axis=-1)
               + oh @ counts).astype(jnp.int32)           # [t]
        slot = jax.nn.one_hot(pos, cap, dtype=jnp.float32)  # 0 if pos>=cap
        disps.append(oh[:, :, None] * slot[:, None, :])     # [t, e, cap]
        gates.append(gate_k)
        counts = counts + jnp.sum(oh, axis=0)
        remaining = remaining * (1.0 - oh)
    dispatch = disps[0] if top_k == 1 else sum(disps)
    if top_k == 1:
        combine = disps[0] * gates[0][:, None, None]
    else:
        denom = sum(gates) + 1e-9
        combine = sum((g / denom)[:, None, None] * dk
                      for g, dk in zip(gates, disps))
    # load statistics use FIRST-choice fractions (the Switch/GShard
    # aux convention, independent of later rounds' capacity outcomes)
    frac = jnp.mean(jax.nn.one_hot(jnp.argmax(probs, axis=-1), e,
                                   dtype=jnp.float32), axis=0)
    mprob = jnp.mean(probs, axis=0)
    return dispatch, combine, frac, mprob


def _expert_ffn(expert_in: jax.Array, w1: jax.Array, w2: jax.Array,
                dtype) -> jax.Array:
    """[e_local, c, d] through each local expert's two-layer FFN —
    scanned so XLA emits one fused kernel pair per expert shard."""
    def one_expert(carry, packed):
        del carry
        inp, w1_e, w2_e = packed
        h = jax.nn.relu(inp @ w1_e.astype(dtype))
        return None, h @ w2_e.astype(dtype)

    _, expert_out = lax.scan(one_expert, None, (expert_in, w1, w2))
    return expert_out


def moe_ffn(x: jax.Array, router_w: jax.Array, w1: jax.Array, w2: jax.Array,
            *, num_experts: int, capacity_factor: float = 1.25,
            router_top_k: int = 1, num_groups: int = 0,
            expert_axis: str | None = None,
            tp_axis: str | None = None,
            stats_axes: tuple[str, ...] = ()) -> tuple[jax.Array, jax.Array]:
    """Top-k routed expert FFN over fixed per-row token groups.

    Args (inside shard_map when ``expert_axis``/``tp_axis`` are set):
      x: [batch, seq, d] activations (replicated over both axes; under
        SP the caller passes its seq-local slice).
      router_w: [d, E] routing weights (replicated).
      w1: [E_local, d, ff_local], w2: [E_local, ff_local, d] — THIS
        rank's expert slice (E_local = E / expert-axis size) and, with
        ``tp_axis``, its Megatron column/row slice of every expert's
        hidden dim (ff_local = ff / tp-axis size). The two shardings
        compose: EP picks which experts live here, TP splits each
        expert's FFN across the model axis, and ONE fused psum over
        both axes reassembles the combined output.
      num_experts: E (global).
      capacity_factor: per-group capacity =
        ceil(cf · top_k · group_size / E); overflow tokens lose that
        round's slot (pass through the residual, or — top-k — flow
        through a later choice).
      router_top_k: experts per token (module docstring).
      num_groups: chunks per GLOBAL sequence row (module docstring);
        the per-call group count divides out any seq sharding named in
        ``stats_axes``. 0 = auto: the minimum this call's sharding
        requires (one group per expert rank, or one group per row
        unsharded) — mesh-dependent, so fixed-mesh comparisons set it
        explicitly.
      stats_axes: extra mesh axes the sequence is sharded over (the seq
        axis under SP): the aux pmean runs over them, and the global
        ``num_groups`` is interpreted per global row across them.

    Returns (out [batch, seq, d], aux): ``aux`` is the mean over token
    groups of the per-group Switch load-balance loss
    E·Σ_e(fraction_e · mean_prob_e), pmean'd over the expert axis and
    ``stats_axes`` — i.e. the mean over ALL of this layer's groups,
    replicated; add ``aux_weight * aux`` to the train loss.
    """
    b, s, d = x.shape
    e = num_experts
    if not 1 <= router_top_k <= e:
        raise ValueError(f"moe_router_top_k={router_top_k} must be in "
                         f"[1, num_experts={e}]")
    # routing math stays f32 (inside _route); the FFN FLOPs run in the
    # compute dtype like the dense branch (bf16 feeds the MXU full-rate)
    dtype = x.dtype

    g_ep = 1
    if expert_axis is not None:
        e_local = w1.shape[0]
        g_ep = e // e_local                       # expert-axis size
    n_seq_shards = 1
    for ax in stats_axes:
        n_seq_shards *= lax.axis_size(ax)

    if num_groups:
        if num_groups % n_seq_shards:
            raise ValueError(
                f"moe_num_groups={num_groups} must divide by the "
                f"sequence sharding ({n_seq_shards} shards) so group "
                "boundaries align with shard boundaries")
        gh = num_groups // n_seq_shards           # groups per local row
    else:
        gh = g_ep                                 # auto: one per EP rank
    if gh % g_ep:
        raise ValueError(
            f"per-shard group count {gh} (moe_num_groups="
            f"{num_groups or 'auto'}) must divide by the expert-parallel "
            f"rank count {g_ep}")
    if s % gh:
        raise ValueError(
            f"local sequence length {s} must divide into {gh} token "
            f"groups (moe_num_groups={num_groups or 'auto'})")
    gs = s // gh                                  # tokens per group
    cap = max(1, math.ceil(capacity_factor * router_top_k * gs / e))

    def route_many(xg):                           # [n_g, gs, d]
        return jax.vmap(lambda g: _route(g, router_w, e, cap,
                                         router_top_k))(xg)

    if expert_axis is None:
        n_g = b * gh
        xg = x.reshape(n_g, gs, d)
        dispatch, combine, frac, mprob = route_many(xg)
        # experts see each group's capacity slots independently
        expert_in = jnp.einsum("gtec,gtd->gecd", dispatch.astype(dtype), xg)
        ei = expert_in.transpose(1, 0, 2, 3).reshape(e, n_g * cap, d)
        eo = _expert_ffn(ei, w1, w2, dtype)
        expert_out = eo.reshape(e, n_g, cap, d).transpose(1, 0, 2, 3)
        out = jnp.einsum("gtec,gecd->gtd", combine.astype(dtype), expert_out)
        out = out.reshape(b, s, d)
        if tp_axis is not None:
            out = lax.psum(out, tp_axis)
    else:
        me = lax.axis_index(expert_axis)
        s_r = s // g_ep                   # this rank's contiguous slice
        gh_l = gh // g_ep                 # its groups per row
        x_r = lax.dynamic_slice_in_dim(x, me * s_r, s_r, axis=1)
        n_g = b * gh_l
        xg = x_r.reshape(n_g, gs, d)
        dispatch, combine, frac, mprob = route_many(xg)
        expert_in = jnp.einsum("gtec,gtd->gecd", dispatch.astype(dtype), xg)
        # all-to-all #1: [n_g, E, cap, d] → [n_g, E_local, G·cap, d] —
        # each rank receives, for its local experts, every rank's
        # dispatched capacity slices
        expert_in = lax.all_to_all(expert_in, expert_axis, 1, 2, tiled=True)
        ei = (expert_in.transpose(1, 0, 2, 3)
              .reshape(e_local, n_g * g_ep * cap, d))
        eo = _expert_ffn(ei, w1, w2, dtype)
        expert_out = (eo.reshape(e_local, n_g, g_ep * cap, d)
                      .transpose(1, 0, 2, 3))
        # all-to-all #2 (inverse): slots come home, experts back in
        # global order (owners are rank-ordered)
        expert_out = lax.all_to_all(expert_out, expert_axis, 2, 1,
                                    tiled=True)
        out_g = jnp.einsum("gtec,gecd->gtd", combine.astype(dtype),
                           expert_out)
        # reassemble the replicated [b, s, d] residual input:
        # scatter+psum (the _gather_replicated idiom — statically
        # replicated, unlike all_gather), fused with the TP reduction
        scat = lax.dynamic_update_slice_in_dim(
            jnp.zeros((b, s, d), dtype), out_g.reshape(b, s_r, d),
            me * s_r, axis=1)
        reduce_axes = ((expert_axis, tp_axis) if tp_axis is not None
                       else (expert_axis,))
        out = lax.psum(scat, reduce_axes)

    # per-group Switch loss, averaged over every group of the layer:
    # mean over this call's groups, then over expert ranks (disjoint
    # group slices) and seq shards — all equal-sized, so the pmean of
    # means IS the global mean over groups
    group_aux = e * jnp.sum(frac * mprob, axis=-1)        # [n_g]
    aux = jnp.mean(group_aux)
    reduce = ((() if expert_axis is None else (expert_axis,))
              + tuple(stats_axes))
    if reduce:
        aux = lax.pmean(aux, reduce)
    return out, aux.astype(jnp.float32)
