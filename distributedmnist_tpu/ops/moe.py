"""Mixture-of-experts FFN with expert parallelism.

The fifth parallelism family (data/tensor/sequence/pipeline/expert —
all absent from the reference, SURVEY §2.2). Switch-Transformer-style
top-1 routing with a fixed per-expert capacity and a load-balancing
auxiliary loss (cf. arXiv:2101.03961), in the GShard dispatch/combine
einsum formulation (arXiv:2006.16668) — static shapes throughout, so
XLA sees two dense batched matmuls per expert shard and the MXU stays
busy regardless of routing.

Expert-parallel layout mirrors the framework's tensor-parallel
pattern: activations are REPLICATED over the expert axis, each rank
holds ``E / axis_size`` experts' weights, computes dispatch/combine
for its local experts only, and one psum over the axis reassembles the
combined output. No all-to-all is needed in this layout because tokens
are already visible to every expert rank; the psum payload is [t, d]
activations, riding ICI.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def moe_ffn(x: jax.Array, router_w: jax.Array, w1: jax.Array, w2: jax.Array,
            *, num_experts: int, capacity_factor: float = 1.25,
            expert_axis: str | None = None,
            tp_axis: str | None = None) -> tuple[jax.Array, jax.Array]:
    """Top-1 routed expert FFN.

    Args (inside shard_map when ``expert_axis``/``tp_axis`` are set):
      x: [batch, seq, d] activations (replicated over both axes).
      router_w: [d, E] routing weights (replicated).
      w1: [E_local, d, ff_local], w2: [E_local, ff_local, d] — THIS
        rank's expert slice (E_local = E / expert-axis size) and, with
        ``tp_axis``, its Megatron column/row slice of every expert's
        hidden dim (ff_local = ff / tp-axis size). The two shardings
        compose: EP picks which experts live here, TP splits each
        expert's FFN across the model axis, and ONE fused psum over
        both axes reassembles the combined output.
      num_experts: E (global).
      capacity_factor: per-expert capacity = ceil(cf · tokens / E);
        overflow tokens pass through the residual unchanged (their
        combine weight is zero).

    Returns (out [batch, seq, d], aux): ``aux`` is the Switch
    load-balancing loss E·Σ_e(fraction_e · mean_prob_e), ≈1 when
    perfectly balanced; add ``aux_weight * aux`` to the train loss.
    """
    b, s, d = x.shape
    t = b * s
    e = num_experts
    cap = max(1, math.ceil(capacity_factor * t / e))
    xf = x.reshape(t, d)

    logits = (xf @ router_w.astype(xf.dtype)).astype(jnp.float32)  # [t, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate = jnp.max(probs, axis=-1)                    # [t]
    choice = jnp.argmax(probs, axis=-1)               # [t]
    onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # [t, E]

    # load-balance aux: fraction of tokens vs mean router prob per expert
    aux = e * jnp.sum(jnp.mean(onehot, axis=0) * jnp.mean(probs, axis=0))

    # position of each token within its expert's queue (0-based);
    # tokens past capacity get a zero dispatch row (dropped -> residual)
    pos = jnp.sum((jnp.cumsum(onehot, axis=0) - 1.0) * onehot,
                  axis=-1).astype(jnp.int32)          # [t]
    slot = jax.nn.one_hot(pos, cap, dtype=jnp.float32)  # [t, C]
    dispatch = onehot[:, :, None] * slot[:, None, :]    # [t, E, C]

    if expert_axis is not None:
        e_local = w1.shape[0]
        me = lax.axis_index(expert_axis)
        dispatch = lax.dynamic_slice_in_dim(dispatch, me * e_local, e_local,
                                            axis=1)   # [t, E_local, C]
    combine = dispatch * gate[:, None, None]

    # routing math stayed f32 above; the FFN FLOPs run in the compute
    # dtype like the dense branch (bf16 feeds the MXU at full rate)
    dtype = x.dtype
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dtype), xf)

    def one_expert(carry, packed):
        del carry
        inp, w1_e, w2_e = packed
        h = jax.nn.relu(inp @ w1_e.astype(dtype))
        return None, h @ w2_e.astype(dtype)

    _, expert_out = lax.scan(one_expert, None,
                             (expert_in, w1, w2))     # [E_local, C, d]
    out = jnp.einsum("tec,ecd->td", combine.astype(dtype), expert_out)
    # One psum reassembles both decompositions: over the expert axis
    # (each rank combined only its local experts) and the TP axis (each
    # rank's w2 row-slice yields a partial sum of the full d).
    reduce_axes = tuple(a for a in (expert_axis, tp_axis) if a is not None)
    if reduce_axes:
        out = lax.psum(out, reduce_axes)
        # (aux needs no reduction: the router is replicated, so every
        # rank computed the identical value)
    return out.reshape(b, s, d), aux.astype(jnp.float32)
