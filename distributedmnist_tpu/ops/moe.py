"""Mixture-of-experts FFN with expert parallelism.

The fifth parallelism family (data/tensor/sequence/pipeline/expert —
all absent from the reference, SURVEY §2.2). Switch-Transformer-style
top-1 routing with a fixed per-expert capacity and a load-balancing
auxiliary loss (cf. arXiv:2101.03961), in the GShard dispatch/combine
einsum formulation (arXiv:2006.16668) — static shapes throughout, so
XLA sees two dense batched matmuls per expert shard and the MXU stays
busy regardless of routing.

Expert-parallel layout (GShard all-to-all dispatch): the expert axis
doubles as a token-group axis inside the MoE block. Each rank slices
its 1/G of the (replicated) token set — free, no collective — routes
those tokens locally with SHARD-LOCAL capacity ceil(cf·t_g/E), and two
``lax.all_to_all``s carry only the dispatched capacity slices
[E_local, G·C_g, d] to the expert owners and back. Routing and the
dispatch/combine einsums therefore run over t/G tokens per rank
(the round-3 layout ran them redundantly over all t on every rank).
The combined group outputs are reassembled replicated via the
framework's scatter+psum idiom (parallel/api.py:_gather_replicated —
an ``all_gather`` result stays tracked device-varying and could not
feed the replicated residual stream), fused over the expert and TP
axes in one reduction.

Capacity semantics: capacity is LOCAL to each token group — a group
whose tokens concentrate on one expert drops tokens that would have
fit under global capacity. This is the documented GShard trade (group-
local dispatch keeps every shape static and the collectives capacity-
sized); with ``capacity_factor ≥ E/…`` such that C_g ≥ t_g nothing can
ever drop and the EP output equals the dense oracle exactly
(tests/test_moe.py gold-parity tests).

The load-balance statistics are averaged over the expert axis (and any
``stats_axes``, e.g. the sequence axis under SP×EP) BEFORE forming the
aux loss, so ``aux`` equals the dense computation over the full token
set exactly — group-local aux would bias toward per-group imbalance.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def _route(xg: jax.Array, router_w: jax.Array, e: int, cap: int):
    """Top-1 routing over one token group [t, d] → dispatch/combine
    [t, e, cap] (f32) plus per-expert load statistics [e]."""
    logits = (xg @ router_w.astype(xg.dtype)).astype(jnp.float32)  # [t, e]
    probs = jax.nn.softmax(logits, axis=-1)
    gate = jnp.max(probs, axis=-1)                    # [t]
    choice = jnp.argmax(probs, axis=-1)               # [t]
    onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # [t, e]
    # position of each token within its expert's queue (0-based);
    # tokens past capacity get a zero dispatch row (dropped -> residual)
    pos = jnp.sum((jnp.cumsum(onehot, axis=0) - 1.0) * onehot,
                  axis=-1).astype(jnp.int32)          # [t]
    slot = jax.nn.one_hot(pos, cap, dtype=jnp.float32)  # [t, cap]
    dispatch = onehot[:, :, None] * slot[:, None, :]    # [t, e, cap]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine, jnp.mean(onehot, axis=0), jnp.mean(probs, axis=0)


def _expert_ffn(expert_in: jax.Array, w1: jax.Array, w2: jax.Array,
                dtype) -> jax.Array:
    """[e_local, c, d] through each local expert's two-layer FFN —
    scanned so XLA emits one fused kernel pair per expert shard."""
    def one_expert(carry, packed):
        del carry
        inp, w1_e, w2_e = packed
        h = jax.nn.relu(inp @ w1_e.astype(dtype))
        return None, h @ w2_e.astype(dtype)

    _, expert_out = lax.scan(one_expert, None, (expert_in, w1, w2))
    return expert_out


def moe_ffn(x: jax.Array, router_w: jax.Array, w1: jax.Array, w2: jax.Array,
            *, num_experts: int, capacity_factor: float = 1.25,
            expert_axis: str | None = None,
            tp_axis: str | None = None,
            stats_axes: tuple[str, ...] = (),
            return_stats: bool = False) -> tuple[jax.Array, jax.Array]:
    """Top-1 routed expert FFN.

    Args (inside shard_map when ``expert_axis``/``tp_axis`` are set):
      x: [batch, seq, d] activations (replicated over both axes; under
        SP the caller passes its seq-local slice).
      router_w: [d, E] routing weights (replicated).
      w1: [E_local, d, ff_local], w2: [E_local, ff_local, d] — THIS
        rank's expert slice (E_local = E / expert-axis size) and, with
        ``tp_axis``, its Megatron column/row slice of every expert's
        hidden dim (ff_local = ff / tp-axis size). The two shardings
        compose: EP picks which experts live here, TP splits each
        expert's FFN across the model axis, and ONE fused psum over
        both axes reassembles the combined output.
      num_experts: E (global).
      capacity_factor: per-group capacity = ceil(cf · t_group / E);
        overflow tokens pass through the residual unchanged (their
        combine weight is zero). Under EP the group is this rank's t/G
        token slice — capacity is shard-local (module docstring).
      stats_axes: extra mesh axes whose token shards the load-balance
        statistics must average over (the seq axis under SP), so the
        aux loss matches the dense full-token computation exactly.
      return_stats: return the RAW averaged routing statistics
        ``(frac, mean_prob)`` (each [E]) instead of the aux scalar —
        for callers that see only a token SLICE per call (the pipeline
        processing one microbatch per tick) and must average the
        statistics across calls BEFORE forming the aux product, since
        E·Σ frac·mprob is not linear in the statistics.

    Returns (out [batch, seq, d], aux): ``aux`` is the Switch
    load-balancing loss E·Σ_e(fraction_e · mean_prob_e), ≈1 when
    perfectly balanced; add ``aux_weight * aux`` to the train loss.
    With ``return_stats``, (out, (frac [E], mean_prob [E])) instead.
    """
    b, s, d = x.shape
    t = b * s
    e = num_experts
    xf = x.reshape(t, d)
    # routing math stays f32 (inside _route); the FFN FLOPs run in the
    # compute dtype like the dense branch (bf16 feeds the MXU full-rate)
    dtype = x.dtype

    if expert_axis is None:
        cap = max(1, math.ceil(capacity_factor * t / e))
        dispatch, combine, frac, mprob = _route(xf, router_w, e, cap)
        expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dtype), xf)
        expert_out = _expert_ffn(expert_in, w1, w2, dtype)   # [e, cap, d]
        out = jnp.einsum("tec,ecd->td", combine.astype(dtype), expert_out)
        if tp_axis is not None:
            out = lax.psum(out, tp_axis)
    else:
        e_local = w1.shape[0]
        g = e // e_local                  # expert-axis size (static)
        if t % g:
            raise ValueError(
                f"MoE token count {t} (batch {b} × seq {s}) must divide "
                f"by the expert-parallel group count {g}")
        t_g = t // g
        me = lax.axis_index(expert_axis)
        # this rank's token group — a local slice of the replicated set
        xg = lax.dynamic_slice_in_dim(xf, me * t_g, t_g, axis=0)
        cap = max(1, math.ceil(capacity_factor * t_g / e))   # shard-local
        dispatch, combine, frac, mprob = _route(xg, router_w, e, cap)
        expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dtype), xg)
        # all-to-all #1: [E, C_g, d] → [E_local, G·C_g, d] — each rank
        # receives, for its local experts, every group's capacity slice
        expert_in = lax.all_to_all(expert_in, expert_axis, 0, 1, tiled=True)
        expert_out = _expert_ffn(expert_in, w1, w2, dtype)
        # all-to-all #2 (inverse): [E_local, G·C_g, d] → [E, C_g, d] —
        # this group's slots come home from every expert owner, experts
        # back in global order (owners are rank-ordered)
        expert_out = lax.all_to_all(expert_out, expert_axis, 1, 0, tiled=True)
        out_g = jnp.einsum("tec,ecd->td", combine.astype(dtype), expert_out)
        # reassemble the replicated [t, d] residual input: scatter+psum
        # (the _gather_replicated idiom — statically replicated, unlike
        # all_gather), fused with the TP row-parallel reduction
        scat = lax.dynamic_update_slice_in_dim(
            jnp.zeros((t, d), dtype), out_g, me * t_g, axis=0)
        reduce_axes = ((expert_axis, tp_axis) if tp_axis is not None
                       else (expert_axis,))
        out = lax.psum(scat, reduce_axes)

    stat_axes = ((() if expert_axis is None else (expert_axis,))
                 + tuple(stats_axes))
    if stat_axes:
        # equal-sized groups ⇒ the mean of group means IS the global
        # mean: aux computed from these equals the dense aux exactly
        frac = lax.pmean(frac, stat_axes)
        mprob = lax.pmean(mprob, stat_axes)
    if return_stats:
        return out.reshape(b, s, d), (frac, mprob)
    aux = e * jnp.sum(frac * mprob)
    return out.reshape(b, s, d), aux.astype(jnp.float32)
