"""Fused flash attention as a Pallas TPU kernel.

The hot op of the long-context model family. XLA's dense-attention
lowering materializes the [s, s] score matrix in HBM; this kernel
streams K/V blocks through VMEM with an online-softmax accumulator, so
HBM traffic stays O(s·d) and the two matmuls per block ride the MXU
back-to-back without leaving the chip.

The reference has no attention at all (SURVEY §5.7; fixed 28×28 inputs,
reference src/mnist.py:27-30) — this is framework capability, not
parity. Composes with the sequence-parallel strategies:

* single-device / data-parallel: :func:`flash_attention_bshd` is the
  model-layout entry — it reads the residual stream's natural
  [batch, seq, heads, head_dim] (one free reshape away from
  [b, s, d_model]) via a head grid axis, so NO transpose is ever
  materialized around the kernel. Measured on v5e at the bench shape
  this removes ~20 ms/step of pure layout copies (~14% of the step).
* Ulysses (ops/ulysses_attention): after the all-to-all each device
  holds full sequences for a head subset in [b, h, s, d] —
  :func:`flash_attention` serves that layout (free reshape to a
  folded batch·heads grid, still no transpose).
* ring (ops/ring_attention): keeps its own psum-free online-softmax
  accumulator across ppermute steps.

Internally both entries run ONE kernel set over [B', s, H', d]:
bhsd folds to [b·h, s, 1, d], bshd keeps [b, s, h, d]; grid =
(B', H', q blocks, k blocks), the k dimension "arbitrary"
(sequential) so the f32 accumulator/max/denominator live in VMEM
scratch across k steps and outputs are written once at the final k
block. Head dim and sequence are padded to lane/block multiples and
masked, so any (s, d) works.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # pre-rename spelling (jax ≤ 0.4.x) of the same dataclass
    pltpu.CompilerParams = pltpu.TPUCompilerParams

_NEG_INF = -1e30  # finite: keeps exp() algebra NaN-free on padded rows

_LANE = 128


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *rest,
                 scale: float, causal: bool, block_q: int, block_k: int,
                 seq_len: int, save_lse: bool):
    if save_lse:  # lse output only exists on the VJP-forward variant
        lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        acc_ref, m_ref, l_ref = rest
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Blocks strictly above the causal diagonal contribute nothing.
    live = (ik * block_k <= iq * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _accumulate():
        # native-dtype operands: bf16 inputs ride the MXU's bf16 path
        # (4× f32 throughput) with f32 accumulation via
        # preferred_element_type
        q = q_ref[0]   # [bq, dp]
        k = k_ref[0]   # [bk, dp]
        v = v_ref[0]   # [bk, dp]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = kpos < seq_len  # padded keys never attend
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask &= qpos >= kpos
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                       # [bq, bk]
        corr = jnp.exp(m_prev - m_new)               # [bq, 1]
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        if save_lse:
            # log-sum-exp per query row, lane-broadcast (the backward
            # kernels re-normalize scores with it instead of
            # re-reducing). The 128-lane replication is the TPU-native
            # layout for a per-sublane-row scalar (the lane dim cannot
            # go below one 128 tile); upstream flash kernels store TWO
            # such arrays (l and m) — folding into lse halves that.
            lse_ref[0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


# Measured (block_q, block_k) table for v5e ("TPU v5 lite", bf16,
# head_dim ≤ 128), keyed by the smallest table seq ≥ s. Swept on-chip
# with scan-chunk timing (one dispatch per 10-50 kernel chains so the
# tunnel relay amortizes) over the FULL train composition — custom-vjp
# forward + dq + dkv kernels with all three cotangents consumed (an
# earlier sweep whose chain used only dq let XLA dead-code the dkv
# kernel and mis-ranked (512,1024) at depth): (1024,1024) wins at
# every S ≥ 1024 — 4.66 ms vs 7.78 for the old fixed (512,512) at the
# S=1024 bench shape, 11.0 vs 16.3 at S=8192. Blocks stay ≤1024:
# 2048-wide blocks exceed the 16 MB scoped-VMEM stack limit at depth
# (compile-time OOM in the dkv kernel). Callers can still override
# explicitly; other chips inherit the table as a heuristic.
_TUNED_BLOCKS = (
    (512, (512, 512)),
    (1 << 62, (1024, 1024)),
)


def _auto_blocks(s: int) -> tuple[int, int]:
    for bound, blocks in _TUNED_BLOCKS:
        if s <= bound:
            return blocks
    raise AssertionError  # unreachable: table ends with a sentinel


def _block_sizes(s: int, block_q: int, block_k: int) -> tuple[int, int]:
    """Clamp blocks to the sequence and align to the 8-row sublane tile.

    Beyond clamping, blocks are *balanced*: keep the block count implied
    by the requested size, then shrink each block so the last one isn't
    mostly padding (s=600 with 512-blocks becomes 2×304 → 608 padded
    rows instead of 2×512 → 1024, saving ~2.9× of masked-out MXU work).
    Balancing is discarded if it blows up the lcm padding instead. The
    backward must derive the SAME values so residual shapes line up.
    """
    import math
    r8 = lambda n: -(-n // 8) * 8
    bq0 = r8(min(block_q, max(s, 1)))
    bk0 = r8(min(block_k, max(s, 1)))
    bq1 = r8(-(-s // max(1, -(-s // bq0))))
    bk1 = r8(-(-s // max(1, -(-s // bk0))))

    def padded(bq, bk):
        m = math.lcm(bq, bk)
        return -(-s // m) * m

    return min(((bq1, bk1), (bq0, bk0)),
               key=lambda p: (padded(*p), -(p[0] * p[1])))


def _prep(x: jax.Array, block_q: int, block_k: int) -> jax.Array:
    """[B', s, H', d] → [B', s_padded, H'·d_padded] (lcm so BOTH grids
    tile the padded sequence exactly). The head axis folds into the
    lane dim — Pallas TPU blocks must keep their last two dims
    (sublane, lane) tile-aligned, so a head GRID axis instead selects
    each head's 128-lane slice via the index map (no transpose, and for
    d=128 no copy at all: the reshape is free)."""
    import math
    bb, s, hh, d = x.shape
    x = _pad_to(x, 3, _LANE)
    x = _pad_to(x, 1, math.lcm(block_q, block_k))
    return x.reshape(bb, x.shape[1], hh * x.shape[3])


def _vma_sds(shape, dtype, *inputs):
    """ShapeDtypeStruct declaring the union of the inputs' varying mesh
    axes — required for pallas_call outputs under shard_map check_vma."""
    vma = frozenset()
    for x in inputs:
        vma |= getattr(jax.typeof(x), "vma", frozenset()) or frozenset()
    return (jax.ShapeDtypeStruct(shape, dtype, vma=vma) if vma
            else jax.ShapeDtypeStruct(shape, dtype))


def _forward(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
             scale: float, block_q: int, block_k: int, interpret: bool,
             save_lse: bool) -> tuple[jax.Array, jax.Array | None]:
    bb, s, hh, d = q.shape
    block_q, block_k = _block_sizes(s, block_q, block_k)
    qp = _prep(q, block_q, block_k)
    kp = _prep(k, block_q, block_k)
    vp = _prep(v, block_q, block_k)
    _, sp, hdp = qp.shape
    dp = hdp // hh
    nq, nk = sp // block_q, sp // block_k

    kernel = functools.partial(
        _attn_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_len=s, save_lse=save_lse)
    out_shape = [_vma_sds((bb, sp, hh * dp), q.dtype, qp, kp, vp)]
    out_specs = [pl.BlockSpec((1, block_q, dp),
                              lambda ib, ih, iq, ik: (ib, iq, ih))]
    if save_lse:
        out_shape.append(_vma_sds((bb, sp, hh * _LANE), jnp.float32,
                                  qp, kp, vp))
        out_specs.append(pl.BlockSpec((1, block_q, _LANE),
                                      lambda ib, ih, iq, ik: (ib, iq, ih)))
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(bb, hh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, dp),
                         lambda ib, ih, iq, ik: (ib, iq, ih)),
            pl.BlockSpec((1, block_k, dp),
                         lambda ib, ih, iq, ik: (ib, ik, ih)),
            pl.BlockSpec((1, block_k, dp),
                         lambda ib, ih, iq, ik: (ib, ik, ih)),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((block_q, dp), jnp.float32),     # acc
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running denom
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    out = res[0].reshape(bb, sp, hh, dp)[:, :s, :, :d]
    return out, (res[1] if save_lse else None)


# ---------------------------------------------------------------------------
# Backward: FlashAttention-2 style pallas kernels. The forward saves
# per-row log-sum-exp, so the backward re-derives p = exp(s - lse) in
# one pass — no second online softmax. Two kernels, both recomputing
# the score block on the MXU from VMEM-resident tiles:
#   * dq: grid (B', H', q, k) — k innermost, dq accumulates in scratch.
#   * dk/dv: grid (B', H', k, q) — q innermost, so each k/v tile stays
#     resident while q/do/lse/delta stream past; the transposed
#     contractions (pᵀ·do, dsᵀ·q) ride the MXU via dot_general instead
#     of materializing a transpose.
# Residuals stay O(s·d) + O(s) for lse; the [s, s] score matrix never
# touches HBM in either direction.
# ---------------------------------------------------------------------------

def _scores_block(q_ref, k_ref, lse_ref, iq, ik, *, scale, causal,
                  block_q, block_k, seq_len):
    """Recompute p = exp(q·kᵀ·scale − lse) for one [bq, bk] tile.

    Padded rows carry garbage lse (the forward never normalized them),
    so validity masking must zero p — selection, not arithmetic, keeps
    the inf/NaN out."""
    s = jax.lax.dot_general(q_ref[0], k_ref[0],
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    qpos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = (qpos < seq_len) & (kpos < seq_len)
    if causal:
        mask &= qpos >= kpos
    p = jnp.where(mask, jnp.exp(s - lse_ref[0][:, :1]), 0.0)
    return p


def _delta_block(do_ref, o_ref):
    """δ_i = rowsum(do ⊙ out) for one q block — recomputed in-kernel
    from the out residual (a [bq, d] elementwise+reduce, negligible
    next to the matmuls) instead of materializing a lane-broadcast
    [s, 128] array in HBM."""
    return jnp.sum(do_ref[0].astype(jnp.float32)
                   * o_ref[0].astype(jnp.float32), axis=1, keepdims=True)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                   dq_ref, dq_acc, *, scale: float, causal: bool,
                   block_q: int, block_k: int, seq_len: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = (ik * block_k <= iq * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _accumulate():
        p = _scores_block(q_ref, k_ref, lse_ref, iq, ik, scale=scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          seq_len=seq_len)
        k = k_ref[0]
        dp = jax.lax.dot_general(do_ref[0], v_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - _delta_block(do_ref, o_ref))
        dq_acc[:] += jnp.dot(ds.astype(k.dtype), k,
                             preferred_element_type=jnp.float32) * scale

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                    causal: bool, block_q: int, block_k: int, seq_len: int):
    ik = pl.program_id(2)
    iq = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # Under the causal mask, q blocks strictly before this k block see
    # none of it.
    live = (iq * block_q + block_q - 1 >= ik * block_k) if causal else True

    @pl.when(live)
    def _accumulate():
        p = _scores_block(q_ref, k_ref, lse_ref, iq, ik, scale=scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          seq_len=seq_len)
        q = q_ref[0]
        do = do_ref[0]
        # contract over the q rows (dim 0 of both): pᵀ·do and dsᵀ·q
        dv_acc[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - _delta_block(do_ref, o_ref))
        dk_acc[:] += jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32) * scale

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                      dq_ref, dk_ref, dv_ref, *, scale: float, causal: bool,
                      block_q: int, block_k: int, seq_len: int):
    """Single-visit backward for the one-block-pair case (nq == nk == 1,
    i.e. the whole padded sequence fits one (block_q, block_k) tile —
    true for every s ≤ 1024 under the tuned table). The split dq / dkv
    kernels each recompute the score matrix; here p and do·vᵀ are
    computed ONCE and feed all three cotangents — 7 → 5 score-sized
    matmuls (−29% backward FLOPs), measured −2.5 ms/step on the v5e
    flash bench. Larger grids keep the two-kernel path: a fused kernel
    would have to revisit dq blocks across non-adjacent iterations,
    and the resulting spill/reload traffic exceeds the recompute."""
    # the always-true pl.when is load-bearing on the interpreter path:
    # cond discharge inserts the vma adjustments that let ref gets on
    # mesh-varying blocks pass shard_map's check_vma (the split
    # kernels get this for free from their real pl.when branches)
    @pl.when(pl.program_id(2) == 0)
    def _all():
        p = _scores_block(q_ref, k_ref, lse_ref, 0, 0, scale=scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          seq_len=seq_len)
        q = q_ref[0]
        do = do_ref[0]
        dv_ref[0] = jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - _delta_block(do_ref, o_ref))
        dq_ref[0] = (jnp.dot(ds.astype(q.dtype), k_ref[0],
                             preferred_element_type=jnp.float32)
                     * scale).astype(dq_ref.dtype)
        dk_ref[0] = (jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
                     * scale).astype(dk_ref.dtype)


def _backward(q, k, v, out, lse, dout, causal: bool, scale: float,
              block_q: int, block_k: int, interpret: bool):
    bb, s, hh, d = q.shape
    block_q, block_k = _block_sizes(s, block_q, block_k)
    qp = _prep(q, block_q, block_k)
    kp = _prep(k, block_q, block_k)
    vp = _prep(v, block_q, block_k)
    dop = _prep(dout, block_q, block_k)
    op = _prep(out, block_q, block_k)
    _, sp, hdp = qp.shape
    dp = hdp // hh
    nq, nk = sp // block_q, sp // block_k
    assert lse.shape == (bb, sp, hh * _LANE), (lse.shape,
                                               (bb, sp, hh * _LANE))

    def unpad(x, dtype):
        return x.reshape(bb, sp, hh, dp)[:, :s, :, :d].astype(dtype)

    if nq == 1 and nk == 1:
        # one block pair — fused single-pass kernel (docstring above).
        # The grid keeps the 4D (B', H', 1, 1) shape of the split
        # kernels so every block index stays a traced grid value (a
        # literal 0 index breaks the interpreter's vma check under
        # shard_map — the Ulysses composition tests pin this).
        fspec = pl.BlockSpec((1, block_q, dp),
                             lambda ib, ih, i, j: (ib, i, ih))
        flane = pl.BlockSpec((1, block_q, _LANE),
                             lambda ib, ih, i, j: (ib, i, ih))
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k, seq_len=s),
            out_shape=[_vma_sds((bb, sp, hdp), t.dtype, qp, kp, vp, dop)
                       for t in (q, k, v)],
            grid=(bb, hh, 1, 1),
            in_specs=[fspec, fspec, fspec, fspec, fspec, flane],
            out_specs=[fspec, fspec, fspec],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel",
                                     "arbitrary")),
            interpret=interpret,
        )(qp, kp, vp, dop, op, lse)
        return (unpad(dq, q.dtype), unpad(dk, k.dtype), unpad(dv, v.dtype))

    # Per grid: the q-tiled operands follow the q program index — dim 2
    # in the dq grid (B', H', nq, nk), dim 3 in the dkv grid
    # (B', H', nk, nq) — and the k-tiled operands follow the other.
    qspec = pl.BlockSpec((1, block_q, dp), lambda ib, ih, i, j: (ib, i, ih))
    lane_q = pl.BlockSpec((1, block_q, _LANE),
                          lambda ib, ih, i, j: (ib, i, ih))
    qspec_inner = pl.BlockSpec((1, block_q, dp),
                               lambda ib, ih, i, j: (ib, j, ih))
    lane_q_inner = pl.BlockSpec((1, block_q, _LANE),
                                lambda ib, ih, i, j: (ib, j, ih))
    kspec = pl.BlockSpec((1, block_k, dp), lambda ib, ih, i, j: (ib, i, ih))
    kspec_inner = pl.BlockSpec((1, block_k, dp),
                               lambda ib, ih, i, j: (ib, j, ih))

    common = dict(scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, seq_len=s)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        out_shape=_vma_sds((bb, sp, hdp), q.dtype, qp, kp, vp, dop),
        grid=(bb, hh, nq, nk),
        in_specs=[qspec, kspec_inner, kspec_inner, qspec, qspec, lane_q],
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((block_q, dp), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, op, lse)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        out_shape=[_vma_sds((bb, sp, hdp), k.dtype, qp, kp, vp, dop),
                   _vma_sds((bb, sp, hdp), v.dtype, qp, kp, vp, dop)],
        grid=(bb, hh, nk, nq),
        in_specs=[qspec_inner, kspec, kspec, qspec_inner, qspec_inner,
                  lane_q_inner],
        out_specs=[kspec, kspec],
        scratch_shapes=[pltpu.VMEM((block_k, dp), jnp.float32),
                        pltpu.VMEM((block_k, dp), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, op, lse)

    return unpad(dq, q.dtype), unpad(dk, k.dtype), unpad(dv, v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _forward(q, k, v, causal, scale, block_q, block_k, interpret,
                    save_lse=False)[0]


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _forward(q, k, v, causal, scale, block_q, block_k, interpret,
                        save_lse=True)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, dout):
    q, k, v, out, lse = res
    return _backward(q, k, v, out, lse, dout, causal, scale, block_q,
                     block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)



def _resolve(s: int, d: int, scale, block_q, block_k, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    auto_q, auto_k = _auto_blocks(s)
    return scale, block_q or auto_q, block_k or auto_k, interpret


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, scale: float | None = None,
                    block_q: int | None = None, block_k: int | None = None,
                    interpret: bool | None = None) -> jax.Array:
    """Exact attention, flash-style. q/k/v: [batch, heads, seq, head_dim]
    (self-attention: one shared seq length). Returns q-shaped output.
    Differentiable (custom blockwise VJP).

    ``block_q``/``block_k`` default to the measured per-seq-length
    table (``_TUNED_BLOCKS``); pass explicit values to override.
    ``interpret=None`` auto-selects: compiled kernel on TPU, pallas
    interpreter elsewhere (the CPU test path).
    """
    b, h, s, d = q.shape
    assert k.shape == v.shape == (b, h, s, d), (q.shape, k.shape, v.shape)
    scale, block_q, block_k, interpret = _resolve(s, d, scale, block_q,
                                                 block_k, interpret)
    # fold heads into the grid's batch dim — a FREE reshape (leading
    # dims merge; no transpose, unlike a [b,s,h,d]→[b,h,s,d] caller)
    fold = lambda x: x.reshape(b * h, s, 1, d)
    out = _flash(fold(q), fold(k), fold(v), causal, scale, block_q,
                 block_k, interpret)
    return out.reshape(b, h, s, d)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret"))
def flash_attention_bshd(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         causal: bool = True, scale: float | None = None,
                         block_q: int | None = None,
                         block_k: int | None = None,
                         interpret: bool | None = None) -> jax.Array:
    """Flash attention over the MODEL layout [batch, seq, heads,
    head_dim] — one free reshape from the residual stream's
    [b, s, d_model], so no [b,s,h,d]→[b,h,s,d] transpose is ever
    materialized (pallas operand layout constraints would force real
    HBM copies; at the bench shape those copies cost more than twice
    the kernel itself). The head dim rides a grid axis; tiles are
    strided in HBM, which the DMA engine handles natively.
    """
    b, s, h, d = q.shape
    assert k.shape == v.shape == (b, s, h, d), (q.shape, k.shape, v.shape)
    scale, block_q, block_k, interpret = _resolve(s, d, scale, block_q,
                                                 block_k, interpret)
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)


flash_attention_bshd.layout = "bshd"  # models detect and skip transposes
