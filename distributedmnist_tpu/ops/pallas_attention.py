"""Fused flash attention as a Pallas TPU kernel.

The hot op of the long-context model family. XLA's dense-attention
lowering materializes the [s, s] score matrix in HBM; this kernel
streams K/V blocks through VMEM with an online-softmax accumulator, so
HBM traffic stays O(s·d) and the two matmuls per block ride the MXU
back-to-back without leaving the chip.

The reference has no attention at all (SURVEY §5.7; fixed 28×28 inputs,
reference src/mnist.py:27-30) — this is framework capability, not
parity. Composes with the sequence-parallel strategies:

* single-device / data-parallel: drop-in ``attention_fn`` for
  models.transformer.
* Ulysses (ops/ulysses_attention): after the all-to-all each device
  holds full sequences for a head subset — exactly this kernel's shape.
* ring (ops/ring_attention): keeps its own psum-free online-softmax
  accumulator across ppermute steps.

Grid = (batch·heads, q blocks, k blocks); the k dimension is
"arbitrary" (sequential), so the f32 accumulator/max/denominator live
in VMEM scratch across k steps and outputs are written once at the
final k block. Head dim and sequence are padded to lane/block
multiples and masked, so any (s, d) works.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30  # finite: keeps exp() algebra NaN-free on padded rows

_LANE = 128


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                 scale: float, causal: bool, block_q: int, block_k: int,
                 seq_len: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Blocks strictly above the causal diagonal contribute nothing.
    live = (ik * block_k <= iq * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)   # [bq, dp]
        k = k_ref[0].astype(jnp.float32)   # [bk, dp]
        v = v_ref[0].astype(jnp.float32)   # [bk, dp]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = kpos < seq_len  # padded keys never attend
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask &= qpos >= kpos
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                       # [bq, bk]
        corr = jnp.exp(m_prev - m_new)               # [bq, 1]
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _forward(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
             scale: float, block_q: int, block_k: int,
             interpret: bool) -> jax.Array:
    b, h, s, d = q.shape
    # Clamp to the sequence, then round up to the 8-row sublane tile so
    # Mosaic gets aligned BlockSpecs even for s not a multiple of 8; the
    # lcm padding + seq_len masking below make the overhang safe.
    block_q = -(-min(block_q, max(s, 1)) // 8) * 8
    block_k = -(-min(block_k, max(s, 1)) // 8) * 8

    import math

    def prep(x):
        x = x.reshape(b * h, s, d)
        x = _pad_to(x, 2, _LANE)
        # lcm so BOTH grids tile the padded sequence exactly
        return _pad_to(x, 1, math.lcm(block_q, block_k))

    qp, kp, vp = prep(q), prep(k), prep(v)
    bh, sp, dp = qp.shape
    nq, nk = sp // block_q, sp // block_k

    kernel = functools.partial(
        _attn_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_len=s)
    # Under shard_map (check_vma) the output must declare which mesh
    # axes it varies over — the union of the inputs' varying axes.
    vma = frozenset()
    for x in (qp, kp, vp):
        vma |= getattr(jax.typeof(x), "vma", frozenset()) or frozenset()
    out_sds = (jax.ShapeDtypeStruct((bh, sp, dp), q.dtype, vma=vma) if vma
               else jax.ShapeDtypeStruct((bh, sp, dp), q.dtype))
    out = pl.pallas_call(
        kernel,
        out_shape=out_sds,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda ib, iq, ik: (ib, iq, 0)),
            pl.BlockSpec((1, block_k, dp), lambda ib, iq, ik: (ib, ik, 0)),
            pl.BlockSpec((1, block_k, dp), lambda ib, iq, ik: (ib, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dp), lambda ib, iq, ik: (ib, iq, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, dp), jnp.float32),     # acc
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running denom
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :s, :d].reshape(b, h, s, d)


# ---------------------------------------------------------------------------
# Backward: flash-style blockwise VJP. The pallas forward isn't
# auto-differentiable (scratch accumulators), so the gradient is a
# custom VJP that recomputes scores block-by-block in f32 — residuals
# stay O(s·d) (q, k, v, out only; the [s, s] score matrix is never
# materialized). Expressed in jnp/lax.scan so XLA fuses it; a dedicated
# backward pallas kernel is a later optimization.
# ---------------------------------------------------------------------------

def _bwd_blockwise(q, k, v, out, dout, causal: bool, scale: float,
                   block: int):
    b, h, s, d = q.shape
    f32 = jnp.float32
    q32, k32, v32, o32, do32 = (x.astype(f32) for x in (q, k, v, out, dout))
    kp = _pad_to(k32, 2, block)
    vp = _pad_to(v32, 2, block)
    sp = kp.shape[2]
    nblk = sp // block
    kpos_base = jnp.arange(block)
    qpos = jnp.arange(s)[:, None]                       # [s, 1]
    delta = jnp.sum(do32 * o32, axis=-1, keepdims=True)  # [b,h,s,1]

    def scores(jblk):
        kj = lax.dynamic_slice_in_dim(kp, jblk * block, block, axis=2)
        sij = jnp.einsum("bhqd,bhkd->bhqk", q32, kj) * scale
        kpos = jblk * block + kpos_base[None, :]
        mask = kpos < s
        if causal:
            mask = mask & (qpos >= kpos)
        return jnp.where(mask, sij, _NEG_INF), kj

    # pass 1: log-sum-exp per query row, streaming over k blocks
    def lse_step(carry, jblk):
        m, l = carry
        sij, _ = scores(jblk)
        m_new = jnp.maximum(m, jnp.max(sij, axis=-1, keepdims=True))
        l = l * jnp.exp(m - m_new) + jnp.sum(jnp.exp(sij - m_new), -1,
                                             keepdims=True)
        return (m_new, l), None

    m0 = jnp.full((b, h, s, 1), _NEG_INF, f32)
    l0 = jnp.zeros((b, h, s, 1), f32)
    dq0 = jnp.zeros_like(q32)

    # Under shard_map, scan carries must match the loop outputs' varying
    # axes (which inherit from the sharded q/k/v).
    def match_vma(x):
        want = getattr(jax.typeof(q32), "vma", frozenset()) or frozenset()
        have = getattr(jax.typeof(x), "vma", frozenset()) or frozenset()
        missing = tuple(want - have)
        return lax.pcast(x, missing, to="varying") if missing else x

    m0, l0, dq0 = (match_vma(x) for x in (m0, l0, dq0))
    (m, l), _ = lax.scan(lse_step, (m0, l0), jnp.arange(nblk))
    lse = m + jnp.log(jnp.maximum(l, 1e-30))

    # pass 2: dq accumulates across blocks; dk/dv are per-block
    def bwd_step(dq, jblk):
        sij, kj = scores(jblk)
        vj = lax.dynamic_slice_in_dim(vp, jblk * block, block, axis=2)
        p = jnp.exp(sij - lse)                            # masked → 0
        dp = jnp.einsum("bhqd,bhkd->bhqk", do32, vj)
        ds = p * (dp - delta)
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kj) * scale
        dkj = jnp.einsum("bhqk,bhqd->bhkd", ds, q32) * scale
        dvj = jnp.einsum("bhqk,bhqd->bhkd", p, do32)
        return dq, (dkj, dvj)

    dq, (dk_blocks, dv_blocks) = lax.scan(bwd_step, dq0, jnp.arange(nblk))

    def unblock(blocks):  # [nblk, b, h, block, d] → [b, h, s, d]
        x = jnp.moveaxis(blocks, 0, 2)          # [b, h, nblk, block, d]
        return x.reshape(b, h, sp, d)[:, :, :s]

    return (dq.astype(q.dtype), unblock(dk_blocks).astype(k.dtype),
            unblock(dv_blocks).astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _forward(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _forward(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v, out)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, dout):
    q, k, v, out = res
    return _bwd_blockwise(q, k, v, out, dout, causal, scale, block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, scale: float | None = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool | None = None) -> jax.Array:
    """Exact attention, flash-style. q/k/v: [batch, heads, seq, head_dim]
    (self-attention: one shared seq length). Returns q-shaped output.
    Differentiable (custom blockwise VJP).

    ``interpret=None`` auto-selects: compiled kernel on TPU, pallas
    interpreter elsewhere (the CPU test path).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, s, d = q.shape
    assert k.shape == v.shape == (b, h, s, d), (q.shape, k.shape, v.shape)
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)
