"""Single-query paged attention as a Pallas TPU kernel.

The decode twin of :mod:`ops.pallas_attention`. The decode service's
hot path reads each slot's K/V through a block table into the paged
cache (:mod:`servesvc.kv_cache`: ``[layers, num_blocks, block_size,
heads, head_dim]`` arrays). The dense path gathers EVERY table entry
into a ``[slots, max_context, heads, head_dim]`` view before attending,
so a 10-token sequence pays the same HBM traffic as a 1k-token one.

This kernel fuses the table walk into the K/V tile load: the grid is
``(slots, max_blocks_per_seq)`` and the K/V BlockSpec index map reads
the prefetched block table — ``(tables[s, j], 0, 0)`` — so each grid
step DMAs exactly one cache block. Two properties make per-token
traffic O(actual context) instead of O(max context):

* dead table entries all point at the reserved null block
  (:data:`servesvc.kv_cache.NULL_BLOCK` = 0), and Pallas skips the DMA
  when consecutive grid steps map to the same block — the dead tail of
  a short sequence's table costs one null-block fetch, not P fetches;
* the accumulation body is wrapped in ``pl.when(j*block_size < length)``
  so dead blocks do no compute at all.

Numeric semantics are pinned to the dense decode path in
``models/transformer.py decode_step`` (and its parity tests): scores
and softmax in f32, scale ``1/sqrt(head_dim)``, masked positions get
the finite ``-1e30`` (whose exp underflows to exactly 0.0 in f32), one
online-softmax accumulator per head in VMEM scratch. The ONE documented
divergence: an idle slot (``length == 0``) returns exact zeros here,
while the dense path softmaxes a fully-masked row into a uniform
average of cache garbage — both are unspecified-by-contract (the
decode loop never reads idle rows), and the parity tests compare live
slots only.

Layout notes: heads are a static in-kernel unroll (decode head counts
are small); K/V tiles ride with heads folded into the lane dim. For
compiled-TPU efficiency size ``block_size`` to a multiple of 8 and
``head_dim`` to a multiple of 128 — other shapes are padded per call
(correct everywhere, and free in interpret mode, but the cache pad is
a real copy on-chip). ``interpret=None`` auto-selects the pallas
interpreter off-TPU, same as the training kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # pre-rename spelling (jax <= 0.4.x) of the same dataclass
    pltpu.CompilerParams = pltpu.TPUCompilerParams

_NEG_INF = -1e30  # finite: matches decode_step's mask, exp -> exact 0.0

_LANE = 128
_SUBLANE = 8


def _paged_kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, scale: float, num_heads: int,
                  block_size: int, hdp: int):
    """One (slot, table-entry) grid step.

    ``tables_ref``/``lengths_ref`` are the scalar-prefetch operands
    (SMEM); the K/V tile for THIS step was already selected by the
    index map reading ``tables_ref[s, j]``, so the kernel body never
    sees a block id — only its tile."""
    s = pl.program_id(0)
    j = pl.program_id(1)
    np_ = pl.num_programs(1)
    length = lengths_ref[s]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Dead blocks (entirely past the sequence) do no compute; their
    # table entries are all NULL_BLOCK so the DMA was skipped too.
    @pl.when(j * block_size < length)
    def _accumulate():
        k_tile = k_ref[0].astype(jnp.float32)   # [Bp, h*hdp]
        v_tile = v_ref[0].astype(jnp.float32)
        q_all = q_ref[0].astype(jnp.float32)    # [hp, hdp]
        bp = k_tile.shape[0]
        tile_pos = jax.lax.broadcasted_iota(jnp.int32, (1, bp), 1)
        live = ((tile_pos < block_size)
                & (j * block_size + tile_pos < length))  # [1, Bp]
        for hh in range(num_heads):
            qh = q_all[hh:hh + 1, :]                       # [1, hdp]
            kh = k_tile[:, hh * hdp:(hh + 1) * hdp]        # [Bp, hdp]
            vh = v_tile[:, hh * hdp:(hh + 1) * hdp]
            sc = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [1, Bp]
            sc = jnp.where(live, sc, _NEG_INF)
            m_prev = m_ref[hh:hh + 1, :1]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
            p = jnp.exp(sc - m_new)                          # [1, Bp]
            corr = jnp.exp(m_prev - m_new)                   # [1, 1]
            l_new = (l_ref[hh:hh + 1, :1] * corr
                     + jnp.sum(p, axis=1, keepdims=True))
            acc_ref[hh:hh + 1, :] = (acc_ref[hh:hh + 1, :] * corr
                                     + jnp.dot(
                                         p, vh,
                                         preferred_element_type=jnp.float32))
            m_ref[hh:hh + 1, :] = jnp.broadcast_to(m_new, (1, _LANE))
            l_ref[hh:hh + 1, :] = jnp.broadcast_to(l_new, (1, _LANE))

    @pl.when(j == np_ - 1)
    def _finalize():
        # idle slots (length 0) never accumulated: l == 0 -> output 0.
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _pad_axis(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    block_tables: jax.Array, lengths: jax.Array, *,
                    scale: float | None = None,
                    interpret: bool | None = None) -> jax.Array:
    """Single-query attention over a paged KV cache, one layer.

    ``q``: [slots, heads, head_dim] (the current token's query, AFTER
    its K/V were scattered into the cache — position ``length-1``
    attends to itself through the cache, exactly like the dense path).
    ``k_pages``/``v_pages``: [num_blocks, block_size, heads, head_dim]
    (one layer of :class:`servesvc.kv_cache.PagedKVCache`).
    ``block_tables``: [slots, max_blocks_per_seq] int32, dead entries
    ``NULL_BLOCK``. ``lengths``: [slots] int32 — position count
    INCLUDING the current token; 0 marks an idle slot (output zeros).

    Returns [slots, heads, head_dim] float32.
    """
    num_slots, num_heads, hd = q.shape
    num_blocks, block_size, h2, hd2 = k_pages.shape
    assert (h2, hd2) == (num_heads, hd), (q.shape, k_pages.shape)
    assert v_pages.shape == k_pages.shape
    assert block_tables.shape[0] == num_slots == lengths.shape[0]
    width = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # tile-align: lanes (head_dim -> 128) and sublanes (block rows -> 8,
    # head rows -> 8). No-ops for TPU-sized models; real copies for the
    # tiny CPU-test shapes, where only correctness matters.
    hdp = hd + ((-hd) % _LANE)
    hp = num_heads + ((-num_heads) % _SUBLANE)
    qp = _pad_axis(_pad_axis(q, 2, _LANE), 1, _SUBLANE)       # [S, hp, hdp]
    kp = _pad_axis(_pad_axis(k_pages, 3, _LANE), 1, _SUBLANE)
    vp = _pad_axis(_pad_axis(v_pages, 3, _LANE), 1, _SUBLANE)
    bp = kp.shape[1]
    # heads fold into the lane dim of the K/V tiles (contiguous ->
    # free reshape); per-head lane slices select them in-kernel
    kp = kp.reshape(num_blocks, bp, num_heads * hdp)
    vp = vp.reshape(num_blocks, bp, num_heads * hdp)

    kernel = functools.partial(
        _paged_kernel, scale=scale, num_heads=num_heads,
        block_size=block_size, hdp=hdp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(num_slots, width),
        in_specs=[
            pl.BlockSpec((1, hp, hdp), lambda s, j, t, l: (s, 0, 0)),
            # the fused gather: this tile load IS the table walk
            pl.BlockSpec((1, bp, num_heads * hdp),
                         lambda s, j, t, l: (t[s, j], 0, 0)),
            pl.BlockSpec((1, bp, num_heads * hdp),
                         lambda s, j, t, l: (t[s, j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hp, hdp), lambda s, j, t, l: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hp, _LANE), jnp.float32),  # running max
            pltpu.VMEM((hp, _LANE), jnp.float32),  # running denom
            pltpu.VMEM((hp, hdp), jnp.float32),    # output accumulator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_slots, hp, hdp), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      qp, kp, vp)
    return out[:, :num_heads, :hd]


def paged_attention_dense(q: jax.Array, k_pages: jax.Array,
                          v_pages: jax.Array, block_tables: jax.Array,
                          lengths: jax.Array, *,
                          scale: float | None = None) -> jax.Array:
    """The dense-gather oracle: same signature/semantics as
    :func:`paged_attention`, implemented with the full-table gather the
    decode path used before the kernel (and still uses under
    ``decode.attention_kernel = dense``). Parity tests pin the kernel
    against this for live slots; idle rows differ by design (see module
    docstring)."""
    num_slots, num_heads, hd = q.shape
    block_size = k_pages.shape[1]
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    ctx = block_tables.shape[1] * block_size
    kd = k_pages[block_tables].reshape(num_slots, ctx, num_heads, hd)
    vd = v_pages[block_tables].reshape(num_slots, ctx, num_heads, hd)
    live = jnp.arange(ctx)[None, :] < lengths[:, None]
    scores = jnp.einsum("shd,skhd->shk", q.astype(jnp.float32),
                        kd.astype(jnp.float32)) * scale
    scores = jnp.where(live[:, None, :], scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("shk,skhd->shd", w, vd.astype(jnp.float32))
