from .drop_connect import drop_connect_grads
from .masked_psum import masked_mean_psum
from .ring_attention import local_self_attention, ring_self_attention

__all__ = [
    "drop_connect_grads", "masked_mean_psum",
    "local_self_attention", "ring_self_attention",
]
