"""Masked-mean cross-replica reduction — the framework's core op.

Replaces the reference's entire parameter-server aggregation stack:
PS-hosted ``ConditionalAccumulator``s that average the first k
gradients and drop stale ones
(sync_replicas_optimizer_modified.py:287-306,363-378), per-worker token
queues (:199-206), and the chief's sync loop (:389-410).

TPU-native form: every replica contributes ``(grad · flag, flag)`` to a
single ``lax.psum`` over the mesh's replica axis; the aggregated
gradient is ``psum(grad·flag) / max(psum(flag), 1)``. Masked-out
replicas (backups, stragglers past deadline, outside the interval
window) contribute zeros — semantically identical to the PS dropping
their gradients, but with no queues, no staleness window, and the
reduction compiler-scheduled onto ICI all-reduce.

Staleness (SURVEY §7 "hard parts") is structurally impossible here:
SPMD replicas are in lockstep, so a masked-out step-t gradient simply
never enters any accumulator that step t+1 could read.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


def contribution_scale(flag: jax.Array,
                       axis_name: str) -> tuple[jax.Array, jax.Array]:
    """(scale, num_contributors): pre-multiplying each replica's
    contribution by ``scale = flag / max(psum(flag), 1)`` makes any
    subsequent cross-replica SUM the masked mean directly — one
    elementwise pass, shared by the all-reduce path below and the
    ZeRO-1 reduce-scatter path (parallel/api.py), so the two
    disciplines cannot drift in masking semantics."""
    flag = flag.astype(jnp.float32)
    num = lax.psum(flag, axis_name)
    return flag / jnp.maximum(num, 1.0), num


def masked_mean_psum(tree: Any, flag: jax.Array, axis_name: str) -> tuple[Any, jax.Array]:
    """Cross-replica masked mean of a pytree.

    Args:
      tree: per-replica pytree (e.g. gradients), inside shard_map.
      flag: scalar 0/1 (or fractional weight) — this replica's
        contribution mask.
      axis_name: mesh axis to reduce over.

    Returns:
      (mean_tree, num_contributors): the masked mean — identical on all
      replicas — and ``psum(flag)``. If no replica contributes, the mean
      is all-zeros (the update becomes a no-op, mirroring a PS step with
      an empty accumulator never firing).
    """
    # One elementwise pass per leaf: pre-scale by the SCALAR flag/denom
    # so psum produces the mean directly (scaling after the psum would
    # spend a second full-size HBM pass per leaf — measured as a real
    # throughput tax on small step times by bench_mode_overhead).
    scale, num = contribution_scale(flag, axis_name)
    mean = jax.tree.map(
        lambda g: lax.psum(g * scale.astype(g.dtype), axis_name), tree)
    return mean, num


